"""Async device prefetcher: overlap host batch production and the
host->device transfer with step compute.

No direct reference counterpart (the reference's ``PrefetchingIter``
overlaps host iterators only; device upload stayed synchronous inside
the training step). TPU-native design: a background thread pulls
batches from ANY source iterable (``gluon.data.DataLoader``, a legacy
``io.DataIter``, a generator), converts them to device-committed
arrays — ``jax.device_put`` onto one device, or sharded across a
data-parallel mesh via ``parallel.spmd.shard_batch`` — and stages them
in a bounded queue ``MXTPU_DEVICE_PREFETCH`` batches ahead (default 2:
double buffering). The consumer's ``next()`` then returns an
already-resident batch, so the accelerator never idles on batchify or
PCIe/ICI while the previous step runs.

Wired in automatically: ``DataLoader(..., device=mx.tpu())``, the
estimator ``fit`` loop and ``Module.fit`` (both prefetch to the model's
context unless ``MXTPU_DEVICE_PREFETCH=0``).

Error contract: an exception raised by the source (or the transfer)
propagates to the consumer's ``next()`` — never a silent hang — and
``close()`` is idempotent and joins the thread (also via ``__del__``).
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as _np

from ... import observability as _obs
from ...base import getenv
from ...context import Context
from ...ndarray.ndarray import NDArray

_DEPTH_DEFAULT = 2


def prefetch_depth() -> int:
    """Queue depth (batches staged ahead) from ``MXTPU_DEVICE_PREFETCH``
    (default 2 = double buffering; 0 disables auto-wrapping)."""
    return max(0, int(getenv("MXTPU_DEVICE_PREFETCH", _DEPTH_DEFAULT,
                             dtype=int)))


def _leaf_nbytes(raw) -> int:
    try:
        return int(raw.size * raw.dtype.itemsize)
    except Exception:
        return 0


class DevicePrefetcher:
    """Wrap a batch source; stage converted batches N ahead on device.

    >>> loader = DataLoader(dataset, batch_size=64, last_batch="pad")
    >>> for x, y in DevicePrefetcher(loader, device=mx.tpu()):
    ...     train_step(x, y)   # x, y already resident on device

    ``device``: a Context (or None to keep batches on host — the
    conversion/batchify work still overlaps). ``mesh``: shard each
    batch's leading axis across the mesh's ``batch_axis`` instead
    (multi-device SPMD feeding). Batch structure (tuple/list/dict/
    ``DataBatch``) is preserved leaf-wise.
    """

    #: machine-checked lock protocol (mxtpu-lint thread-guard): epoch
    #: lifecycle state swaps only under the lifecycle lock — close()
    #: racing _start_epoch() (consumer restart vs GC __del__, or an
    #: elastic repartition) otherwise orphans a producer thread blocked
    #: on a queue nobody drains
    _GUARDED_BY = {"_thread": "_lifecycle_lock",
                   "_queue": "_lifecycle_lock",
                   "_stop": "_lifecycle_lock"}

    def __init__(self, source, device=None, mesh=None, depth=None,
                 batch_axis="dp"):
        if device is not None and mesh is not None:
            raise ValueError("pass device OR mesh, not both")
        self._lifecycle_lock = threading.Lock()
        self._source = source
        self._device = device
        self._mesh = mesh
        self._batch_axis = batch_axis
        self._depth = max(1, depth if depth is not None
                          else (prefetch_depth() or _DEPTH_DEFAULT))
        self._queue = None
        self._thread = None
        self._stop = threading.Event()
        self._exhausted = False
        self._delivered = 0  # batches handed to the consumer this epoch
        self._source_steps0 = 0  # stream-source step count at epoch start:
        # a structured cursor reports steps0 + delivered, so batches the
        # producer staged ahead but never handed over are NOT marked
        # consumed (they re-read on resume — zero skip)
        self._placement_gen = 0  # bumped by repartition(): staged-ahead
        # batches carry the generation they were placed under, and a
        # stale one is re-staged onto the CURRENT mesh at delivery

    # -- conversion -------------------------------------------------------
    def _jax_device(self):
        if isinstance(self._device, Context):
            return self._device.jax_device
        return self._device  # already a jax.Device (or None)

    def _convert_leaf(self, obj, nbytes_box):
        if isinstance(obj, (list, tuple)):
            return type(obj)(self._convert_leaf(o, nbytes_box) for o in obj)
        if isinstance(obj, dict):
            return {k: self._convert_leaf(v, nbytes_box)
                    for k, v in obj.items()}
        if obj.__class__.__name__ == "DataBatch" and hasattr(obj, "data"):
            from ...io.io import DataBatch

            return DataBatch(
                data=self._convert_leaf(obj.data, nbytes_box),
                label=self._convert_leaf(obj.label, nbytes_box),
                pad=obj.pad, index=obj.index, bucket_key=obj.bucket_key,
                provide_data=obj.provide_data,
                provide_label=obj.provide_label)
        if isinstance(obj, NDArray):
            raw = obj.data
        elif isinstance(obj, _np.ndarray):
            raw = obj
        else:
            return obj  # scalars / strings ride through untouched
        nbytes_box[0] += _leaf_nbytes(raw)
        if self._mesh is not None:
            from ...parallel.spmd import shard_batch

            placed = shard_batch(raw, self._mesh, self._batch_axis)
            return NDArray(placed,
                           ctx=obj.ctx if isinstance(obj, NDArray) else None)
        import jax

        dev = self._jax_device()
        placed = jax.device_put(raw, dev) if dev is not None \
            else (raw if isinstance(raw, jax.Array)
                  else jax.numpy.asarray(raw))
        ctx = self._device if isinstance(self._device, Context) else \
            (obj.ctx if isinstance(obj, NDArray) else None)
        return NDArray(placed, ctx=ctx)

    def _stage(self, batch):
        nbytes_box = [0]
        t0 = time.perf_counter()
        out = self._convert_leaf(batch, nbytes_box)
        from ...resilience import chaos as _chaos

        if _chaos.ENABLED and _chaos.nan_due("prefetch"):
            # injected bad batch (MXTPU_CHAOS=nan@prefetch:N): float
            # leaves of the Nth staged batch become NaN — the
            # regression hook for loss-scale skip / data validation
            out = _chaos.poison_struct(out)
        if _obs.ENABLED:
            _obs.record_h2d(nbytes_box[0], time.perf_counter() - t0,
                            self._queue.qsize())
        return out

    # -- producer ---------------------------------------------------------
    def _produce(self, q, stop):
        def put(item):
            # bounded put that aborts promptly on close(): never leaves
            # the thread blocked on a full queue nobody will drain
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            for batch in self._source:
                if stop.is_set():
                    return
                # generation read BEFORE staging: if a repartition()
                # lands mid-stage the payload may be mixed across
                # meshes, but it carries the OLD generation and is
                # re-staged wholly at delivery
                gen = self._placement_gen
                if not put(("ok", (gen, self._stage(batch)))):
                    return
            put(("end", None))
        except BaseException as e:  # propagate to the consumer's next()
            put(("err", e))

    def _start_epoch(self):
        self.close()
        if self._exhausted and hasattr(self._source, "reset"):
            self._source.reset()
        self._exhausted = False
        self._delivered = 0
        state_fn = getattr(self._source, "state", None)
        if callable(state_fn):
            self._source_steps0 = int(state_fn().get("steps", 0))
        with self._lifecycle_lock:
            self._stop = threading.Event()
            self._queue = queue.Queue(maxsize=self._depth)
            self._thread = threading.Thread(
                target=self._produce, args=(self._queue, self._stop),
                name="mxtpu-device-prefetch", daemon=True)
            self._thread.start()

    # -- consumer protocol ------------------------------------------------
    def __iter__(self):
        # iterator protocol: iter() on an IN-FLIGHT epoch returns self
        # untouched (list(it)/enumerate(it) re-invoke iter and must not
        # restart — close() would silently drop the staged batches); a
        # fresh or exhausted wrapper starts the next epoch
        if self._thread is None or self._exhausted:
            self._start_epoch()
        return self

    def __next__(self):
        if self._exhausted:
            # stay exhausted until iter()/reset(), like any iterator —
            # auto-restarting here would hand duplicated batches to a
            # consumer draining past the epoch end
            raise StopIteration
        if self._thread is None:
            self._start_epoch()
        t0 = time.perf_counter()
        kind, payload = self._queue.get()
        if _obs.ENABLED:
            wait = time.perf_counter() - t0
            _obs.DATA_PREFETCH_WAIT_SECONDS.inc(wait)
            _obs.DATA_PREFETCH_QUEUE_DEPTH.set(self._queue.qsize())
            if _obs.attribution.ENABLED:
                # spike evidence for the attribution plane: the longest
                # SINGLE wait inside the step period (a dict compare +
                # rare store — the running total above stays the source
                # of the per-step input_wait delta)
                _obs.attribution.note_input_wait(wait)
        if kind == "ok":
            gen, batch = payload
            if gen != self._placement_gen:
                # staged ahead of a repartition(): re-stage leaf-wise
                # onto the CURRENT mesh/device — the batch is consumed
                # exactly once, just on the new extent
                batch = self._convert_leaf(batch, [0])
            self._delivered += 1
            return batch
        self._exhausted = True
        self.close()
        if kind == "err":
            raise payload
        raise StopIteration

    def next(self):
        return self.__next__()

    def repartition(self, mesh=None, device=None, batch_axis=None,
                    world=None, rank=None):
        """Re-partition the pipeline across a NEW device extent WITHOUT
        losing position (the elastic-resize hook): the deterministic
        ``cursor`` is untouched, batches already staged ahead on the
        old mesh are re-staged onto the new one at delivery, and
        everything staged from here on lands on the new extent
        directly — a dp change never skips or replays data.

        ``world``/``rank`` additionally re-partition a streaming
        SOURCE (one exposing ``repartition(world, rank, steps=)``, e.g.
        :class:`~.stream.StreamReader`) across a new rank extent: the
        in-flight epoch stops, the source's global cursor rebases to
        the last DELIVERED batch (staged-ahead batches were never
        marked consumed, so they re-read under the new partitioning —
        zero skip, zero replay), and the next ``next()`` resumes
        there."""
        if mesh is not None and device is not None:
            raise ValueError("pass device OR mesh, not both")
        if batch_axis is not None:
            self._batch_axis = batch_axis
        if mesh is not None:
            self._mesh, self._device = mesh, None
        elif device is not None:
            self._device, self._mesh = device, None
        self._placement_gen += 1
        if world is not None or rank is not None:
            rp = getattr(self._source, "repartition", None)
            if not callable(rp):
                raise ValueError(
                    "repartition(world=, rank=): source "
                    f"{type(self._source).__name__} has no repartition() "
                    "— only streaming sources re-shard their cursor")
            steps = self._source_steps0 + self._delivered
            self.close()  # join producer before rewinding its source
            rp(world=world, rank=rank, steps=steps)
            self._exhausted = False
            self._delivered = 0
            self._source_steps0 = 0  # rebased: steps reset with base
        return self

    @property
    def cursor(self):
        """The input-pipeline position a checkpoint records. A
        streaming source (one exposing ``state()``) yields its
        structured global cursor, adjusted to batches DELIVERED to the
        consumer (staged-ahead work is not consumed); otherwise the
        plain delivered-batch count this epoch, for
        ``resilience.resume.skip_batches``."""
        state_fn = getattr(self._source, "state", None)
        if callable(state_fn):
            if self._thread is None and not self._exhausted:
                return state_fn()  # no epoch in flight: source is truth
            return state_fn(steps=self._source_steps0 + self._delivered)
        return self._delivered

    def __len__(self):
        return len(self._source)

    def __getattr__(self, name):
        # transparent wrapper: provide_data / provide_label / batch_size /
        # ... fall through to the source (DataIter protocol consumers)
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.__dict__["_source"], name)

    def reset(self):
        """DataIter-protocol reset: stop the in-flight epoch, reset the
        source (when it supports it), arm a fresh epoch."""
        self.close()
        if hasattr(self._source, "reset"):
            self._source.reset()
        self._exhausted = False

    def close(self):
        """Idempotent shutdown: unblock and join the producer thread.
        The thread/queue swap out under the lifecycle lock; the drain
        and JOIN run outside it (holding a lock across a join is the
        deadlock shape the lock-order rule exists for)."""
        if "_lifecycle_lock" not in self.__dict__:
            return  # partially-constructed instance (GC during __init__)
        with self._lifecycle_lock:
            thread, q, stop = self._thread, self._queue, self._stop
            self._thread = None
            self._queue = None
        if thread is None:
            return
        stop.set()
        while True:  # drain so a producer blocked on put() wakes up
            try:
                q.get_nowait()
            except queue.Empty:
                break
        thread.join(timeout=5.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _stack_leaves(batches):
    """Leaf-wise device stack of structurally identical batches into
    ``[K, ...]`` arrays (tuple/list/dict/NDArray structure preserved).
    The stack runs on device over already-staged arrays — one fused
    concat per leaf, counted as a ``superstep_stage`` dispatch."""
    import jax.numpy as jnp

    first = batches[0]
    if isinstance(first, tuple) and hasattr(first, "_fields"):  # namedtuple
        return type(first)(*(_stack_leaves([b[i] for b in batches])
                             for i in range(len(first))))
    if isinstance(first, (list, tuple)):
        return type(first)(_stack_leaves([b[i] for b in batches])
                           for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _stack_leaves([b[k] for b in batches]) for k in first}
    if first.__class__.__name__ == "DataBatch" and hasattr(first, "data"):
        from ...io.io import DataBatch

        return DataBatch(
            data=_stack_leaves([b.data for b in batches]),
            label=_stack_leaves([b.label for b in batches]),
            pad=first.pad, index=first.index, bucket_key=first.bucket_key,
            provide_data=first.provide_data,
            provide_label=first.provide_label)
    if isinstance(first, NDArray):
        raws = [b.data for b in batches]
        if _obs.ENABLED:
            _obs.record_xla_dispatch("superstep_stage")
        return NDArray(jnp.stack(raws), ctx=first.ctx)
    if hasattr(first, "shape"):
        if _obs.ENABLED:
            _obs.record_xla_dispatch("superstep_stage")
        return jnp.stack([jnp.asarray(b) for b in batches])
    if isinstance(first, (int, float, str, bool, type(None))):
        return first  # scalar metadata: assumed slot-invariant
    raise TypeError(f"cannot stack batch leaf of type {type(first)!r}")


def stack_batches(batches):
    """Stack a list of structurally identical batches into one batch
    whose every array leaf gains a leading ``[K]`` slot axis — the
    operand block one K-step superstep dispatch consumes. Raises
    ``ValueError`` on shape/structure mismatch (unpadded final batches:
    stabilize with ``DataLoader(last_batch="pad")`` / bucketing first)."""
    if not batches:
        raise ValueError("stack_batches: empty batch list")
    try:
        return _stack_leaves(batches)
    except Exception as e:
        raise ValueError(
            f"stack_batches: batches are not shape/structure stable "
            f"({e}); pad partial batches and bucket variable-length "
            f"inputs (docs/performance.md 'input pipeline')") from e


class SuperstepRing:
    """K-deep device staging ring feeding a training superstep.

    Wraps any batch source in a :class:`DevicePrefetcher` whose queue is
    at least ``k`` deep, so the producer thread stages (device_put / mesh
    ``shard_batch``) the NEXT superstep's K slots while the previous
    superstep executes on device. Iterating yields ``(batch, k_actual)``
    groups: ``k_actual == k`` means ``batch`` is the stacked ``[K, ...]``
    operand block; a final short group (source exhausted mid-ring) is
    yielded as the raw LIST of staged batches with ``k_actual < k`` so
    the consumer can single-step the tail.

    Error/close contract is the prefetcher's: a source/transfer exception
    propagates from ``next()`` (after any full groups already staged),
    and ``close()`` is idempotent and joins the producer thread.

    >>> ring = SuperstepRing(loader, k=8, device=mx.tpu())
    >>> for group, n in ring:
    ...     if n == ring.k:
    ...         sstep.step(*group, batch_size)   # one dispatch, 8 steps
    """

    def __init__(self, source, k, device=None, mesh=None, depth=None):
        self.k = max(1, int(k))
        if isinstance(source, DevicePrefetcher):
            if device is not None or mesh is not None or depth is not None:
                # silently dropping these would leave batches on the
                # wrong device / the queue too shallow with no signal
                raise ValueError(
                    "SuperstepRing: device/mesh/depth apply only when "
                    "the ring builds its own prefetcher — configure "
                    "them on the DevicePrefetcher you passed in")
            if source._depth < self.k:
                import logging

                logging.getLogger(__name__).warning(
                    "SuperstepRing: wrapped DevicePrefetcher depth %d "
                    "< k=%d — the next superstep's slots cannot all "
                    "stage while the current one runs (lost overlap); "
                    "build the prefetcher with depth >= k",
                    source._depth, self.k)
            self._pf = source
            self._own = False
        else:
            # queue depth covers one full superstep plus the configured
            # lookahead, so staging the next K slots overlaps execution
            d = depth if depth is not None \
                else self.k + (prefetch_depth() or _DEPTH_DEFAULT)
            self._pf = DevicePrefetcher(source, device=device, mesh=mesh,
                                        depth=d)
            self._own = True
        self._err = None

    def __iter__(self):
        iter(self._pf)
        return self

    def __next__(self):
        if self._err is not None:
            # a source/transfer error interrupted the previous group:
            # its staged batches were delivered, now the error surfaces
            err, self._err = self._err, None
            raise err
        group = []
        for _ in range(self.k):
            try:
                group.append(next(self._pf))
            except StopIteration:
                break
            except Exception as e:
                # producer/transfer errors: deliver already-staged work
                # first, re-raise on the NEXT group so no staged batch
                # is silently dropped. KeyboardInterrupt/SystemExit are
                # NOT deferred — an interrupt must not train a tail
                # group first.
                if not group:
                    raise
                self._err = e
                break
        if not group:
            raise StopIteration
        if self._err is not None or len(group) < self.k:
            return group, len(group)  # short tail: consumer single-steps
        return stack_batches(group), self.k

    @property
    def cursor(self):
        """Batches delivered through the ring this epoch (stacked
        groups count their K slots) — recorded by the checkpoint
        manager as the data-pipeline position."""
        return self._pf.cursor

    def repartition(self, mesh=None, device=None, batch_axis=None,
                    world=None, rank=None):
        """Delegate to the underlying prefetcher (elastic resize: the
        cursor is preserved; staged batches re-stage onto the new
        extent at delivery; ``world``/``rank`` re-shard a streaming
        source's global cursor)."""
        self._pf.repartition(mesh=mesh, device=device,
                             batch_axis=batch_axis, world=world,
                             rank=rank)
        return self

    def reset(self):
        self._err = None
        self._pf.reset()

    def close(self):
        if self._own:
            self._pf.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def wrap_for_fit(source, ctx=None, depth=None):
    """Auto-wrap a fit-loop's train data in a DevicePrefetcher (the
    estimator / ``Module.fit`` integration seam). Returns ``source``
    unchanged when prefetch is disabled (``MXTPU_DEVICE_PREFETCH=0``)
    or already wrapped."""
    d = depth if depth is not None else prefetch_depth()
    if d <= 0 or isinstance(source, DevicePrefetcher):
        return source
    if getattr(source, "_device", None) is not None \
            or getattr(source, "_mesh", None) is not None:
        # e.g. DataLoader(device=...): it already prefetches to device —
        # stacking a second wrapper would stage (and count in telemetry)
        # every batch twice
        return source
    device = ctx if isinstance(ctx, Context) else None
    return DevicePrefetcher(source, device=device, depth=d)
