"""Cluster-scale streaming data plane (ROADMAP item 4, the unfinished
half of the elasticity work).

The compute fast paths (fused step, superstep, overlapped ZeRO, 4D
parallelism) all assume input arrives at line rate — but until now
input was whatever the user's Python iterator yielded, with its cursor
hidden inside iterator state. This module is the half MXNet solved in
C++ (SURVEY Data IO: ``ImageRecordIter2``/``PrefetcherIter`` threaded
decode/augment/prefetch off the Python thread), rebuilt TPU-native:

- :class:`ShardIndex` — one RecordIO pack or webdataset-style tar
  shard with an O(1) per-record byte index (native
  ``MXTPURecordIOScanIndex`` fast scan when ``libmxtpu.so`` is
  available, pure-Python scan / ``.idx`` sidecar / tar-member walk
  otherwise).
- :class:`GlobalOrder` — the deterministic epoch-scale sample order:
  shard-level shuffle composed with block **window shuffle**, both
  derived purely from ``(seed, epoch)`` so ANY position in the
  permuted sequence is computable in O(1) without materializing an
  epoch-sized permutation (datasets that don't fit in memory shuffle
  at window granularity; ``window=0`` keeps shard order).
- :class:`StreamReader` — the sharded, resumable, line-rate reader:
  a read-ahead thread streams raw records from (possibly slow,
  latency-emulated) storage under bounded backpressure, a
  multi-threaded decode pool turns them into samples off the train
  thread, and a sequence-numbered reorder stage re-emits batches in
  the exact deterministic global order. Feed it to
  :class:`~.prefetcher.DevicePrefetcher` / ``SuperstepRing`` for the
  device-staging leg; host work is decode only — augmentation belongs
  on device via :func:`device_augment` (crop/flip/normalize inside
  the compiled step).
- **Deterministic global cursor** — ``state()`` is a plain dict
  ``(seed, base_batch, steps, world, rank, batch_size, ...)`` from
  which every future sample is derivable; it checkpoints through the
  PR-8 manager (``CheckpointManager`` accepts structured cursors) and
  re-partitions across ranks on a PR-11 elastic resize
  (:meth:`StreamReader.repartition`) without skipping or replaying a
  single sample.

Partitioning contract: the global sample sequence is chunked into
batches of ``batch_size``; at partition step ``t`` rank ``r`` of
``world`` consumes global batch ``base + t*world + r``. A resize at a
step boundary (all ranks at equal ``t``) rebases
``base += t * world`` and continues under the new ``(world', rank')``
— the union of all ranks' batches remains exactly the uninterrupted
global sequence. See docs/performance.md "Streaming input".
"""

from __future__ import annotations

import ctypes
import io
import os
import random
import struct
import tarfile
import threading
import time

import numpy as _np

from ... import observability as _obs
from ..._native import get_lib
from ...base import MXNetError, getenv
from ...recordio import _LEN_MASK, _MAGIC, IRHeader, unpack

__all__ = [
    "ShardIndex", "ShardSet", "GlobalOrder", "StreamReader",
    "device_augment", "write_recordio_shards", "decode_threads",
    "readahead_records", "emulated_latency_ms", "shuffle_window",
]

CURSOR_VERSION = 1


# -- knobs (docs/env_vars.md, machine-enforced) ---------------------------

def decode_threads() -> int:
    """``MXTPU_STREAM_DECODE_THREADS`` (default 4): decode/augment pool
    width. Decode never runs on the train thread regardless; this is
    how many records decode concurrently."""
    return max(1, int(getenv("MXTPU_STREAM_DECODE_THREADS", 4,
                             dtype=int)))


def readahead_records() -> int:
    """``MXTPU_STREAM_READAHEAD`` (default 128): bounded read-ahead in
    RECORDS — the raw-bytes staging queue and the decoded reorder
    buffer are each capped at this depth (backpressure against slow
    consumers; read-ahead against slow storage)."""
    return max(2, int(getenv("MXTPU_STREAM_READAHEAD", 128, dtype=int)))


def emulated_latency_ms() -> float:
    """``MXTPU_STREAM_LATENCY_MS`` (default 0): emulated slow-storage
    latency added to every shard read op — the bench/chaos knob that
    turns local files into 'remote object storage' so prefetch-ahead
    and backpressure are measurable without a network."""
    return max(0.0, float(getenv("MXTPU_STREAM_LATENCY_MS", 0.0,
                                 dtype=float)))


def shuffle_window() -> int:
    """``MXTPU_STREAM_WINDOW`` (default 0 = shard order): default
    window-shuffle size in records when ``StreamReader(window=None)``.
    Epoch-scale datasets shuffle at this granularity without an
    epoch-sized permutation in memory."""
    return max(0, int(getenv("MXTPU_STREAM_WINDOW", 0, dtype=int)))


# -- shard index ----------------------------------------------------------

def _python_scan_recordio(path):
    """Pure-Python offset scan (the no-native fallback): hop over
    payloads header-by-header."""
    offsets = []
    with open(path, "rb") as f:
        while True:
            pos = f.tell()
            hdr = f.read(8)
            if not hdr:
                break
            if len(hdr) < 8:
                raise MXNetError(f"{path}: truncated RecordIO header")
            magic, lrec = struct.unpack("<II", hdr)
            if magic != _MAGIC:
                raise MXNetError(
                    f"{path}: invalid RecordIO magic {magic:#x}")
            length = lrec & _LEN_MASK
            f.seek(length + ((4 - (length % 4)) % 4), io.SEEK_CUR)
            offsets.append(pos)
    return _np.asarray(offsets, dtype=_np.uint64)


def _native_scan_recordio(path):
    """Native index scan: one call to size, one to fill (both are pure
    fseeko hops in C — ~100x the Python scan on large packs)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "MXTPURecordIOScanIndex"):
        return None
    n = lib.MXTPURecordIOScanIndex(path.encode(), None, 0)
    if n < 0:
        raise MXNetError(
            f"{path}: {lib.MXTPUGetLastError().decode()}")
    offsets = _np.zeros(int(n), dtype=_np.uint64)
    if n:
        buf = offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
        n2 = lib.MXTPURecordIOScanIndex(path.encode(), buf, int(n))
        if n2 != n:
            raise MXNetError(f"{path}: index scan changed size "
                             f"({n} -> {n2}) — file being written?")
    return offsets


class ShardIndex:
    """One shard with an O(1) per-record byte index.

    Two layouts:

    - ``kind="recordio"``: a RecordIO pack (magic ``0xced7230a``);
      the index is the byte offset of every record header, built by
      the native scan, loaded from a ``.idx`` sidecar, or scanned in
      Python. ``read(i)`` returns the raw record payload bytes.
    - ``kind="webdataset"``: a webdataset-style tar shard; members are
      grouped by basename stem into samples, the index stores each
      member's ``(data_offset, size)`` from one tar walk. ``read(i)``
      returns ``{extension: bytes}`` for sample ``i``.

    Reads are thread-safe (per-thread file handles) and charge the
    emulated-storage latency + byte/rate telemetry per read op.
    """

    def __init__(self, path, kind, index, name=None):
        self.path = str(path)
        self.kind = kind
        self._index = index
        self.name = name or os.path.basename(self.path)
        self._tls = threading.local()

    def __len__(self):
        return len(self._index)

    def __repr__(self):
        return (f"ShardIndex({self.name!r}, kind={self.kind!r}, "
                f"records={len(self)})")

    # -- constructors ---------------------------------------------------
    @classmethod
    def recordio(cls, path, idx_path=None):
        """Index a RecordIO pack. ``idx_path`` (or ``<path>.idx`` /
        the im2rec ``<base>.idx`` sidecar, when present) is preferred;
        otherwise the native scan, then the Python scan."""
        for cand in ([idx_path] if idx_path else
                     [str(path) + ".idx",
                      os.path.splitext(str(path))[0] + ".idx"]):
            if cand and os.path.exists(cand):
                offsets = []
                with open(cand) as f:
                    for line in f:
                        parts = line.split()
                        if len(parts) >= 2:
                            offsets.append(int(parts[1]))
                return cls(path, "recordio",
                           _np.asarray(sorted(offsets), dtype=_np.uint64))
        offsets = _native_scan_recordio(str(path))
        if offsets is None:
            offsets = _python_scan_recordio(str(path))
        return cls(path, "recordio", offsets)

    @classmethod
    def webdataset(cls, path):
        """Index a webdataset-style tar shard: one tar walk records
        every member's data offset/size; members sharing a basename
        stem (up to the first dot) form one sample."""
        samples = {}  # stem -> [(ext, offset, size)]
        order = []
        with tarfile.open(path, "r:") as tf:
            for m in tf:
                if not m.isfile():
                    continue
                base = os.path.basename(m.name)
                stem, _, ext = base.partition(".")
                if stem not in samples:
                    samples[stem] = []
                    order.append(stem)
                samples[stem].append((ext, m.offset_data, m.size))
        index = [tuple(samples[s]) for s in order]
        return cls(path, "webdataset", index)

    # -- reads ----------------------------------------------------------
    def _fp(self):
        fp = getattr(self._tls, "fp", None)
        if fp is None or getattr(self._tls, "pid", None) != os.getpid():
            fp = open(self.path, "rb")
            self._tls.fp = fp
            self._tls.pid = os.getpid()
        return fp

    def _native_handle(self):
        """Per-thread native RecordIO handle (the read-at data pointer
        is only valid until the handle's next read, so handles cannot
        be shared across threads)."""
        if self.kind != "recordio":
            return None
        h = getattr(self._tls, "nh", None)
        if h is not None and getattr(self._tls, "nh_pid", None) == os.getpid():
            return h
        lib = get_lib()
        if lib is None or not hasattr(lib, "MXTPURecordIOReadAt"):
            self._tls.nh = None
            return None
        handle = ctypes.c_void_p()
        if lib.MXTPURecordIOOpen(self.path.encode(), 0,
                                 ctypes.byref(handle)) != 0:
            self._tls.nh = None
            return None
        self._tls.nh = handle
        self._tls.nh_pid = os.getpid()
        return handle

    def _charge(self, nbytes, dt):
        if _obs.ENABLED:
            _obs.record_stream_read(self.name, nbytes, dt)

    def read(self, i):
        """Record ``i``: payload ``bytes`` (recordio) or
        ``{ext: bytes}`` (webdataset). O(1): one seek+read per
        member; native ``MXTPURecordIOReadAt`` when libmxtpu is
        loaded, Python seek+read otherwise."""
        lat = emulated_latency_ms()
        t0 = time.perf_counter()
        if self.kind == "recordio":
            if lat:
                time.sleep(lat / 1e3)
            nh = self._native_handle()
            if nh is not None:
                lib = get_lib()
                data = ctypes.POINTER(ctypes.c_uint8)()
                # the index is a host numpy array — the cast is a
                # scalar read, not a device sync
                off = int(self._index[i])  # mxtpu-lint: host-sync-ok
                n = lib.MXTPURecordIOReadAt(nh, off, ctypes.byref(data))
                if n < 0:
                    raise MXNetError(
                        f"{self.path}[{i}]: "
                        f"{lib.MXTPUGetLastError().decode()}")
                out = ctypes.string_at(data, n)
            else:
                fp = self._fp()
                fp.seek(int(self._index[i]))  # mxtpu-lint: host-sync-ok
                hdr = fp.read(8)
                magic, lrec = struct.unpack("<II", hdr)
                if magic != _MAGIC:
                    raise MXNetError(
                        f"{self.path}[{i}]: invalid magic {magic:#x} "
                        f"(stale index?)")
                out = fp.read(lrec & _LEN_MASK)
            self._charge(len(out) + 8, time.perf_counter() - t0)
            return out
        sample = {}
        fp = self._fp()
        for ext, off, size in self._index[i]:
            if lat:
                time.sleep(lat / 1e3)  # one op per member, like object
                # storage range requests
            fp.seek(off)
            sample[ext] = fp.read(size)
        self._charge(sum(len(v) for v in sample.values()),
                     time.perf_counter() - t0)
        return sample

    def close(self):
        fp = getattr(self._tls, "fp", None)
        if fp is not None:
            try:
                fp.close()
            except OSError:
                pass
            self._tls.fp = None
        nh = getattr(self._tls, "nh", None)
        if nh is not None:
            lib = get_lib()
            if lib is not None:
                lib.MXTPURecordIOClose(nh)
            self._tls.nh = None


def _open_shard(spec):
    """Coerce one shard spec (ShardIndex | path) to a ShardIndex; tar
    suffixes open as webdataset, everything else as RecordIO."""
    if isinstance(spec, ShardIndex):
        return spec
    p = str(spec)
    if p.endswith((".tar", ".tgz", ".tar.gz")):
        if p.endswith(("gz",)):
            raise MXNetError(
                f"{p}: compressed tar shards have no O(1) member "
                f"access — repack uncompressed (webdataset convention)")
        return ShardIndex.webdataset(p)
    return ShardIndex.recordio(p)


class ShardSet:
    """An ordered shard collection with global-record prefix sums: maps
    a linear record id (under a given shard permutation) to
    ``(shard, record)`` in O(log S)."""

    def __init__(self, shards):
        self.shards = [_open_shard(s) for s in shards]
        if not self.shards:
            raise MXNetError("ShardSet: no shards")
        self.sizes = _np.asarray([len(s) for s in self.shards],
                                 dtype=_np.int64)
        self.total = int(self.sizes.sum())
        if self.total == 0:
            raise MXNetError("ShardSet: shards contain no records")

    def __len__(self):
        return self.total

    def close(self):
        for s in self.shards:
            s.close()


# -- deterministic epoch order -------------------------------------------

def _rng(*key):
    """A process-independent deterministic RNG: string seeding goes
    through sha512, not PYTHONHASHSEED."""
    return random.Random(":".join(str(k) for k in key))


class GlobalOrder:
    """The deterministic order of one epoch: shard permutation composed
    with block window shuffle, all derived from ``(seed, epoch)``.

    ``locate(epoch, i)`` -> ``(shard_id, record_id)`` for within-epoch
    position ``i`` in O(1) amortized: the shard permutation + prefix
    sums are cached per epoch, window permutations (``window``-sized)
    are generated on demand and memoized for the handful of windows a
    sequential consumer straddles — never an epoch-sized array."""

    def __init__(self, shardset, seed=0, window=0, shuffle_shards=True):
        self.shardset = shardset
        self.seed = int(seed)
        self.window = int(window)
        self.shuffle_shards = bool(shuffle_shards)
        self._epoch = None
        self._perm = None     # shard permutation for _epoch
        self._cum = None      # prefix sums under that permutation
        self._windows = {}    # (epoch, w) -> list perm (tiny LRU)

    def _epoch_tables(self, epoch):
        if self._epoch != epoch:
            perm = list(range(len(self.shardset.shards)))
            if self.shuffle_shards:
                _rng(self.seed, epoch, "shards").shuffle(perm)
            sizes = self.shardset.sizes[perm]
            self._perm = perm
            self._cum = _np.concatenate(
                ([0], _np.cumsum(sizes))).astype(_np.int64)
            self._epoch = epoch
            self._windows.clear()
        return self._perm, self._cum

    def _window_perm(self, epoch, w):
        key = (epoch, w)
        cached = self._windows.get(key)
        if cached is None:
            n = self.shardset.total
            lo = w * self.window
            size = min(self.window, n - lo)
            cached = list(range(size))
            _rng(self.seed, epoch, "win", w).shuffle(cached)
            self._windows[key] = cached
            while len(self._windows) > 8:  # sequential consumers
                self._windows.pop(next(iter(self._windows)))
        return cached

    def locate(self, epoch, i):
        """Within-epoch position ``i`` -> ``(shard_id, record_id)``."""
        perm, cum = self._epoch_tables(epoch)
        if self.window:
            w = i // self.window
            i = w * self.window + self._window_perm(epoch, w)[i % self.window]
        s = int(_np.searchsorted(cum, i, side="right")) - 1
        return perm[s], int(i - cum[s])


# -- default decode/collate ----------------------------------------------

def decode_recordio_f32(payload):
    """Default RecordIO decode: ``recordio.unpack`` the IRHeader, view
    the body as float32 — the synthetic-tensor shard format
    ``write_recordio_shards`` emits. Returns ``(data, label)``."""
    header, body = unpack(payload)
    return (_np.frombuffer(body, dtype=_np.float32).copy(),
            _np.asarray(header.label, dtype=_np.float32))


def _collate(samples):
    """Stack structurally identical samples leaf-wise into batch
    arrays (tuple/dict structure preserved)."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(_collate([s[i] for s in samples])
                           for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _collate([s[k] for s in samples]) for k in first}
    if isinstance(first, _np.ndarray):
        return _np.stack(samples)
    return list(samples)


# -- the reader -----------------------------------------------------------

_SENTINEL = object()


class StreamReader:
    """Sharded, resumable, line-rate streaming reader.

    >>> rd = StreamReader(["train-000.rec", "train-001.rec"],
    ...                   batch_size=64, seed=0, window=4096)
    >>> pf = DevicePrefetcher(rd, mesh=mesh)   # device staging leg
    >>> state = rd.state()                     # checkpointable cursor
    >>> rd.repartition(world=2, rank=0)        # elastic resize, no
    ...                                        # skip, no replay

    Threads: one read-ahead thread streams raw records (bounded by
    ``readahead``), a ``pool``-wide decode pool turns them into
    samples, a reorder stage re-emits them in exact global order.
    ``epochs=None`` streams forever (epoch = reshuffle boundary);
    ``epochs=k`` stops after k full passes (drop-tail to whole
    batches). An exception in any stage propagates from ``next()``.
    """

    #: machine-checked lock protocol (mxtpu-lint thread-guard): the
    #: reorder buffer and error slot are shared between the decode
    #: pool, the reader thread, and the consumer — mutating them
    #: off-lock re-creates the PR-8 flush() race shape (a batch
    #: observed missing between a worker's pop and its put)
    _GUARDED_BY = {"_reorder": "_cv", "_error": "_cv",
                   "_eof_seq": "_cv", "_live_workers": "_cv"}

    def __init__(self, shards, batch_size, seed=0, world=1, rank=0,
                 window=None, shuffle_shards=True, decode=None,
                 collate=None, pool=None, readahead=None, epochs=None):
        self.shardset = shards if isinstance(shards, ShardSet) \
            else ShardSet(shards)
        self.batch_size = int(batch_size)
        if self.batch_size <= 0:
            raise MXNetError("StreamReader: batch_size must be >= 1")
        self.seed = int(seed)
        self._window = shuffle_window() if window is None else int(window)
        self.order = GlobalOrder(self.shardset, seed=self.seed,
                                 window=self._window,
                                 shuffle_shards=shuffle_shards)
        self._decode = decode if decode is not None \
            else decode_recordio_f32
        self._collate = collate if collate is not None else _collate
        self._pool_size = pool if pool is not None else decode_threads()
        self._readahead = readahead if readahead is not None \
            else readahead_records()
        self.epochs = epochs
        # -- cursor (the deterministic global position) ----------------
        self._world = int(world)
        self._rank = int(rank)
        if not (0 <= self._rank < self._world):
            raise MXNetError(
                f"StreamReader: rank {self._rank} outside world "
                f"{self._world}")
        self._base = 0    # global batch index all ranks rebased from
        self._steps = 0   # batches THIS partition delivered since base
        # -- pipeline state --------------------------------------------
        self._cv = threading.Condition()
        self._reorder = {}      # seq -> decoded sample
        self._error = None
        self._eof_seq = None    # first seq the reader did NOT produce
        self._live_workers = 0
        self._raw_q = None
        self._threads = []
        self._stop = threading.Event()
        self._next_seq = 0      # consumer's next expected sample seq

    # -- cursor arithmetic ----------------------------------------------
    def _global_batch(self, step):
        return self._base + step * self._world + self._rank

    def _sample_limit(self):
        """First global sample index past the end (None = infinite)."""
        if self.epochs is None:
            return None
        return int(self.epochs) * self.shardset.total

    def locate_sample(self, g):
        """Global sample index -> (epoch, shard_id, record_id)."""
        n = self.shardset.total
        e = g // n
        shard, rec = self.order.locate(e, g % n)
        return e, shard, rec

    def state(self, steps=None):
        """The deterministic global cursor: a plain JSON-serializable
        dict from which every future sample is derivable. ``steps``
        overrides the delivered-batch count (the DevicePrefetcher
        passes its DELIVERED count so staged-ahead batches are not
        marked consumed)."""
        return {
            "version": CURSOR_VERSION,
            "kind": "stream",
            "seed": self.seed,
            "batch_size": self.batch_size,
            "world": self._world,
            "rank": self._rank,
            "base_batch": self._base,
            "steps": int(self._steps if steps is None else steps),
            "window": self._window,
            "records": self.shardset.total,
        }

    def restore(self, state):
        """Resume from a :meth:`state` cursor — bit-exact continuation:
        the next batch yielded is exactly the one that would have
        followed the checkpoint."""
        if not isinstance(state, dict) or state.get("kind") != "stream":
            raise MXNetError(f"StreamReader.restore: not a stream "
                             f"cursor: {state!r}")
        if int(state.get("version", -1)) > CURSOR_VERSION:
            raise MXNetError(
                f"StreamReader.restore: cursor version "
                f"{state['version']} is newer than this reader "
                f"({CURSOR_VERSION})")
        if int(state["records"]) != self.shardset.total:
            raise MXNetError(
                f"StreamReader.restore: cursor was cut for "
                f"{state['records']} records, shards now hold "
                f"{self.shardset.total} — the global order would "
                f"silently diverge")
        if int(state["batch_size"]) != self.batch_size or \
                int(state["seed"]) != self.seed or \
                int(state["window"]) != self._window:
            raise MXNetError(
                "StreamReader.restore: batch_size/seed/window differ "
                "from the cursor's — the global order would diverge")
        self._drain()
        self._world = int(state["world"])
        self._rank = int(state["rank"])
        self._base = int(state["base_batch"])
        self._steps = int(state["steps"])
        return self

    def repartition(self, world, rank, steps=None):
        """Re-partition the stream across a NEW rank extent at a step
        boundary (the PR-11 elastic-resize hook). The collective
        contract: every surviving rank calls this with the same
        ``steps`` (defaults to its own delivered count — equal across
        ranks at a boundary), so the global position rebases to
        ``base + steps*old_world`` and the union of the new ranks'
        batches continues the global sequence with zero skipped and
        zero replayed samples."""
        world, rank = int(world), int(rank)
        if not (0 <= rank < world):
            raise MXNetError(
                f"StreamReader.repartition: rank {rank} outside "
                f"world {world}")
        self._drain()
        t = self._steps if steps is None else int(steps)
        self._base = self._base + t * self._world
        self._steps = 0
        self._world = world
        self._rank = rank
        if _obs.ENABLED:
            _obs.STREAM_REPARTITIONS_TOTAL.inc()
        return self

    @property
    def cursor(self):
        """Structured cursor property (DevicePrefetcher/checkpoint
        integration point)."""
        return self.state()

    # -- producer side ---------------------------------------------------
    def _positions(self):
        """Yield ``(seq, global_sample_index)`` for every sample this
        partition will consume, starting at the current cursor."""
        limit = self._sample_limit()
        seq = self._next_seq
        step = self._steps
        while True:
            g = self._global_batch(step)
            lo = g * self.batch_size
            if limit is not None and lo + self.batch_size > limit:
                return  # drop-tail: only whole batches
            for j in range(self.batch_size):
                yield seq, lo + j
                seq += 1
            step += 1

    def _read_loop(self, raw_q, stop):
        """Read-ahead thread: stream raw records for the upcoming
        sample positions, in order, under queue backpressure."""
        last_seq = None
        try:
            for seq, g in self._positions():
                if stop.is_set():
                    return
                _e, shard_id, rec = self.locate_sample(g)
                shard = self.shardset.shards[shard_id]
                raw = shard.read(rec)
                while not stop.is_set():
                    try:
                        raw_q.put((seq, g, raw), timeout=0.05)
                        last_seq = seq
                        break
                    except Exception:  # queue.Full
                        continue
                else:
                    return
                if _obs.ENABLED:
                    _obs.STREAM_QUEUE_DEPTH.set(raw_q.qsize(),
                                                queue="raw")
        except BaseException as e:
            with self._cv:
                if self._error is None:
                    self._error = e
                self._cv.notify_all()
        finally:
            for _ in range(self._pool_size):  # one sentinel per worker
                while not stop.is_set():
                    try:
                        raw_q.put(_SENTINEL, timeout=0.05)
                        break
                    except Exception:
                        continue
            with self._cv:
                if self._error is None:
                    self._eof_seq = (last_seq + 1) if last_seq is not None \
                        else self._next_seq
                self._cv.notify_all()

    def _decode_loop(self, raw_q, stop):
        """Decode-pool worker: raw record -> sample, emitted into the
        reorder buffer under bounded decoded-ahead backpressure."""
        import queue as _queue

        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                item = raw_q.get(timeout=0.05)
            except _queue.Empty:
                if _obs.ENABLED:
                    _obs.STREAM_DECODE_WAIT_SECONDS.inc(
                        time.perf_counter() - t0)
                continue
            if item is _SENTINEL:
                break
            if _obs.ENABLED:
                _obs.STREAM_DECODE_WAIT_SECONDS.inc(
                    time.perf_counter() - t0)
            seq, g, raw = item
            try:
                t1 = time.perf_counter()
                sample = self._decode(raw)
                dt = time.perf_counter() - t1
                with self._cv:
                    while (not stop.is_set()
                           and self._error is None
                           and len(self._reorder) >= self._readahead
                           and seq >= self._next_seq + self._readahead):
                        self._cv.wait(0.05)
                    if stop.is_set():
                        return
                    self._reorder[seq] = sample
                    self._cv.notify_all()
                if _obs.ENABLED:
                    _obs.record_stream_decode(dt)
            except BaseException as e:
                with self._cv:
                    if self._error is None:
                        self._error = e
                    self._cv.notify_all()
                return

    # -- lifecycle --------------------------------------------------------
    def _start(self):
        import queue as _queue

        self._stop = threading.Event()
        self._raw_q = _queue.Queue(maxsize=self._readahead)
        with self._cv:
            self._reorder = {}
            self._error = None
            self._eof_seq = None
            self._live_workers = self._pool_size
        self._next_seq = 0
        self._threads = [threading.Thread(
            target=self._read_loop, args=(self._raw_q, self._stop),
            name="mxtpu-stream-read", daemon=True)]
        for i in range(self._pool_size):
            self._threads.append(threading.Thread(
                target=self._decode_loop,
                args=(self._raw_q, self._stop),
                name=f"mxtpu-stream-decode-{i}", daemon=True))
        for t in self._threads:
            t.start()

    def _drain(self):
        """Stop the pipeline, discarding staged-but-undelivered work
        (the cursor marks only DELIVERED batches, so nothing staged is
        lost — it is re-read on restart)."""
        self._stop.set()
        q = self._raw_q
        if q is not None:
            while True:
                try:
                    q.get_nowait()
                except Exception:
                    break
        with self._cv:
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        self._raw_q = None
        with self._cv:
            self._reorder = {}
            self._error = None
            self._eof_seq = None

    def close(self):
        """Idempotent shutdown: join the reader + pool threads and
        close per-thread shard handles."""
        self._drain()
        self.shardset.close()

    def reset(self):
        """DataIter-protocol reset: restart this partition from the
        beginning of the stream."""
        self._drain()
        self._base = 0
        self._steps = 0

    def __del__(self):
        try:
            self._drain()
        except Exception:
            pass

    # -- consumer ---------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if not self._threads:
            self._start()
        t0 = time.perf_counter()
        samples = []
        with self._cv:
            stop = self._stop
            for _ in range(self.batch_size):
                want = self._next_seq
                while (want not in self._reorder
                       and self._error is None
                       and not stop.is_set()
                       and (self._eof_seq is None
                            or want < self._eof_seq)):
                    self._cv.wait(0.1)
                if self._error is not None:
                    err = self._error  # kept set: later next() re-raises
                    self._drain_locked_exit()
                    raise err
                if stop.is_set():
                    # drained under us (repartition/close from another
                    # thread): surface end-of-epoch, never a hang
                    raise StopIteration
                if want in self._reorder:
                    samples.append(self._reorder.pop(want))
                    self._next_seq = want + 1
                    self._cv.notify_all()
                    continue
                # EOF before a full batch: drop-tail contract
                break
        wait = time.perf_counter() - t0
        if len(samples) < self.batch_size:
            raise StopIteration
        self._steps += 1
        batch = self._collate(samples)
        if _obs.ENABLED:
            _obs.record_stream_batch(wait, len(self._reorder))
            if _obs.attribution.ENABLED:
                _obs.attribution.note_input_wait(wait)
        return batch

    def next(self):
        return self.__next__()

    def _drain_locked_exit(self):
        # called with self._cv held, on the error path only: stop
        # producers so the failed epoch does not keep decoding behind
        # a consumer that already raised
        self._stop.set()
        self._cv.notify_all()


# -- shard authoring (tests/bench) ---------------------------------------

def write_recordio_shards(directory, samples, shard_size,
                          prefix="shard"):
    """Write ``(data: np.float32 array, label: float)`` samples into
    RecordIO shards of ``shard_size`` records each + ``.idx`` sidecars.
    Returns the shard paths (the ``im2rec``-compatible pack layout the
    streaming reader consumes)."""
    from ...recordio import MXIndexedRecordIO, pack

    os.makedirs(directory, exist_ok=True)
    paths = []
    writer = None
    for i, (data, label) in enumerate(samples):
        if i % shard_size == 0:
            if writer is not None:
                writer.close()
            p = os.path.join(directory,
                             f"{prefix}-{len(paths):05d}.rec")
            writer = MXIndexedRecordIO(p + ".idx", p, "w")
            paths.append(p)
        payload = pack(IRHeader(0, float(label), i, 0),
                       _np.ascontiguousarray(data, _np.float32).tobytes())
        writer.write_idx(i % shard_size, payload)
    if writer is not None:
        writer.close()
    return paths


# -- on-device augmentation ----------------------------------------------

def device_augment(crop=None, flip=False, mean=None, std=None):
    """Build a jit-composable on-device augmentation: random crop /
    horizontal flip / normalize, executed INSIDE the compiled step (the
    host does image decode only — SURVEY Data IO's C++ augment stage
    moves onto the accelerator where it is free under XLA fusion).

    Returns ``fn(images, key) -> images`` for NHWC batches: ``crop``
    is the target ``(h, w)`` (random offsets per image, derived from
    ``jax.random.fold_in(key, i)`` so augmentation is deterministic in
    the global RNG key), ``flip`` mirrors each image with p=0.5,
    ``mean``/``std`` normalize per channel. All shapes are static —
    safe under ``jit``/``scan``/donation.
    """
    import jax
    import jax.numpy as jnp

    mean_a = None if mean is None else jnp.asarray(mean, jnp.float32)
    std_a = None if std is None else jnp.asarray(std, jnp.float32)

    def one(img, key):
        if crop is not None:
            ch, cw = crop
            kh, kw, key = jax.random.split(key, 3)
            oy = jax.random.randint(kh, (), 0, img.shape[0] - ch + 1)
            ox = jax.random.randint(kw, (), 0, img.shape[1] - cw + 1)
            img = jax.lax.dynamic_slice(
                img, (oy, ox, 0), (ch, cw, img.shape[2]))
        if flip:
            kf, key = jax.random.split(key)
            img = jnp.where(jax.random.bernoulli(kf),
                            img[:, ::-1, :], img)
        img = img.astype(jnp.float32)
        if mean_a is not None:
            img = img - mean_a
        if std_a is not None:
            img = img / std_a
        return img

    def augment(images, key):
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
            jnp.arange(images.shape[0]))
        return jax.vmap(one)(images, keys)

    return augment
