"""Batch-shape stabilization: pad partial batches, bucket variable
lengths.

Reference analog: ``io.py`` ``last_batch_handle="pad"`` (NDArrayIter)
and GluonNLP's ``FixedBucketSampler`` — unified here because on XLA a
shape wobble is not a correctness detail but a COMPILE event: every
distinct input signature retraces the CachedOp forward/backward and the
fused train step (SURVEY.md flags shape churn as the #1 TPU perf
pathology). The guard keeps the shape set small and known:

- :func:`pad_batch` pads a partial final batch up to ``batch_size`` and
  returns the validity mask, so metrics/losses can exclude the pad rows
  exactly (parity with ``last_batch="discard"`` on the valid rows);
- :class:`SequenceBucketer` pads variable-length sequences to a small
  fixed set of lengths, bounding the executable count at
  ``len(buckets)``;
- the per-block retrace budget (``MXTPU_RETRACE_BUDGET``, enforced in
  ``gluon/block.py``) flags ``shape_wobble`` loudly when the shape set
  grows past what padding/bucketing should allow.
"""

from __future__ import annotations

import numpy as _np

from ...base import MXNetError, check_shape
from ...ndarray.ndarray import NDArray


def _pad_leaf(arr, batch_size):
    """Pad ``arr``'s leading axis to ``batch_size`` by repeating its
    first row (finite values — safe under any loss once masked)."""
    n = arr.shape[0]
    if n == batch_size:
        return arr
    if n > batch_size:
        raise MXNetError(
            f"pad_batch: batch of {n} rows exceeds batch_size {batch_size}")
    if n == 0:
        raise MXNetError("pad_batch: cannot pad an empty batch")
    reps = (batch_size - n,) + (1,) * (arr.ndim - 1)
    if isinstance(arr, NDArray):
        import jax.numpy as jnp

        return NDArray(jnp.concatenate(
            [arr.data, jnp.tile(arr.data[:1], reps)]), ctx=arr.ctx)
    return _np.concatenate([arr, _np.tile(arr[:1], reps)])


def pad_batch(batch, batch_size):
    """Pad every array in ``batch`` (leading axis) to ``batch_size``.

    Returns ``(padded, mask)`` where ``mask`` is a float32 ``NDArray``
    of shape ``(batch_size,)`` with 1.0 on original rows and 0.0 on pad
    rows. Feed the mask as the loss ``sample_weight`` (and divide by
    ``mask.sum()`` instead of the batch size) and the padded batch
    produces the same gradients and metrics as discarding the tail —
    while keeping every step the SAME shape, so nothing retraces.

    ``batch``: an array, or a (possibly nested) list/tuple of arrays
    (the DataLoader ``[data, label]`` convention). Structure is
    preserved.
    """
    first = batch
    while isinstance(first, (list, tuple)):
        first = first[0]
    n = first.shape[0]

    def walk(obj):
        if isinstance(obj, (list, tuple)):
            return type(obj)(walk(o) for o in obj)
        if obj.shape[0] != n:
            raise MXNetError(
                f"pad_batch: leading axes disagree ({obj.shape[0]} vs {n})")
        return _pad_leaf(obj, batch_size)

    padded = walk(batch)
    mask = _np.zeros((batch_size,), _np.float32)
    mask[:n] = 1.0
    return padded, NDArray(mask)


def pad_to_shape(arr, shape, pad_value=0):
    """Pad ``arr`` (trailing-edge, any number of axes) up to ``shape``.

    The general-rank sibling of :class:`SequenceBucketer`: the serving
    batcher uses it to lift each request's rows onto its shape bucket
    before stacking, so ragged traffic reaches the engine in at most
    ``len(buckets)`` shapes. Rank mismatches and dimensions LARGER than
    the target raise (implicit truncation would silently change the
    math, same contract as ``bucket_for``).
    """
    raw = arr.data if isinstance(arr, NDArray) else _np.asarray(arr)
    shape = tuple(int(s) for s in shape)
    if raw.ndim != len(shape):
        raise MXNetError(
            f"pad_to_shape: rank {raw.ndim} input cannot pad to {shape}")
    if any(d > t for d, t in zip(raw.shape, shape)):
        raise MXNetError(
            f"pad_to_shape: input shape {tuple(raw.shape)} exceeds target "
            f"{shape}; add a bucket (truncation is never implicit)")
    if tuple(raw.shape) == shape:
        return arr
    pad_width = [(0, t - d) for d, t in zip(raw.shape, shape)]
    if isinstance(arr, NDArray):
        import jax.numpy as jnp

        return NDArray(jnp.pad(arr.data, pad_width,
                               constant_values=pad_value), ctx=arr.ctx)
    return _np.pad(raw, pad_width, constant_values=pad_value)


class SequenceBucketer:
    """Pad variable-length sequences to a fixed set of bucket lengths.

    >>> bucketer = SequenceBucketer([32, 64, 128])
    >>> x_padded, valid_len = bucketer(x)   # x: (batch, T<=128, ...)

    Every emitted array has one of ``len(buckets)`` shapes, so a
    hybridized block (or the fused train step) compiles AT MOST
    ``len(buckets)`` executables — the retrace-count regression test in
    ``tests/test_fused_step.py`` pins exactly that. Sequences longer
    than the largest bucket raise (truncation would silently change the
    math; pick buckets to cover the corpus).

    ``axis``: the sequence axis (default 1, the ``(batch, T)`` layout);
    ``pad_value``: fill for the padded tail (default 0, the usual
    ``<pad>`` token id / zero embedding row).
    """

    def __init__(self, buckets, axis=1, pad_value=0):
        lens = sorted({int(b) for b in check_shape(buckets)})
        if not lens or lens[0] <= 0:
            raise MXNetError(f"invalid bucket lengths {buckets!r}")
        self.buckets = tuple(lens)
        self.axis = axis
        self.pad_value = pad_value

    def bucket_for(self, length: int) -> int:
        """Smallest bucket >= ``length``."""
        for b in self.buckets:
            if length <= b:
                return b
        raise MXNetError(
            f"sequence length {length} exceeds the largest bucket "
            f"{self.buckets[-1]}; add a bucket (truncation is never "
            "implicit)")

    def __call__(self, arr):
        """Pad ``arr`` along ``axis`` to its bucket length.

        Returns ``(padded, valid_length)`` — ``valid_length`` is the
        original length (host int), for masks / ``SequenceMask``.
        """
        raw = arr.data if isinstance(arr, NDArray) else arr
        length = int(raw.shape[self.axis])
        target = self.bucket_for(length)
        if target == length:
            return arr, length
        pad_width = [(0, 0)] * raw.ndim
        pad_width[self.axis] = (0, target - length)
        if isinstance(arr, NDArray):
            import jax.numpy as jnp

            out = NDArray(jnp.pad(arr.data, pad_width,
                                  constant_values=self.pad_value),
                          ctx=arr.ctx)
        else:
            out = _np.pad(_np.asarray(raw), pad_width,
                          constant_values=self.pad_value)
        return out, length
