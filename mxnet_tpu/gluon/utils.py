"""Gluon utilities (reference: ``python/mxnet/gluon/utils.py``)."""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import Context
from ..ndarray.ndarray import NDArray, array as _array


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along ``batch_axis`` into ``num_slice`` pieces (reference:
    ``gluon.utils.split_data``)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}"
        )
    step = size // num_slice
    if not even_split and size % num_slice != 0:
        slices = [
            data.slice_axis(axis=batch_axis, begin=i * step,
                            end=(i + 1) * step if i < num_slice - 1 else size)
            for i in range(num_slice)
        ]
    else:
        slices = [
            data.slice_axis(axis=batch_axis, begin=i * step, end=(i + 1) * step)
            for i in range(num_slice)
        ]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split and move one shard to each ctx (reference: ``split_and_load``;
    this is the P1 data-parallel sharding entry, SURVEY.md §2.5)."""
    if not isinstance(data, NDArray):
        data = _array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so their joint L2 norm <= max_norm."""
    import jax.numpy as jnp

    total = sum(float(jnp.sum(jnp.square(a.data))) for a in arrays)
    total_norm = total ** 0.5
    if check_isfinite and not _np.isfinite(total_norm):
        import warnings

        warnings.warn("nan or inf found in clip_global_norm")
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._set_data(a.data * scale)
    return total_norm


def check_sha1(filename, sha1_hash):
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Download a file (reference: ``gluon.utils.download``). This
    environment is zero-egress; raises with a clear message if attempted."""
    raise MXNetError(
        f"download({url}) is unavailable: no network egress. Place files "
        "locally and pass a local path instead."
    )


def shape_is_known(shape):
    if shape is None:
        return False
    return all(s is not None and s > 0 for s in shape)
