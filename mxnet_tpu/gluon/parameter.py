"""Gluon Parameter / ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py`` (symbols ``Parameter``,
``ParameterDict``, ``defer_init``). Same deferred-init and multi-device
replication semantics; buffers are NDArray handles that stay *stable* across
updates (the tape and Trainer key off handle identity).
"""

from __future__ import annotations

import numpy as _np

import jax.numpy as jnp

from .. import initializer
from ..base import MXNetError
from ..context import Context, current_context, cpu
from ..ndarray.ndarray import NDArray


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape is known."""


def _unknown_dim(s):
    """Unknown-dim sentinel depends on shape semantics (reference:
    ``mx.util.is_np_shape``): legacy uses 0, numpy semantics use -1 (and 0
    is a real empty dimension)."""
    from ..util import is_np_shape

    if s is None:
        return True
    return s == -1 if is_np_shape() else s <= 0


def _shape_known(shape):
    return shape is not None and not any(_unknown_dim(s) for s in shape)


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self.grad_req = grad_req if differentiable else "null"
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None  # {Context: NDArray}
        self._grad = None
        self._deferred_init = None  # (init, ctx_list, default_init)
        self._ctx_list = None

    def __repr__(self):
        return f"Parameter {self.name} (shape={self._shape}, dtype={self.dtype})"

    # -- shape ------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        if len(self._shape) != len(new_shape):
            raise MXNetError(
                f"Cannot change shape of {self.name} from {self._shape} "
                f"to {new_shape}")
        # allow filling unknown dims only (0 legacy / -1 np semantics)
        merged = []
        for s, u in zip(self._shape, new_shape):
            if _unknown_dim(s):
                merged.append(u)
            elif _unknown_dim(u) or s == u:
                merged.append(s)
            else:
                raise MXNetError(
                    f"Cannot change shape of {self.name} from {self._shape} to {new_shape}"
                )
        self._shape = tuple(merged)
        if self._deferred_init is not None and _shape_known(self._shape):
            self._finish_deferred_init()

    # -- init -------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        default_init = default_init or initializer.Uniform()
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        ctx = [Context(c) for c in ctx]
        if not _shape_known(self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                f"Cannot initialize Parameter {self.name} because it has "
                f"invalid shape {self._shape} and allow_deferred_init=False"
            )
        self._init_impl(init, ctx, default_init)

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        init, ctx, default_init = self._deferred_init
        self._deferred_init = None
        self._init_impl(init, ctx, default_init)

    def _init_impl(self, init, ctx_list, default_init):
        self._ctx_list = list(ctx_list)
        # run the initializer once on a host buffer, replicate to all ctx
        host = NDArray(jnp.zeros(self._shape, jnp.dtype(self.dtype)), ctx=cpu())
        used_init = self.init if self.init is not None else (init or default_init)
        if used_init is not None:
            if isinstance(used_init, str):
                used_init = initializer.create(used_init)
            used_init(initializer.InitDesc(self.name), host)
        self._data = {}
        self._grad = {}
        for c in self._ctx_list:
            arr = host.copyto(c)
            self._data[c] = arr
            if self.grad_req != "null":
                arr.attach_grad(self.grad_req)
                self._grad[c] = arr.grad

    def _load_init(self, data, ctx=None, cast_dtype=False, dtype_source="current"):
        """Load from a saved NDArray (reference: ``Parameter._load_init``)."""
        if self._shape is not None and _shape_known(self._shape):
            if tuple(data.shape) != tuple(self._shape):
                raise MXNetError(
                    f"Failed loading Parameter {self.name}: shape mismatch "
                    f"saved {data.shape} vs expected {self._shape}"
                )
        else:
            self._shape = tuple(data.shape)
        if cast_dtype and dtype_source == "current":
            data = data.astype(self.dtype)
        else:
            self.dtype = str(data.dtype)
        if ctx is None:
            ctx = self._ctx_list or [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._deferred_init = None
        if self._data is None:
            self._ctx_list = list(ctx)
            self._data = {}
            self._grad = {}
            for c in self._ctx_list:
                arr = data.copyto(c)
                self._data[c] = arr
                if self.grad_req != "null":
                    arr.attach_grad(self.grad_req)
                    self._grad[c] = arr.grad
        else:
            for c, arr in self._data.items():
                arr._set_data(data.data.astype(arr.dtype))

    # -- access -----------------------------------------------------------
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has not been initialized yet because "
                    "initialization was deferred. Actual initialization happens "
                    "during the first forward pass."
                )
            raise MXNetError(
                f"Parameter {self.name} has not been initialized. You should "
                "initialize parameters and create a Trainer first."
            )

    def _resolve_ctx(self, ctx):
        if ctx is None:
            if len(self._data) == 1:
                return next(iter(self._data))
            ctx = current_context()
        ctx = Context(ctx)
        if ctx not in self._data:
            raise MXNetError(
                f"Parameter {self.name} was not initialized on context {ctx}; "
                f"it is on {list(self._data)}"
            )
        return ctx

    def data(self, ctx=None) -> NDArray:
        self._check_initialized(ctx)
        return self._data[self._resolve_ctx(ctx)]

    def list_data(self):
        self._check_initialized()
        return [self._data[c] for c in self._ctx_list]

    def grad(self, ctx=None) -> NDArray:
        self._check_initialized(ctx)
        if self.grad_req == "null":
            raise MXNetError(f"Parameter {self.name} has grad_req='null'")
        return self._data[self._resolve_ctx(ctx)].grad

    def list_grad(self):
        self._check_initialized()
        return [self._data[c].grad for c in self._ctx_list]

    def list_ctx(self):
        if self._data is None and self._deferred_init is not None:
            return self._deferred_init[1]
        self._check_initialized()
        return self._ctx_list

    def set_data(self, data):
        self.shape = tuple(data.shape)
        if self._data is None:
            if self._deferred_init is not None:
                self._finish_deferred_init()
            else:
                raise MXNetError(f"Parameter {self.name} not initialized")
        raw = data.data if isinstance(data, NDArray) else jnp.asarray(data)
        for arr in self._data.values():
            arr._set_data(raw.astype(arr.dtype))

    def zero_grad(self):
        if self._grad is None:
            return
        for g in self._data.values():
            if g.grad is not None:
                g.grad._set_data(jnp.zeros(g.shape, g.grad.data.dtype))

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        ctx = [Context(c) for c in ctx]
        if self._data is not None:
            data = next(iter(self._data.values()))
            self._data = None
            self._grad = None
            self._load_init(data, ctx)
        elif self._deferred_init is not None:
            init, _, default_init = self._deferred_init
            self._deferred_init = (init, ctx, default_init)

    def cast(self, dtype):
        self.dtype = dtype if isinstance(dtype, str) else _np.dtype(dtype).name
        if self._data is None:
            return
        for arr in self._data.values():
            arr._set_data(arr.data.astype(jnp.dtype(self.dtype)))
            if arr.grad is not None:
                arr.grad._set_data(arr.grad.data.astype(jnp.dtype(self.dtype)))

    def var(self):
        from ..symbol.symbol import var

        return var(self.name, shape=self._shape, dtype=self.dtype)


class Constant(Parameter):
    """Non-differentiable constant parameter (reference: ``gluon.Constant``)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = NDArray(jnp.asarray(value))
        self.value = value
        super().__init__(
            name, grad_req="null", shape=value.shape,
            dtype=str(value.dtype), init=_ConstantInit(value),
        )


class _ConstantInit(initializer.Initializer):
    def __init__(self, value):
        super().__init__()
        self.value = value

    def _init_weight(self, _, arr):
        arr._set_data(self.value.data)

    _init_default = _init_weight


class ParameterDict:
    """Prefix-scoped parameter dictionary (reference: ``ParameterDict``)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        s = "\n".join(repr(p) for p in self._params.values())
        return f"{self._prefix}(\n{s}\n)"

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name in self._params:
            param = self._params[name]
            for k, v in kwargs.items():
                if k == "shape" and param.shape is not None:
                    param.shape = v
                elif getattr(param, k, None) in (None,) and v is not None:
                    setattr(param, k, v)
            return param
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        param = Parameter(name, **kwargs)
        self._params[name] = param
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        if name in self._params:
            return self._params[name]
        if value is None:
            raise MXNetError(f"No constant named {name}")
        c = Constant(name, value)
        self._params[name] = c
        return c

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"Parameter name {k} conflicts")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        for p in self._params.values():
            p.initialize(init=None, ctx=ctx, default_init=init or initializer.Uniform(),
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import ndarray as nd

        arg_dict = {}
        for param in self._params.values():
            block = param.list_data()
            weight = block[0]
            name = param.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix="", cast_dtype=False,
             dtype_source="current"):
        from ..ndarray import ndarray as nd

        loaded = nd.load(filename)
        loaded = {restore_prefix + k.replace("arg:", "").replace("aux:", ""): v
                  for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in loaded:
                    raise MXNetError(f"Parameter {name} missing in file {filename}")
        for name, data in loaded.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError(f"Parameter {name} in file but not in dict")
                continue
            self._params[name]._load_init(data, ctx, cast_dtype=cast_dtype,
                                          dtype_source=dtype_source)
