"""Gluon losses (reference: ``python/mxnet/gluon/loss.py``)."""

from __future__ import annotations

from .block import HybridBlock


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return F.reshape_like(x, y)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{self.__class__.__name__}(batch_axis={self._batch_axis}, w={self._weight})"

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SoftmaxCrossEntropyLoss(Loss):
    """Reference: ``SoftmaxCrossEntropyLoss`` (a.k.a. SoftmaxCELoss)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if self._sparse_label and not self._from_logits:
            # lse - pick form: identical math to
            # -pick(log_softmax(pred)) but never materialises the
            # (batch, ..., V) log-probability tensor — at BERT's 30522
            # vocab that tensor costs ~2 ms/step of pure HBM traffic —
            # and the reduction accumulates in f32 regardless of pred's
            # dtype (bf16 logsumexp over 30k classes is sloppy)
            lse = F.logsumexp(F.cast(pred, dtype="float32"),
                              axis=self._axis, keepdims=True)
            picked = F.pick(pred, label, axis=self._axis, keepdims=True)
            loss = lse - F.cast(picked, dtype="float32")
        else:
            if not self._from_logits:
                pred = F.log_softmax(pred, axis=self._axis)
            if self._sparse_label:
                loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
            else:
                label = _reshape_like(F, label, pred)
                loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * (
                    F.Activation(-F.abs(pred), act_type="softrelu")
                    + F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label, pos_weight)
                         + F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class CTCLoss(Loss):
    """Connectionist temporal classification loss.

    Reference: ``gluon.loss.CTCLoss`` over ``src/operator/contrib/ctc_loss``.
    TPU-native: dynamic-programming forward over ``lax.scan`` (log-space),
    blank label configurable at first or last position.
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        import jax.numpy as jnp
        from ..ndarray.ndarray import NDArray

        if self._layout == "NTC":
            pred_ = jnp.swapaxes(pred.data, 0, 1)  # -> (T, N, C)
        else:
            pred_ = pred.data
        if self._label_layout == "TN":
            label_ = jnp.swapaxes(label.data, 0, 1)
        else:
            label_ = label.data
        pl = pred_lengths.data if pred_lengths is not None else None
        ll = label_lengths.data if label_lengths is not None else None
        from ..ops.dispatch import invoke

        loss = invoke("_ctc_loss", NDArray(pred_), NDArray(label_.astype("int32")),
                      None if pl is None else NDArray(pl),
                      None if ll is None else NDArray(ll))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed", **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = F.reshape(input1, shape=(-1, input1.shape[-1] if input1.ndim > 1 else 1))
        input2 = F.reshape(input2, shape=(-1, input2.shape[-1] if input2.ndim > 1 else 1))
        label = F.reshape(label, shape=(-1, 1))
        cos_sim = self._cosine_similarity(F, input1, input2)
        y_1 = label == 1
        y_minus_1 = label == -1
        loss = y_1 * (1 - cos_sim) + y_minus_1 * F.relu(cos_sim - self._margin)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)

    def _cosine_similarity(self, F, x, y, axis=-1):
        x_norm = F.norm(x, axis=axis).reshape((-1, 1))
        y_norm = F.norm(y, axis=axis).reshape((-1, 1))
        xy = F.sum(x * y, axis=axis).reshape((-1, 1))
        eps_arr = F.full((1, 1), 1e-12)
        return xy / F.broadcast_maximum(x_norm * y_norm, eps_arr)
