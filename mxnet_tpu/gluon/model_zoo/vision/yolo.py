"""YOLOv3 (mini): the one-stage anchor-grid detector in the zoo.

Reference anchor: GluonCV ``model_zoo/yolo/yolo3.py`` (``YOLOV3``,
``YOLOOutputV3``, ``YOLOV3TargetMerger``) — BASELINE config #2 names
YOLOv3 alongside Faster-RCNN; the core reference repo ships the ops,
GluonCV composes them.

TPU-native shape discipline: predictions stay on the static anchor grid
(B, cells*anchors, 5+C) at every scale; target assignment masks rather
than filters; NMS is the shared static `box_nms`.

Layout per scale s with A anchors and C classes:
  head output (B, A*(5+C), H, W) -> (B, H*W*A, 5+C)
  channels: [tx, ty, tw, th, objectness, class logits...]
  decode: cx = (sigmoid(tx) + col) / W, cy likewise; w = aw * exp(tw)
  (anchors normalized to image size, the standard YOLOv3 parameterization)
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...block import HybridBlock
from ...nn import Conv2D, HybridSequential, MaxPool2D


def _conv_block(channels, stride=1):
    blk = HybridSequential(prefix="")
    blk.add(Conv2D(channels, 3, strides=stride, padding=1,
                   activation="relu"))
    return blk


class YOLOv3(HybridBlock):
    """Two-scale mini YOLOv3. ``forward(x)`` returns a list of per-scale
    raw prediction grids [(B, N_s, 5+C)] plus the static per-scale cell
    metadata used by the decoder/loss."""

    def __init__(self, classes=3, base_channels=(16, 32, 64),
                 anchors=(((0.1, 0.15), (0.25, 0.3)),
                          ((0.4, 0.5), (0.7, 0.8))), **kwargs):
        super().__init__(**kwargs)
        self.classes = classes
        self.anchors = tuple(tuple(map(tuple, a)) for a in anchors)
        self.num_scales = len(self.anchors)
        self._stem_pools = len(base_channels)  # one MaxPool per stem stage
        with self.name_scope():
            self.stem = HybridSequential(prefix="stem_")
            for c in base_channels:
                self.stem.add(_conv_block(c))
                self.stem.add(MaxPool2D(2))
            self.stages = HybridSequential(prefix="stages_")
            self.heads = HybridSequential(prefix="heads_")
            for i, anch in enumerate(self.anchors):
                stage = HybridSequential(prefix=f"s{i}_")
                if i > 0:
                    stage.add(_conv_block(base_channels[-1], stride=2))
                else:
                    stage.add(HybridSequential(prefix=""))
                self.stages.add(stage)
                self.heads.add(Conv2D(len(anch) * (5 + classes), 1))

    def hybrid_forward(self, F, x):
        feat = self.stem(x)
        outs = []
        for stage, head in zip(self.stages, self.heads):
            feat = stage(feat)
            p = head(feat)                       # (B, A*(5+C), H, W)
            B, _, H, W = p.shape
            A = len(self.anchors[len(outs)])
            p = F.reshape(F.transpose(p, axes=(0, 2, 3, 1)),
                          (B, H * W * A, 5 + self.classes))
            outs.append(p)
        return outs

    # -- static grid metadata ---------------------------------------------
    def grids(self, img_size):
        """Per-scale (H, W, A, anchor_wh array) for an img_size input."""
        meta = []
        s = img_size
        for _ in range(self._stem_pools):
            s //= 2
        for i, anch in enumerate(self.anchors):
            if i > 0:
                s //= 2
            meta.append((s, s, len(anch),
                         np.asarray(anch, np.float32)))
        return meta


def decode_predictions(preds, grids):
    """Raw grids -> (B, N, 6+C-1...) decoded [cx, cy, w, h, obj, cls...]
    in normalized image coordinates (pure jnp; reference YOLOOutputV3)."""
    decoded = []
    for p, (H, W, A, anchor_wh) in zip(preds, grids):
        raw = p.data if hasattr(p, "data") else jnp.asarray(p)
        B = raw.shape[0]
        raw = raw.reshape(B, H, W, A, -1)
        col = jnp.arange(W).reshape(1, 1, W, 1)
        row = jnp.arange(H).reshape(1, H, 1, 1)
        cx = (jax_sigmoid(raw[..., 0]) + col) / W
        cy = (jax_sigmoid(raw[..., 1]) + row) / H
        aw = jnp.asarray(anchor_wh[:, 0]).reshape(1, 1, 1, A)
        ah = jnp.asarray(anchor_wh[:, 1]).reshape(1, 1, 1, A)
        w = aw * jnp.exp(jnp.clip(raw[..., 2], -8, 8))
        h = ah * jnp.exp(jnp.clip(raw[..., 3], -8, 8))
        obj = jax_sigmoid(raw[..., 4])
        cls = jax_sigmoid(raw[..., 5:])
        out = jnp.concatenate(
            [jnp.stack([cx, cy, w, h, obj], axis=-1), cls], axis=-1)
        decoded.append(out.reshape(B, H * W * A, -1))
    return jnp.concatenate(decoded, axis=1)


def jax_sigmoid(x):
    import jax

    return jax.nn.sigmoid(x)


def _bce_logits(ndop, x, t):
    """Stable BCE-with-logits on NDArrays (one definition for the
    objectness and class terms)."""
    return ndop.relu(x) - x * t + ndop.log(1.0 + ndop.exp(-ndop.abs(x)))


def yolo_detect(net, x, score_thresh=0.1, nms_thresh=0.45):
    """Full inference -> (B, N, 6) [cls, score, x1 y1 x2 y2] normalized,
    suppressed rows -1 (box_nms convention)."""
    from ....ndarray import op as ndop
    from ....ndarray.ndarray import NDArray

    preds = net(x)
    dec = decode_predictions(preds, net.grids(x.shape[2]))
    cx, cy, w, h, obj = (dec[..., 0], dec[..., 1], dec[..., 2], dec[..., 3],
                         dec[..., 4])
    cls_scores = dec[..., 5:] * obj[..., None]
    cls_id = jnp.argmax(cls_scores, axis=-1).astype(dec.dtype)
    score = jnp.max(cls_scores, axis=-1)
    boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                      axis=-1)
    rows = jnp.concatenate([cls_id[..., None], score[..., None], boxes],
                           axis=-1)
    return ndop.box_nms(NDArray(rows), overlap_thresh=nms_thresh,
                        valid_thresh=score_thresh, coord_start=2,
                        score_index=1, id_index=0, force_suppress=False)


class YOLOv3Loss:
    """YOLOv3 objective (reference: YOLOV3TargetMerger + YOLOV3Loss):
    per-gt best-anchor assignment; BCE on objectness — positives 1,
    negatives 0, except non-assigned cells whose DECODED prediction
    overlaps a gt above ``ignore_iou``, which are excluded from the
    objectness loss (the dynamic ignore of the reference); BCE class and
    L2 on the raw box parameterization at assigned cells."""

    def __init__(self, net, ignore_iou=0.5):
        self._net = net
        self._ignore = ignore_iou

    def _ignore_mask(self, preds, grids, gt_raw):
        """(B, N_s) per scale: 1 where the decoded prediction's IoU with
        ANY gt exceeds the threshold (computed on detached values)."""
        dec = decode_predictions([p.detach() for p in preds], grids)
        cx, cy, w, h = dec[..., 0], dec[..., 1], dec[..., 2], dec[..., 3]
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2, cy + h / 2], axis=-1)  # (B, N, 4)
        gt = jnp.asarray(gt_raw[..., 1:5])                    # (B, M, 4)
        x1 = jnp.maximum(boxes[:, :, None, 0], gt[:, None, :, 0])
        y1 = jnp.maximum(boxes[:, :, None, 1], gt[:, None, :, 1])
        x2 = jnp.minimum(boxes[:, :, None, 2], gt[:, None, :, 2])
        y2 = jnp.minimum(boxes[:, :, None, 3], gt[:, None, :, 3])
        inter = jnp.clip(x2 - x1, 0) * jnp.clip(y2 - y1, 0)
        area_p = (boxes[:, :, 2] - boxes[:, :, 0]) \
            * (boxes[:, :, 3] - boxes[:, :, 1])
        area_g = (gt[:, :, 2] - gt[:, :, 0]) * (gt[:, :, 3] - gt[:, :, 1])
        iou = inter / jnp.maximum(
            area_p[:, :, None] + area_g[:, None, :] - inter, 1e-9)
        best = jnp.max(iou, axis=-1)                          # (B, N)
        flat = (best > self._ignore).astype(jnp.float32)
        # split back per scale
        out = []
        ofs = 0
        for H, W, A, _ in grids:
            n = H * W * A
            out.append(flat[:, ofs:ofs + n])
            ofs += n
        return out

    def _targets(self, grids, gt, dtype):
        """gt (M, 5) [cls, x1, y1, x2, y2] normalized. Returns per-scale
        (obj_target, box_target(4), cls_target) flat arrays matched to
        the prediction layout."""
        per_scale = []
        # global best anchor over every (scale, anchor) pair per gt
        all_anchors = []
        for si, (H, W, A, wh) in enumerate(grids):
            for ai in range(A):
                all_anchors.append((si, ai, wh[ai]))
        for si, (H, W, A, wh) in enumerate(grids):
            obj = np.zeros((H, W, A), np.float32)
            boxt = np.zeros((H, W, A, 4), np.float32)
            clst = np.zeros((H, W, A), np.int32)
            for m in range(gt.shape[0]):
                cls, x1, y1, x2, y2 = gt[m]
                gw, gh = x2 - x1, y2 - y1
                if gw <= 0 or gh <= 0:
                    continue
                gcx, gcy = (x1 + x2) / 2, (y1 + y2) / 2
                # IoU of (gw, gh) against each anchor shape (origin-aligned)
                best, best_key = -1.0, None
                for (sj, aj, awh) in all_anchors:
                    iw = min(gw, awh[0])
                    ih = min(gh, awh[1])
                    inter = iw * ih
                    iou = inter / (gw * gh + awh[0] * awh[1] - inter)
                    if iou > best:
                        best, best_key = iou, (sj, aj)
                if best_key[0] != si:
                    continue
                aj = best_key[1]
                ci = min(int(gcx * W), W - 1)
                ri = min(int(gcy * H), H - 1)
                obj[ri, ci, aj] = 1.0
                boxt[ri, ci, aj] = [gcx * W - ci, gcy * H - ri,
                                    np.log(max(gw / wh[aj][0], 1e-9)),
                                    np.log(max(gh / wh[aj][1], 1e-9))]
                clst[ri, ci, aj] = int(cls)
            per_scale.append((obj.reshape(-1), boxt.reshape(-1, 4),
                              clst.reshape(-1)))
        return per_scale

    def __call__(self, preds, gt_boxes, img_size):
        from ....ndarray import op as ndop
        from ....ndarray.ndarray import NDArray

        grids = self._net.grids(img_size)
        gt_raw = np.asarray(gt_boxes.data if hasattr(gt_boxes, "data")
                            else gt_boxes)
        B = gt_raw.shape[0]
        # one assignment pass per sample (covers all scales), reused below
        per_sample = [self._targets(grids, gt_raw[b], np.float32)
                      for b in range(B)]
        ignore = self._ignore_mask(preds, grids, gt_raw)
        total = None
        for si, p in enumerate(preds):
            H, W, A, wh = grids[si]
            tgt = [per_sample[b][si] for b in range(B)]
            obj_t = NDArray(jnp.asarray(np.stack([t[0] for t in tgt])))
            box_t = NDArray(jnp.asarray(np.stack([t[1] for t in tgt])))
            cls_t = NDArray(jnp.asarray(np.stack([t[2] for t in tgt])))
            raw = p  # (B, N, 5+C) NDArray
            txy = ndop.slice_axis(raw, axis=2, begin=0, end=2)
            twh = ndop.slice_axis(raw, axis=2, begin=2, end=4)
            tobj = ndop.slice_axis(raw, axis=2, begin=4, end=5) \
                .reshape((B, -1))
            tcls = ndop.slice_axis(raw, axis=2, begin=5,
                                   end=5 + self._net.classes)

            pos = obj_t  # (B, N)
            npos = ndop.maximum(pos.sum(), 1.0)
            # objectness BCE: high-IoU non-assigned cells contribute zero
            # (the ignore mask); positives always count
            ign = NDArray(ignore[si])
            weight = pos + (1.0 - pos) * (1.0 - ign)
            obj_bce = _bce_logits(ndop, tobj, obj_t)
            obj_loss = (obj_bce * weight).sum() / \
                ndop.maximum(weight.sum(), 1.0)
            # box: sigmoid-xy vs fractional offset, raw wh vs log ratio
            pxy = ndop.sigmoid(txy)
            bxy = ndop.slice_axis(box_t, axis=2, begin=0, end=2)
            bwh = ndop.slice_axis(box_t, axis=2, begin=2, end=4)
            box_loss = (((pxy - bxy) ** 2 + (twh - bwh) ** 2).sum(axis=2)
                        * pos).sum() / npos
            # class BCE at positives
            onehot = ndop.one_hot(cls_t, self._net.classes)
            cls_bce = _bce_logits(ndop, tcls, onehot)
            cls_loss = (cls_bce.sum(axis=2) * pos).sum() / npos
            part = obj_loss + box_loss + 0.5 * cls_loss
            total = part if total is None else total + part
        return total


def yolo3_tiny(classes=3, **kwargs):
    return YOLOv3(classes=classes, **kwargs)
