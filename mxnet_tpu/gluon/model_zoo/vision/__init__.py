"""``mx.gluon.model_zoo.vision`` (reference: ``model_zoo/vision/``).

``get_model(name)`` registry; pretrained download is unavailable in this
environment (zero egress) — load local ``.params`` instead.
"""

from ....base import MXNetError
from .resnet import (  # noqa: F401
    get_resnet,
    resnet18_v1, resnet34_v1, resnet50_v1, resnet101_v1, resnet152_v1,
    resnet18_v2, resnet34_v2, resnet50_v2, resnet101_v2, resnet152_v2,
    ResNetV1, ResNetV2,
    BasicBlockV1, BasicBlockV2, BottleneckV1, BottleneckV2,
)

_models = {
    "resnet18_v1": resnet18_v1,
    "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1,
    "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2,
    "resnet34_v2": resnet34_v2,
    "resnet50_v2": resnet50_v2,
    "resnet101_v2": resnet101_v2,
    "resnet152_v2": resnet152_v2,
}


def register_model(name, fn):
    _models[name] = fn


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"Model {name} is not supported. Available: {sorted(_models)}"
        )
    return _models[name](**kwargs)
