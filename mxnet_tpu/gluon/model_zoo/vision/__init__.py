"""``mx.gluon.model_zoo.vision`` (reference: ``model_zoo/vision/``).

``get_model(name)`` registry; pretrained download is unavailable in this
environment (zero egress) — load local ``.params`` instead.
"""

from ....base import MXNetError
from .resnet import (  # noqa: F401
    get_resnet,
    resnet18_v1, resnet34_v1, resnet50_v1, resnet101_v1, resnet152_v1,
    resnet18_v2, resnet34_v2, resnet50_v2, resnet101_v2, resnet152_v2,
    ResNetV1, ResNetV2,
    BasicBlockV1, BasicBlockV2, BottleneckV1, BottleneckV2,
)

from .alexnet import AlexNet, alexnet  # noqa: F401
from .vgg import (  # noqa: F401
    VGG, get_vgg,
    vgg11, vgg13, vgg16, vgg19,
    vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn,
)
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1  # noqa: F401
from .densenet import (  # noqa: F401
    DenseNet, densenet121, densenet161, densenet169, densenet201,
)
from .mobilenet import (  # noqa: F401
    MobileNet, MobileNetV2, get_mobilenet, get_mobilenet_v2,
    mobilenet1_0, mobilenet0_75, mobilenet0_5, mobilenet0_25,
    mobilenet_v2_1_0, mobilenet_v2_0_75, mobilenet_v2_0_5, mobilenet_v2_0_25,
)
from .inception import Inception3, inception_v3  # noqa: F401
from .ssd import SSD, SSDLoss, ssd_tiny, ssd_300  # noqa: F401
from .faster_rcnn import (FasterRCNN, FasterRCNNLoss,  # noqa: F401
                          faster_rcnn_tiny)
from .yolo import YOLOv3, YOLOv3Loss, yolo3_tiny, yolo_detect  # noqa: F401

_models = {
    "resnet18_v1": resnet18_v1,
    "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1,
    "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2,
    "resnet34_v2": resnet34_v2,
    "resnet50_v2": resnet50_v2,
    "resnet101_v2": resnet101_v2,
    "resnet152_v2": resnet152_v2,
    "alexnet": alexnet,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn,
    "vgg16_bn": vgg16_bn, "vgg19_bn": vgg19_bn,
    "squeezenet1.0": squeezenet1_0,
    "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5, "mobilenetv2_0.25": mobilenet_v2_0_25,
    "inceptionv3": inception_v3,
    "ssd_tiny": ssd_tiny,
    "faster_rcnn_tiny": faster_rcnn_tiny,
    "yolo3_tiny": yolo3_tiny,
    "ssd_300": ssd_300,
}


def register_model(name, fn):
    _models[name] = fn


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"Model {name} is not supported. Available: {sorted(_models)}"
        )
    return _models[name](**kwargs)
