"""VGG 11/13/16/19 ±BN (reference: ``gluon/model_zoo/vision/vgg.py``)."""

from __future__ import annotations

from ...block import HybridBlock
from ...nn import (
    Activation,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    HybridSequential,
    MaxPool2D,
)
from ....base import MXNetError


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(filters)
        with self.name_scope():
            self.features = self._make_features(layers, filters, batch_norm)
            self.features.add(Dense(4096, activation="relu", flatten=True,
                                    weight_initializer="normal",
                                    bias_initializer="zeros"))
            self.features.add(Dropout(rate=0.5))
            self.features.add(Dense(4096, activation="relu",
                                    weight_initializer="normal",
                                    bias_initializer="zeros"))
            self.features.add(Dropout(rate=0.5))
            self.output = Dense(classes, weight_initializer="normal",
                                bias_initializer="zeros")

    def _make_features(self, layers, filters, batch_norm):
        featurizer = HybridSequential(prefix="")
        for i, num in enumerate(layers):
            for _ in range(num):
                featurizer.add(Conv2D(filters[i], kernel_size=3, padding=1,
                                      weight_initializer="xavier",
                                      bias_initializer="zeros"))
                if batch_norm:
                    featurizer.add(BatchNorm())
                featurizer.add(Activation("relu"))
            featurizer.add(MaxPool2D(strides=2))
        return featurizer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


def get_vgg(num_layers, pretrained=False, **kwargs):
    if num_layers not in vgg_spec:
        raise MXNetError(f"invalid vgg depth {num_layers}")
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        raise MXNetError("pretrained weights unavailable (zero-egress)")
    return net


def vgg11(**kwargs):
    return get_vgg(11, **kwargs)


def vgg13(**kwargs):
    return get_vgg(13, **kwargs)


def vgg16(**kwargs):
    return get_vgg(16, **kwargs)


def vgg19(**kwargs):
    return get_vgg(19, **kwargs)


def vgg11_bn(**kwargs):
    return get_vgg(11, batch_norm=True, **kwargs)


def vgg13_bn(**kwargs):
    return get_vgg(13, batch_norm=True, **kwargs)


def vgg16_bn(**kwargs):
    return get_vgg(16, batch_norm=True, **kwargs)


def vgg19_bn(**kwargs):
    return get_vgg(19, batch_norm=True, **kwargs)
