"""SSD single-shot detector (reference models: GluonCV ``ssd_300_vgg16``
family driven by ``src/operator/contrib/multibox_*.cc``; BASELINE.json
config #2 names the detection path).

TPU-first: every stage is static-shape — anchors are generated per
feature map by ``MultiBoxPrior``, training targets by ``MultiBoxTarget``
(dense IoU matching), and inference by ``MultiBoxDetection`` (fixed
trip-count NMS) — so train and predict both compile to single XLA
programs.
"""

from __future__ import annotations

from ...block import HybridBlock
from ...nn import (
    Activation,
    BatchNorm,
    Conv2D,
    HybridSequential,
    MaxPool2D,
)
from ... import loss as gloss


def _conv_block(channels, stride=1):
    out = HybridSequential(prefix="")
    out.add(Conv2D(channels, 3, strides=stride, padding=1, use_bias=False))
    out.add(BatchNorm())
    out.add(Activation("relu"))
    return out


class SSD(HybridBlock):
    """Compact SSD: a strided conv backbone emitting ``len(sizes)`` feature
    scales, each with class + box prediction heads and multibox priors.

    Outputs of ``hybrid_forward``: (anchors (1, N, 4), cls_preds
    (B, num_classes+1, N), box_preds (B, N*4)) — exactly the trio
    MultiBoxTarget / MultiBoxDetection consume.
    """

    def __init__(self, classes=20, base_channels=(16, 32, 64),
                 sizes=((0.2, 0.272), (0.37, 0.447), (0.54, 0.619)),
                 ratios=((1.0, 2.0, 0.5),) * 3, **kwargs):
        super().__init__(**kwargs)
        assert len(sizes) == len(ratios)
        self.classes = classes
        self.sizes = tuple(tuple(s) for s in sizes)
        self.ratios = tuple(tuple(r) for r in ratios)
        num_anchors = [len(s) + len(r) - 1
                       for s, r in zip(self.sizes, self.ratios)]
        with self.name_scope():
            self.stem = HybridSequential(prefix="stem_")
            for c in base_channels:
                self.stem.add(_conv_block(c))
                self.stem.add(MaxPool2D(2))
            self.stages = HybridSequential(prefix="stages_")
            self.cls_heads = HybridSequential(prefix="cls_")
            self.box_heads = HybridSequential(prefix="box_")
            c = base_channels[-1]
            for i in range(len(self.sizes)):
                if i > 0:
                    self.stages.add(_conv_block(c, stride=2))
                else:
                    self.stages.add(HybridSequential(prefix=""))
                self.cls_heads.add(Conv2D(num_anchors[i] * (classes + 1), 3,
                                          padding=1))
                self.box_heads.add(Conv2D(num_anchors[i] * 4, 3, padding=1))

    def hybrid_forward(self, F, x):
        feat = self.stem(x)
        anchors, cls_preds, box_preds = [], [], []
        for stage, cls_head, box_head in zip(self.stages, self.cls_heads,
                                             self.box_heads):
            feat = stage(feat)
            i = len(anchors)
            anchors.append(F.MultiBoxPrior(feat, sizes=self.sizes[i],
                                           ratios=self.ratios[i]))
            # (B, A*(C+1), H, W) -> (B, H*W*A, C+1)
            cp = F.transpose(cls_head(feat), axes=(0, 2, 3, 1))
            cls_preds.append(F.reshape(cp, (0, -1, self.classes + 1)))
            bp = F.transpose(box_head(feat), axes=(0, 2, 3, 1))
            box_preds.append(F.reshape(bp, (0, -1)))
        anchor = F.reshape(F.concat(*anchors, dim=1), (1, -1, 4)) \
            if len(anchors) > 1 else anchors[0]
        cls_pred = F.concat(*cls_preds, dim=1) if len(cls_preds) > 1 \
            else cls_preds[0]
        box_pred = F.concat(*box_preds, dim=1) if len(box_preds) > 1 \
            else box_preds[0]
        # cls to (B, C+1, N) layout for MultiBoxTarget/Detection
        cls_pred = F.transpose(cls_pred, axes=(0, 2, 1))
        return anchor, cls_pred, box_pred


class SSDLoss:
    """SSD training objective: softmax CE on matched classes (ignoring
    mined-out anchors) + smooth-L1 on encoded box offsets."""

    def __init__(self, lambd=1.0, **target_kwargs):
        self._lambd = lambd
        self._target_kwargs = target_kwargs

    def __call__(self, anchor, cls_pred, box_pred, label):
        from ....ndarray import op as ndop

        box_t, box_m, cls_t = ndop.MultiBoxTarget(
            anchor, label, cls_pred, **self._target_kwargs)
        # per-anchor CE with mined-out (-1) anchors contributing zero
        valid = cls_t >= 0
        logp = ndop.log_softmax(cls_pred, axis=1)  # (B, C+1, N)
        picked = ndop.pick(logp, cls_t * valid, axis=1)  # (B, N)
        cls_loss = -(picked * valid).mean()
        l1 = gloss.HuberLoss(rho=1.0)
        box_loss = l1(box_pred * box_m, box_t)
        return cls_loss + self._lambd * box_loss.mean()


def ssd_tiny(classes=20, **kwargs):
    """Small SSD for CI-scale training (3 scales, 16-64 channels)."""
    return SSD(classes=classes, **kwargs)


def ssd_300(classes=20, **kwargs):
    """SSD-300-ish capacity: deeper stem + 6 scales (reference:
    GluonCV ssd_300)."""
    sizes = ((0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
             (0.71, 0.79), (0.88, 0.961))
    ratios = ((1.0, 2.0, 0.5),) * 2 + ((1.0, 2.0, 0.5, 3.0, 1.0 / 3),) * 4
    return SSD(classes=classes, base_channels=(32, 48, 64),
               sizes=sizes, ratios=ratios, **kwargs)
