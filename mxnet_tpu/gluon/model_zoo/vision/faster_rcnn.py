"""Faster-RCNN (mini): the two-stage detection pipeline in the zoo.

Reference anchors: the contrib ops this composes — ``Proposal``
(``src/operator/contrib/proposal.cc``) and ``ROIAlign``
(``src/operator/contrib/roi_align.cc``) — plus the rcnn example's target
assigners (``example/rcnn``: anchor/proposal target layers). BASELINE
config #2 names Faster-RCNN as the second detection architecture.

TPU-native shape discipline: every stage is static-shape. Proposal pads
to ``rpn_post_nms_top_n`` rows; during training the last ``M`` roi slots
per image are overwritten with the ground-truth boxes (the standard
"append gt" trick, made static by replacement instead of concat) so the
RCNN head always sees positives; target assignment masks padded/ignored
entries instead of filtering them.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...block import HybridBlock
from ...nn import Conv2D, Dense, HybridSequential
from ....ops import detection as _det


class FasterRCNN(HybridBlock):
    """Backbone -> RPN -> Proposal -> ROIAlign -> cls/bbox heads.

    ``hybrid_forward(x, im_info)`` (inference) returns
    ``(rpn_cls, rpn_bbox, rois, cls_scores, bbox_pred)``;
    pass ``gt_boxes`` (B, M, 5) ``[cls, x1, y1, x2, y2]`` (pixel coords,
    cls in [0, classes)) to plant them into the roi set for training.
    """

    def __init__(self, classes=3, base_channels=(16, 32, 64),
                 rpn_channels=64, scales=(1, 2, 4), ratios=(0.5, 1, 2),
                 rpn_pre_nms=256, rpn_post_nms=32, roi_size=(7, 7),
                 top_units=128, **kwargs):
        super().__init__(**kwargs)
        self.classes = classes
        self.scales = tuple(scales)
        self.ratios = tuple(ratios)
        self.num_anchors = len(scales) * len(ratios)
        self.feature_stride = 2 ** len(base_channels)
        self.rpn_pre_nms = rpn_pre_nms
        self.rpn_post_nms = rpn_post_nms
        self.roi_size = tuple(roi_size)
        with self.name_scope():
            self.stem = HybridSequential(prefix="stem_")
            for c in base_channels:
                self.stem.add(Conv2D(c, 3, padding=1, strides=2,
                                     activation="relu"))
            A = self.num_anchors
            self.rpn_conv = Conv2D(rpn_channels, 3, padding=1,
                                   activation="relu", prefix="rpn_conv_")
            self.rpn_cls = Conv2D(2 * A, 1, prefix="rpn_cls_")
            self.rpn_bbox = Conv2D(4 * A, 1, prefix="rpn_bbox_")
            self.top = HybridSequential(prefix="top_")
            self.top.add(Dense(top_units, activation="relu"),
                         Dense(top_units, activation="relu"))
            self.cls_score = Dense(classes + 1, prefix="cls_score_")
            self.bbox_pred = Dense((classes + 1) * 4, prefix="bbox_pred_")

    def hybrid_forward(self, F, x, im_info, gt_boxes=None):
        B = x.shape[0]
        A = self.num_anchors
        feat = self.stem(x)
        r = self.rpn_conv(feat)
        rpn_cls = self.rpn_cls(r)          # (B, 2A, H, W)
        rpn_bbox = self.rpn_bbox(r)        # (B, 4A, H, W)
        H, W = rpn_cls.shape[2], rpn_cls.shape[3]
        # pairwise softmax over {bg, fg} per anchor (reference layout:
        # channels [0:A] = bg, [A:2A] = fg)
        prob = F.reshape(rpn_cls, (B, 2, A * H * W))
        prob = F.softmax(prob, axis=1)
        prob = F.reshape(prob, (B, 2 * A, H, W))
        rois = F.Proposal(prob, rpn_bbox, im_info,
                          rpn_pre_nms_top_n=self.rpn_pre_nms,
                          rpn_post_nms_top_n=self.rpn_post_nms,
                          feature_stride=self.feature_stride,
                          scales=self.scales, ratios=self.ratios)
        rois = F.stop_gradient(rois)       # proposals are constants
        if gt_boxes is not None:
            # overwrite the LAST M roi slots per image with gt boxes
            # (static-shape "append gt": guarantees RCNN positives)
            M = gt_boxes.shape[1]
            rois3 = F.reshape(rois, (B, self.rpn_post_nms, 5))
            keep = F.slice_axis(rois3, axis=1, begin=0,
                                end=self.rpn_post_nms - M)
            batch_idx = F.broadcast_to(
                F.reshape(F.arange(0, B), (B, 1, 1)), (B, M, 1))
            gt_rois = F.concat(batch_idx,
                               F.slice_axis(gt_boxes, axis=2, begin=1, end=5),
                               dim=2)
            rois3 = F.concat(keep, F.stop_gradient(gt_rois), dim=1)
            rois = F.reshape(rois3, (B * self.rpn_post_nms, 5))
        pooled = F.ROIAlign(feat, rois, pooled_size=self.roi_size,
                            spatial_scale=1.0 / self.feature_stride,
                            sample_ratio=2)
        flat = F.reshape(pooled, (pooled.shape[0], -1))
        top = self.top(flat)
        cls_scores = self.cls_score(top)       # (B*R, C+1)
        bbox_pred = self.bbox_pred(top)        # (B*R, (C+1)*4)
        return rpn_cls, rpn_bbox, rois, cls_scores, bbox_pred

    # -- inference decode (eager helper; reference: rcnn PredictorOp) ------
    def detect(self, x, im_info, score_thresh=0.05, nms_thresh=0.3):
        """Full two-stage inference -> (B, R, 6) [cls, score, x1 y1 x2 y2]
        rows, suppressed entries -1 (box_nms conventions)."""
        from ....ndarray import op as ndop
        from ....ndarray.ndarray import NDArray

        _, _, rois, cls_scores, bbox_pred = self(x, im_info)
        B = x.shape[0]
        R = self.rpn_post_nms
        probs = ndop.softmax(cls_scores, axis=-1)        # (B*R, C+1)
        cls = ndop.argmax(ndop.slice_axis(probs, axis=1, begin=1,
                                          end=self.classes + 1), axis=1) + 1
        score = ndop.max(ndop.slice_axis(probs, axis=1, begin=1,
                                         end=self.classes + 1), axis=1)
        # decode the predicted class's deltas against its roi
        raw_rois = rois.data if isinstance(rois, NDArray) else rois
        raw_cls = cls.data.astype(jnp.int32)
        raw_deltas = bbox_pred.data.reshape(-1, self.classes + 1, 4)
        deltas = jnp.take_along_axis(
            raw_deltas, raw_cls[:, None, None].repeat(4, -1), axis=1)[:, 0]
        boxes = _decode_deltas(raw_rois[:, 1:5], deltas)
        h = im_info.data[0, 0] if hasattr(im_info, "data") else im_info[0, 0]
        w = im_info.data[0, 1] if hasattr(im_info, "data") else im_info[0, 1]
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, w - 1),
                           jnp.clip(boxes[:, 1], 0, h - 1),
                           jnp.clip(boxes[:, 2], 0, w - 1),
                           jnp.clip(boxes[:, 3], 0, h - 1)], axis=-1)
        det = jnp.concatenate([raw_cls[:, None].astype(boxes.dtype),
                               score.data[:, None], boxes], axis=1)
        det = det.reshape(B, R, 6)
        out = ndop.box_nms(NDArray(det), overlap_thresh=nms_thresh,
                           valid_thresh=score_thresh, coord_start=2,
                           score_index=1, id_index=0, force_suppress=False)
        return out


def _decode_deltas(rois_xyxy, deltas):
    """Inverse of the RCNN bbox encoding (reference bbox_transform_inv)."""
    w = rois_xyxy[:, 2] - rois_xyxy[:, 0] + 1.0
    h = rois_xyxy[:, 3] - rois_xyxy[:, 1] + 1.0
    cx = rois_xyxy[:, 0] + 0.5 * w
    cy = rois_xyxy[:, 1] + 0.5 * h
    pcx = deltas[:, 0] * w + cx
    pcy = deltas[:, 1] * h + cy
    pw = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * w
    ph = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * h
    return jnp.stack([pcx - 0.5 * pw, pcy - 0.5 * ph,
                      pcx + 0.5 * pw - 1.0, pcy + 0.5 * ph - 1.0], axis=-1)


def _encode_deltas(rois_xyxy, gt_xyxy):
    w = rois_xyxy[:, 2] - rois_xyxy[:, 0] + 1.0
    h = rois_xyxy[:, 3] - rois_xyxy[:, 1] + 1.0
    cx = rois_xyxy[:, 0] + 0.5 * w
    cy = rois_xyxy[:, 1] + 0.5 * h
    gw = gt_xyxy[:, 2] - gt_xyxy[:, 0] + 1.0
    gh = gt_xyxy[:, 3] - gt_xyxy[:, 1] + 1.0
    gcx = gt_xyxy[:, 0] + 0.5 * gw
    gcy = gt_xyxy[:, 1] + 0.5 * gh
    return jnp.stack([(gcx - cx) / w, (gcy - cy) / h,
                      jnp.log(gw / w), jnp.log(gh / h)], axis=-1)


class FasterRCNNLoss:
    """Four-term objective: RPN objectness CE + RPN bbox smooth-L1 +
    RCNN class CE + RCNN per-class bbox smooth-L1 (reference:
    rcnn example's anchor/proposal target layers + module losses).
    Targets are assigned eagerly (no tape) from detached rois/anchors."""

    def __init__(self, net, rpn_pos_iou=0.7, rpn_neg_iou=0.3,
                 rcnn_fg_iou=0.5):
        self._net = net
        self._rpn_pos = rpn_pos_iou
        self._rpn_neg = rpn_neg_iou
        self._fg = rcnn_fg_iou

    def _rpn_targets(self, anchors, gt):  # anchors (N,4), gt (M,5)
        iou = _det._iou_matrix(anchors, gt[:, 1:5])      # (N, M)
        best = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        labels = jnp.full((anchors.shape[0],), -1.0)
        labels = jnp.where(best < self._rpn_neg, 0.0, labels)
        labels = jnp.where(best >= self._rpn_pos, 1.0, labels)
        # the best anchor per gt is always positive
        best_anchor = jnp.argmax(iou, axis=0)            # (M,)
        labels = labels.at[best_anchor].set(1.0)
        deltas = _encode_deltas(anchors, gt[best_gt, 1:5])
        return labels, deltas

    def _rcnn_targets(self, rois, gt):  # rois (R,5), gt (M,5)
        iou = _det._iou_matrix(rois[:, 1:5], gt[:, 1:5])
        best = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        cls = jnp.where(best >= self._fg, gt[best_gt, 0] + 1.0, 0.0)
        deltas = _encode_deltas(rois[:, 1:5], gt[best_gt, 1:5])
        return cls, deltas

    def __call__(self, out, gt_boxes):
        from ....ndarray import op as ndop
        from ....ndarray.ndarray import NDArray

        rpn_cls, rpn_bbox, rois, cls_scores, bbox_pred = out
        net = self._net
        A = net.num_anchors
        B, _, H, W = rpn_cls.shape
        gt_raw = gt_boxes.data if isinstance(gt_boxes, NDArray) else gt_boxes
        anchors = _det._make_grid_anchors(
            H, W, net.feature_stride, net.scales, net.ratios,
            jnp.float32)                                  # (HWA, 4)

        rpn_lab, rpn_tgt, rcnn_lab, rcnn_tgt = [], [], [], []
        rois_raw = (rois.data if isinstance(rois, NDArray) else rois) \
            .reshape(B, net.rpn_post_nms, 5)
        for i in range(B):
            lab, dl = self._rpn_targets(anchors, gt_raw[i])
            rpn_lab.append(lab)
            rpn_tgt.append(dl)
            cl, dt = self._rcnn_targets(rois_raw[i], gt_raw[i])
            rcnn_lab.append(cl)
            rcnn_tgt.append(dt)
        rpn_lab = NDArray(jnp.stack(rpn_lab))             # (B, N)
        rpn_tgt = NDArray(jnp.stack(rpn_tgt))             # (B, N, 4)
        rcnn_lab = NDArray(jnp.concatenate(rcnn_lab))     # (B*R,)
        rcnn_tgt = NDArray(jnp.concatenate(rcnn_tgt))     # (B*R, 4)

        # RPN objectness: channels [0:A]=bg, [A:2A]=fg in (H, W, A) order
        bg = ndop.reshape(ndop.transpose(
            ndop.slice_axis(rpn_cls, axis=1, begin=0, end=A),
            axes=(0, 2, 3, 1)), (B, -1))
        fg = ndop.reshape(ndop.transpose(
            ndop.slice_axis(rpn_cls, axis=1, begin=A, end=2 * A),
            axes=(0, 2, 3, 1)), (B, -1))
        logits = ndop.stack(bg, fg, axis=1)               # (B, 2, N)
        logp = ndop.log_softmax(logits, axis=1)
        valid = rpn_lab >= 0
        picked = ndop.pick(logp, rpn_lab * valid, axis=1)
        rpn_cls_loss = -(picked * valid).sum() / valid.sum()

        # RPN bbox: (B, 4A, H, W) -> (B, N, 4) matching anchor order
        bp = ndop.reshape(rpn_bbox, (B, A, 4, H, W))
        bp = ndop.reshape(ndop.transpose(bp, axes=(0, 3, 4, 1, 2)),
                          (B, -1, 4))
        pos = rpn_lab == 1
        rpn_box_loss = (ndop.smooth_l1(bp - rpn_tgt, scalar=3.0)
                        * pos.expand_dims(-1)).sum() / \
            ndop.maximum(pos.sum() * 4, 1.0)

        # RCNN class CE over all rois
        logp2 = ndop.log_softmax(cls_scores, axis=-1)     # (B*R, C+1)
        rcnn_cls_loss = -ndop.pick(logp2, rcnn_lab, axis=1).mean()

        # RCNN bbox: differentiable class-column pick via one_hot mask
        dp = ndop.reshape(bbox_pred, (-1, net.classes + 1, 4))
        onehot = ndop.one_hot(rcnn_lab, net.classes + 1)  # (B*R, C+1)
        picked_deltas = (dp * onehot.expand_dims(-1)).sum(axis=1)
        fgm = rcnn_lab > 0
        rcnn_box_loss = (ndop.smooth_l1(picked_deltas - rcnn_tgt, scalar=1.0)
                         * fgm.expand_dims(-1)).sum() / \
            ndop.maximum(fgm.sum() * 4, 1.0)

        return rpn_cls_loss + rpn_box_loss + rcnn_cls_loss + rcnn_box_loss


def faster_rcnn_tiny(classes=3, **kwargs):
    """64x64-image scale config used by the tests/examples."""
    return FasterRCNN(classes=classes, base_channels=(16, 32, 64),
                      scales=(1, 2, 4), ratios=(0.5, 1, 2),
                      rpn_pre_nms=192, rpn_post_nms=32, **kwargs)
