"""Convolution / pooling Gluon layers.

Reference: ``python/mxnet/gluon/nn/conv_layers.py`` (symbols ``_Conv``,
``Conv2D``, ``MaxPool2D``, ``GlobalAvgPool2D``...). NCHW-family layouts
only (the TPU-efficient path: XLA re-lays-out internally as needed).
"""

from __future__ import annotations

from ..block import HybridBlock
from .basic_layers import Activation


def _tuple(x, n):
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,) * n


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._in_channels = in_channels
        nd = len(kernel_size)
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout,
        }
        if adj is not None:
            self._kwargs["adj"] = adj
        self._op_name = op_name
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=self._weight_shape(), init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _weight_shape(self):
        if self._op_name == "Deconvolution":
            return (self._in_channels, self._channels // self._kwargs["num_group"]) \
                + tuple(self._kwargs["kernel"])
        return (self._channels, self._in_channels // max(self._kwargs["num_group"], 1)) \
            + tuple(self._kwargs["kernel"])

    def infer_shape(self, x):
        layout = self._kwargs.get("layout") or ""
        in_c = x.shape[-1] if layout.endswith("C") else x.shape[1]
        self._in_channels = in_c
        if self._op_name == "Deconvolution":
            self.weight.shape = (in_c, self._channels // self._kwargs["num_group"]) \
                + tuple(self._kwargs["kernel"])
        else:
            self.weight.shape = (self._channels, in_c // self._kwargs["num_group"]) \
                + tuple(self._kwargs["kernel"])

    def hybrid_forward(self, F, x, weight, bias=None):
        if getattr(self, "_tpu_fused", False):
            out = self._fused_forward(F, x, weight, bias)
            if out is not None:
                return out
        op = getattr(F, self._op_name)
        if bias is None:
            out = op(x, weight, **self._kwargs)
        else:
            out = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out

    def _fused_forward(self, F, x, weight, bias=None):
        """TPU fused 1x1-conv path (optimize_for backend): NHWC matmul
        with BN-stats epilogue; consumes a PendingApply input in the
        kernel prologue. A conv bias stays unapplied on the StatsArray
        (a batch-stat BN cancels it). See gluon/nn/tpu_fusion.py."""
        from .tpu_fusion import PendingApply, StatsArray

        if getattr(x, "ndim", 0) != 4:
            return None
        b, h, wd, c = x.shape
        o = self._channels
        wt = F.transpose(weight.reshape((o, c)))
        if isinstance(x, PendingApply):
            raw2 = x.raw.reshape((b * h * wd, c))
            y2, ysum, yssq = F._contrib_fused_scaled_matmul_stats(
                raw2, x.scale, x.shift, wt, relu=x.relu_flag)
        else:
            x2 = x.reshape((b * h * wd, c))
            y2, ysum, yssq = F._contrib_fused_matmul_stats(x2, wt)
        y = y2.reshape((b, h, wd, o))
        return StatsArray(y, ysum, yssq, b * h * wd, bias=bias)

    def __repr__(self):
        return (f"{self.__class__.__name__}({self._in_channels} -> "
                f"{self._channels}, kernel_size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), _tuple(strides, 1),
                         _tuple(padding, 1), _tuple(dilation, 1), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), _tuple(strides, 2),
                         _tuple(padding, 2), _tuple(dilation, 2), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), _tuple(strides, 3),
                         _tuple(padding, 3), _tuple(dilation, 3), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), _tuple(strides, 1),
                         _tuple(padding, 1), _tuple(dilation, 1), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuple(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), _tuple(strides, 2),
                         _tuple(padding, 2), _tuple(dilation, 2), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuple(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0), dilation=(1, 1, 1),
                 groups=1, layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), _tuple(strides, 3),
                         _tuple(padding, 3), _tuple(dilation, 3), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuple(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout=None, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
        }
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return (f"{self.__class__.__name__}(size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']}, "
                f"padding={self._kwargs['pad']})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 1),
                         _tuple(strides, 1) if strides is not None else None,
                         _tuple(padding, 1), ceil_mode, False, "max", **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 2),
                         _tuple(strides, 2) if strides is not None else None,
                         _tuple(padding, 2), ceil_mode, False, "max", **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 3),
                         _tuple(strides, 3) if strides is not None else None,
                         _tuple(padding, 3), ceil_mode, False, "max", **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuple(pool_size, 1),
                         _tuple(strides, 1) if strides is not None else None,
                         _tuple(padding, 1), ceil_mode, False, "avg",
                         count_include_pad=count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuple(pool_size, 2),
                         _tuple(strides, 2) if strides is not None else None,
                         _tuple(padding, 2), ceil_mode, False, "avg",
                         count_include_pad=count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuple(pool_size, 3),
                         _tuple(strides, 3) if strides is not None else None,
                         _tuple(padding, 3), ceil_mode, False, "avg",
                         count_include_pad=count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), (1,), (0,), True, True, "max", **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), (1, 1), (0, 0), True, True, "max", **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), (1, 1, 1), (0, 0, 0), True, True, "max",
                         **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), (1,), (0,), True, True, "avg", **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), (1, 1), (0, 0), True, True, "avg", **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), (1, 1, 1), (0, 0, 0), True, True, "avg",
                         **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
