"""Basic Gluon layers.

Reference: ``python/mxnet/gluon/nn/basic_layers.py`` (symbols ``Dense``,
``BatchNorm``, ``Dropout``, ``Sequential``...). Same parameter naming
(``weight``/``bias``/``gamma``/``beta``/``running_mean``/``running_var``)
so reference checkpoints map 1:1.
"""

from __future__ import annotations

from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import Parameter


class Sequential(Block):
    """Stack of blocks executed eagerly (reference: ``nn.Sequential``)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = []
            if isinstance(x, (tuple, list)):
                args = x[1:]
                x = x[0]
        if args:
            return (x,) + tuple(args)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(
            isinstance(c, HybridBlock) for c in self._children.values()
        ):
            import warnings

            warnings.warn(
                "All children of this Sequential layer are HybridBlocks. "
                "Consider using HybridSequential for the best performance."
            )
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack of hybridizable blocks (reference: ``nn.HybridSequential``)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (reference: ``nn.Dense`` over the
    ``FullyConnected`` op; lowers to one MXU matmul)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x):
        if self._flatten:
            in_units = 1
            for d in x.shape[1:]:
                in_units *= d
        else:
            in_units = x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        if getattr(self, "_tpu_nchw", False):
            if getattr(x, "ndim", None) is None:
                # Symbol: the layout can't be inspected, and skipping the
                # restore would silently contract NHWC features against
                # NCHW weights — refuse loudly (the pass's contract)
                raise MXNetError(
                    "symbolic forward of an optimize_for'd Dense is "
                    "unsupported: input layout cannot be inferred from a "
                    "Symbol")
            if x.ndim == 4:
                # NHWC fused interior: restore NCHW feature order so the
                # implicit flatten (or last-axis contraction) matches
                # NCHW-trained weights (mirrors Flatten's
                # _tpu_nchw_flatten)
                x = F.transpose(x, axes=(0, 3, 1, 2))
        out = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} -> "
                f"{shape[0] if shape else None}, "
                f"{'linear' if self.act is None else self.act._act_type})")


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        if self._act_type == "relu":
            from .tpu_fusion import PendingApply

            if isinstance(x, PendingApply) and not x.relu_flag:
                return x.with_relu()
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return F.identity(x)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with moving-stat aux state.

    Reference: ``nn.BatchNorm`` (note the reference default
    ``scale=True`` => ``fix_gamma=False`` at the op level)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {
            "axis": axis, "eps": epsilon, "momentum": momentum,
            "fix_gamma": not scale, "use_global_stats": use_global_stats,
        }
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def _effective_axis(self, x):
        """NHWC fused mode normalises the last axis of 4-D tensors;
        2-D (post-Dense) inputs keep the configured axis. Symbol has no
        ndim: refuse loudly — the converted conv emits NHWC symbolically,
        so the configured axis would normalise H, silently wrong."""
        if getattr(self, "_tpu_nhwc", False):
            nd = getattr(x, "ndim", None)
            if nd is None:
                raise MXNetError(
                    "symbolic forward of an optimize_for'd BatchNorm is "
                    "unsupported: input layout cannot be inferred from a "
                    "Symbol")
            if nd == 4:
                return 3
        return self._axis

    def infer_shape(self, x):
        c = x.shape[self._effective_axis(x)]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        if str(dtype) == "float16":
            dtype = "float32"  # BN statistics stay fp32 (reference behavior)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from .tpu_fusion import StatsArray, fused_batch_norm

        if isinstance(x, StatsArray):
            k = self._kwargs
            return fused_batch_norm(
                x, gamma, beta, running_mean, running_var, k["eps"],
                k["momentum"], k["fix_gamma"], k["use_global_stats"])
        kwargs = self._kwargs
        ax = self._effective_axis(x)
        if ax != kwargs["axis"]:
            kwargs = dict(kwargs, axis=ax)
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0] if self.gamma.shape else None
        return f"BatchNorm(axis={self._axis}, in_channels={in_channels})"


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BN (reference: ``contrib.nn.SyncBatchNorm``).

    Under pjit/SPMD the batch statistics are computed over the *global*
    batch automatically when the step is sharded — so this inherits plain
    BatchNorm; the distinction only matters in the eager multi-process path.
    """

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._kwargs["eps"])


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"axis": axis, "eps": epsilon}
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._kwargs["axis"],
                           eps=self._kwargs["eps"])


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._eps)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._kwargs = {
            "input_dim": input_dim, "output_dim": output_dim,
            "dtype": dtype, "sparse_grad": sparse_grad,
        }
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        if getattr(self, "_tpu_nchw_flatten", False):
            if getattr(x, "ndim", None) is None:
                raise MXNetError(
                    "symbolic forward of an optimize_for'd Flatten is "
                    "unsupported: input layout cannot be inferred from a "
                    "Symbol")
            if x.ndim == 4:
                # NHWC fused interior: restore NCHW feature order so the
                # flattened vector matches NCHW-trained downstream weights
                x = F.transpose(x, axes=(0, 3, 1, 2))
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ...ndarray import op as F

            function = getattr(F, function)
        self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function
            function = None
        else:
            self._func_name = getattr(function, "__name__", "lambda")
        self._func_impl = function

    def hybrid_forward(self, F, *args):
        if self._func_impl is None:
            return getattr(F, self._func_name)(*args)
        return self._func_impl(F, *args)
