"""TPU conv+BN fusion pass (``HybridBlock.optimize_for`` backend).

Reference analog: ``HybridBlock.optimize_for(x, backend='MKLDNN')`` —
the subgraph property that fuses Conv+BN(+ReLU) and switches activation
layouts to the backend's preferred blocked format
(``src/operator/subgraph/mkldnn/mkldnn_conv.cc``). The TPU design is
different in kind: there is no graph IR to rewrite (tracing is direct),
so fusion happens through *cooperating blocks* exchanging lazily-applied
tensors:

- ``optimize_for(net, backend='tpu_fused_conv_bn')`` walks the tree,
  switches every Conv2D/Pooling to NHWC (activations only — parameter
  layouts are untouched, so checkpoints remain interchangeable), marks
  eligible 1x1 convolutions, and wraps the net in an adapter that keeps
  the external NCHW interface.
- A marked conv emits a :class:`StatsArray` — its raw output plus
  per-channel (sum, sum-of-squares) accumulated in the Pallas kernel's
  epilogue (ops/fused_conv_bn.py), so the following BatchNorm never
  re-reads the tensor to compute batch moments.
- That BatchNorm returns a :class:`PendingApply` — the raw tensor plus
  folded per-channel scale/shift. A following marked conv consumes it
  *unmaterialised* (normalize+relu runs in the matmul prologue); any
  other consumer transparently materialises on first ``.data`` access
  through recorded ops, so autograd is oblivious to the laziness.
"""

from __future__ import annotations

from ... import autograd
from ...base import MXNetError
from ...ndarray.ndarray import NDArray


class StatsArray(NDArray):
    """A conv output that carries its own batch statistics.

    ``raw`` is the bias-free matmul output; ``bias`` (or None) is the
    conv's additive bias, kept UNAPPLIED because a following batch-stat
    BatchNorm cancels it exactly (it only shifts the recorded running
    mean). ``bn_stats = (ysum, yssq, count)`` are the kernel-epilogue
    sums of ``raw``. Mathematically this array is ``raw + bias`` —
    non-BN consumers materialise that lazily on ``.data`` access."""

    __slots__ = ("raw", "bias", "bn_stats")

    def __init__(self, y: NDArray, ysum: NDArray, yssq: NDArray,
                 count: int, bias: NDArray = None):
        super().__init__(y.data[:0], ctx=y.ctx)
        self._data_ = None
        self.raw = y
        self.bias = bias
        self.bn_stats = (ysum, yssq, count)

    @property
    def data(self):
        if self._data_ is None:
            if self.bias is None:
                self._data_ = self.raw.data
                self._ag = self.raw._ag
            else:
                c = self.raw.shape[-1]
                bshape = (1,) * (len(self.raw.shape) - 1) + (c,)
                out = self.raw + self.bias.astype(self.raw.dtype) \
                    .reshape(bshape)
                self._data_ = out.data
                self._ag = out._ag
            self._version += 1
        return self._data_

    @property
    def shape(self):
        return self.raw.shape if self._data_ is None \
            else tuple(self._data_.shape)

    @property
    def dtype(self):
        import numpy as _np

        return _np.dtype(self.raw.dtype) if self._data_ is None \
            else _np.dtype(self._data_.dtype)


class PendingApply(NDArray):
    """A BatchNorm output in deferred form: raw tensor + per-channel
    scale/shift (+relu) not yet applied. Cooperating convs consume the
    raw form in their kernel prologue; everyone else materialises
    lazily (the apply runs as recorded ops, so gradients flow)."""

    __slots__ = ("raw", "scale", "shift", "relu_flag")

    def __init__(self, raw: NDArray, scale: NDArray, shift: NDArray,
                 relu: bool):
        # shell: no buffer until materialised
        super().__init__(raw.data[:0], ctx=raw.ctx)  # placeholder, replaced
        self._data_ = None
        self.raw = raw
        self.scale = scale
        self.shift = shift
        self.relu_flag = relu

    def with_relu(self) -> "PendingApply":
        return PendingApply(self.raw, self.scale, self.shift, True)

    # -- lazy materialisation ------------------------------------------
    @property
    def data(self):
        if self._data_ is None:
            self._materialize()
        return self._data_

    @property
    def shape(self):
        return self.raw.shape if self._data_ is None \
            else tuple(self._data_.shape)

    @property
    def dtype(self):
        import numpy as _np

        return _np.dtype(self.raw.dtype) if self._data_ is None \
            else _np.dtype(self._data_.dtype)

    def _materialize(self):
        from ...ndarray import op as F

        c = self.raw.shape[-1]
        bshape = (1,) * (len(self.raw.shape) - 1) + (c,)
        s = self.scale.astype(self.raw.dtype).reshape(bshape)
        t = self.shift.astype(self.raw.dtype).reshape(bshape)
        out = self.raw * s + t
        if self.relu_flag:
            out = F.relu(out)
        self._data_ = out.data
        self._ag = out._ag
        self._version += 1


def fused_batch_norm(x: StatsArray, gamma, beta, running_mean, running_var,
                     eps, momentum, fix_gamma, use_global_stats):
    """BatchNorm over a StatsArray: batch moments come from the conv
    kernel's epilogue sums — no pass over the tensor. Returns a
    PendingApply; running stats update in place (reference mutates aux
    states in-kernel, ``src/operator/nn/batch_norm.cc``)."""
    from ...ndarray import op as F

    ysum, yssq, count = x.bn_stats
    training = autograd.is_training() and not use_global_stats
    if training:
        mean = ysum / float(count)  # of the bias-free raw output
        var = F.maximum(yssq / float(count) - mean * mean,
                        F.zeros_like(ysum))
        with autograd.pause():
            m = float(momentum)
            # the recorded running mean is of conv-out = raw + bias
            rm_new = mean.data if x.bias is None \
                else mean.data + x.bias.data.astype(mean.dtype)
            running_mean._set_data(
                (m * running_mean.data
                 + (1.0 - m) * rm_new).astype(running_mean.dtype))
            running_var._set_data(
                (m * running_var.data
                 + (1.0 - m) * var.data).astype(running_var.dtype))
    else:
        mean, var = running_mean, running_var
    acc = str(ysum.dtype)  # promote-based stat dtype (f32; f64 on x64)
    inv = (var.astype(acc) + float(eps)) ** -0.5
    if fix_gamma:
        s = inv
    else:
        s = gamma.astype(acc) * inv
    # shift for the BIAS-FREE raw tensor: in training the conv bias
    # cancels against the batch mean; in eval it survives as (+bias)
    t = beta.astype(acc) - mean.astype(acc) * s
    if not training and x.bias is not None:
        t = t + x.bias.astype(acc) * s
    return PendingApply(x.raw, s, t, False)


# ---------------------------------------------------------------------------
# the optimize_for pass
# ---------------------------------------------------------------------------

#: block classes that are layout-agnostic (safe to leave untouched)
_AGNOSTIC = ()


def _agnostic_types():
    global _AGNOSTIC
    if not _AGNOSTIC:
        from . import activations, basic_layers

        # Dense/Flatten are NOT here: they are layout-sensitive (implicit
        # flatten over NHWC vs NCHW feature order) and convert_block
        # handles them explicitly
        types = [basic_layers.Activation, basic_layers.Dropout,
                 basic_layers.Lambda, basic_layers.HybridLambda]
        for name in ("LeakyReLU", "PReLU", "ELU", "SELU", "GELU", "Swish"):
            if hasattr(activations, name):
                types.append(getattr(activations, name))
        _AGNOSTIC = tuple(types)
    return _AGNOSTIC


def convert_block(block):
    """Switch one block's activation layout to NHWC / mark for fusion.
    Returns True if handled."""
    from . import basic_layers, conv_layers

    if isinstance(block, conv_layers.Conv2D):
        block._kwargs["layout"] = "NHWC"
        k = block._kwargs
        block._tpu_fused = (
            tuple(k["kernel"]) == (1, 1) and tuple(k["stride"]) == (1, 1)
            and tuple(k["pad"]) == (0, 0) and tuple(k["dilate"]) == (1, 1)
            and k["num_group"] == 1 and block.act is None)
        return True
    if isinstance(block, basic_layers.BatchNorm):
        # runtime-gated: 4-D inputs normalise the last axis; 2-D
        # (post-Dense) BNs keep their configured axis
        block._tpu_nhwc = True
        return True
    if isinstance(block, basic_layers.Dense):
        # Dense consuming a 4-D NHWC interior tensor (VGG/AlexNet-style
        # conv->Dense without an explicit Flatten) must see NCHW feature
        # order before the implicit flatten, or its weights — NCHW-
        # trained — silently mismatch (ADVICE r5 medium)
        block._tpu_nchw = True
        return True
    if isinstance(block, conv_layers._Pooling):
        block._kwargs["layout"] = "NHWC"
        return True
    if isinstance(block, basic_layers.Flatten):
        # flattening an NHWC interior tensor would permute features vs
        # the NCHW parameter order; transpose back first (no-op for the
        # common post-global-pool (b, 1, 1, c) case)
        block._tpu_nchw_flatten = True
        return True
    return False


class NCHWAdapter(object):
    """Callable façade keeping the external NCHW interface of a net whose
    interior was switched to NHWC. Forward transposes the input once;
    4-D outputs — including each 4-D element of tuple/list outputs
    (multi-feature-map nets) — are transposed back."""

    def __init__(self, net):
        self._net = net

    @staticmethod
    def _back(out):
        from ...ndarray import op as F

        if isinstance(out, NDArray) and out.ndim == 4:
            return F.transpose(out, axes=(0, 3, 1, 2))
        return out

    def __call__(self, x):
        from ...ndarray import op as F

        if getattr(x, "ndim", 0) == 4:
            x = F.transpose(x, axes=(0, 2, 3, 1))
        out = self._net(x)
        if isinstance(out, (tuple, list)):
            mapped = [self._back(o) for o in out]
            if hasattr(out, "_fields"):  # namedtuple: positional fields
                return type(out)(*mapped)
            return type(out)(mapped)
        return self._back(out)

    def __getattr__(self, name):  # delegate (collect_params, cast, ...)
        return getattr(self._net, name)


def optimize_for(net, backend="tpu_fused_conv_bn", strict=True):
    """Walk ``net`` converting conv/BN/pooling blocks to the NHWC fused
    pipeline; returns an adapter preserving the NCHW interface.

    ``strict=False`` skips unknown block types instead of raising (the
    reference backend falls back to the default graph the same way)."""
    if backend != "tpu_fused_conv_bn":
        raise MXNetError(f"unknown optimize_for backend '{backend}'")

    seen = set()

    def walk(b):
        if id(b) in seen:
            return
        seen.add(id(b))
        handled = convert_block(b)
        if not handled and strict and b._reg_params \
                and not isinstance(b, _agnostic_types()):
            # a block with its OWN parameters that we don't understand is
            # likely layout-sensitive (InstanceNorm axis=1, Conv3D, ...):
            # refuse rather than silently compute the wrong thing — the
            # reference backend falls back the same way
            raise MXNetError(
                "optimize_for(tpu_fused_conv_bn): unsupported "
                f"parameterised block {type(b).__name__}; pass "
                "strict=False to skip it (at your own risk)")
        for child in b._children.values():
            walk(child)

    walk(net)
    return NCHWAdapter(net)
