"""Weight initializers.

Reference: ``python/mxnet/initializer.py`` (symbols ``Xavier``, ``MSRAPrelu``,
``Mixed``, ``InitDesc``). Same registry + name-pattern dispatch semantics.
"""

from __future__ import annotations

import json
import re

import numpy as _np

from .base import MXNetError

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


class InitDesc(str):
    """Parameter name + attrs descriptor (reference: ``InitDesc``)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose, self._print_func = verbose, print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be a string or InitDesc")
        init = getattr(desc, "attrs", {}).get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _set(self, arr, value):
        arr._set_data(_np.asarray(value, dtype=arr.dtype))

    def _init_zero(self, _, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_one(self, _, arr):
        self._set(arr, _np.ones(arr.shape))

    def _init_bias(self, _, arr):
        self._init_zero(_, arr)

    def _init_gamma(self, _, arr):
        self._init_one(_, arr)

    def _init_beta(self, _, arr):
        self._init_zero(_, arr)

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._init_zero(_, arr)


zeros = Zero


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        self._init_one(_, arr)


ones = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        self._set(arr, _np.full(arr.shape, self.value))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._set(arr, _np.random.uniform(-self.scale, self.scale, arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._set(arr, _np.random.normal(0, self.sigma, arr.shape))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        self._set(arr, self.scale * q.reshape(arr.shape))


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        if len(shape) < 2:
            raise MXNetError(
                f"Xavier initializer needs >=2D weight, got {shape} for {desc}"
            )
        hw_scale = float(_np.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {
            "avg": (fan_in + fan_out) / 2.0,
            "in": fan_in,
            "out": fan_out,
        }[self.factor_type]
        scale = _np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, _np.random.uniform(-scale, scale, shape))
        else:
            self._set(arr, _np.random.normal(0, scale, shape))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        weight = _np.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight)


@register
class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = _np.zeros(arr.shape)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        self._set(arr, b)


class Mixed:
    """Regex-dispatched initializer (reference: ``initializer.py:Mixed``)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must have same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(f"Parameter name {name} did not match any pattern")


class Load:
    def __init__(self, param, default_init=None, verbose=False):
        self.param = {
            k.replace("arg:", "").replace("aux:", ""): v for k, v in param.items()
        }
        self.default_init = default_init

    def __call__(self, name, arr):
        if name in self.param:
            arr._set_data(self.param[name].data)
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise ValueError(f"Cannot init parameter {name} from loaded params")


_ALIASES = {"zeros": "zero", "ones": "one", "gaussian": "normal",
            "msraprelu": "msraprelu", "xavier": "xavier"}


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if name is None or name == "":
        return Uniform()
    key = name.lower()
    key = _ALIASES.get(key, key)
    if key not in _REGISTRY:
        raise MXNetError(f"unknown initializer {name}")
    return _REGISTRY[key](**kwargs)


registry = _REGISTRY
