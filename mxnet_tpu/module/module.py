"""Legacy Module API.

Reference: ``python/mxnet/module/`` (symbols ``BaseModule.fit``, ``Module``,
``BucketingModule``). Implemented over the Symbol Executor; the
data-parallel multi-executor machinery of the reference collapses to one
XLA-sharded executor (SURVEY.md §3.4).
"""

from __future__ import annotations

import logging
import time

import numpy as _np

from .. import metric as _metric
from .. import optimizer as _opt
from ..base import MXNetError
from ..callback import BatchEndParam
from ..context import cpu, current_context
from ..initializer import Uniform
from ..io import DataBatch, DataDesc
from ..ndarray.ndarray import NDArray, array as _array, zeros as _zeros


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- high-level API ---------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, batch_end_callback=None,
              score_end_callback=None, reset=True, epoch=0, sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch, nbatch, eval_metric))
            actual_num_batch += 1
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outputs = [
                out[0:out.shape[0] - pad] for out in self.get_outputs()
            ]
            output_list.append(outputs)
        if not output_list:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [
                _concat([o[i] for o in output_list]) for i in range(num_outputs)
            ]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The classic training loop (reference: ``BaseModule.fit``)."""
        assert num_epoch is not None, "please specify number of epochs"
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        # stage batches to the module's device N ahead from a background
        # thread (MXTPU_DEVICE_PREFETCH, 0 disables); reset()/provide_*
        # pass through the wrapper, so the epoch loop below is unchanged
        from ..gluon.data.prefetcher import wrap_for_fit

        train_data = wrap_for_fit(train_data,
                                  getattr(self, "_context", None))

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            end_of_batch = False
            data_iter = iter(train_data)
            next_data_batch = next(data_iter)
            while not end_of_batch:
                data_batch = next_data_batch
                self.forward_backward(data_batch)
                self.update()
                try:
                    next_data_batch = next(data_iter)
                except StopIteration:
                    end_of_batch = True
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(BatchEndParam(epoch, nbatch, eval_metric))
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)
            train_data.reset()

    @property
    def symbol(self):
        return self._symbol


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def _concat(arrays):
    import jax.numpy as jnp

    return NDArray(jnp.concatenate([a.data for a in arrays], axis=0))


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._context = context or current_context()
        if isinstance(self._context, (list, tuple)):
            self._context = self._context[0]  # XLA shards; one logical ctx
        self._fixed_param_names = set(fixed_param_names or [])
        self._exec = None
        self._optimizer = None
        self._updater_states = {}
        arg_names = symbol.list_arguments()
        self._param_names = [
            n for n in arg_names
            if n not in self._data_names and n not in self._label_names
        ]
        self._aux_names = symbol.list_auxiliary_states()

    # -- binding ----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        shapes = {}
        for d in data_shapes:
            name, shape = (d.name, d.shape) if isinstance(d, DataDesc) else (d[0], d[1])
            shapes[name] = shape
        if label_shapes:
            for d in label_shapes:
                name, shape = (d.name, d.shape) if isinstance(d, DataDesc) else (d[0], d[1])
                shapes[name] = shape
        self._data_shapes = dict(shapes)
        self._exec = self._symbol.simple_bind(
            ctx=self._context, grad_req=grad_req if for_training else "null",
            **shapes)
        # don't compute grads for data/label
        for n in self._data_names + self._label_names:
            if n in self._exec.grad_dict:
                del self._exec.grad_dict[n]
        self.binded = True

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        from ..initializer import InitDesc

        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr._set_data(arg_params[name].data)
            elif initializer is not None:
                initializer(InitDesc(name), arr)
            elif not allow_missing:
                raise MXNetError(f"no initializer and no value for {name}")
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr._set_data(aux_params[name].data)
            elif initializer is not None:
                initializer(InitDesc(name), arr)
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not self.params_initialized:
            self.init_params(None, arg_params, aux_params, allow_missing, True)
            return
        for n, v in (arg_params or {}).items():
            if n in self._exec.arg_dict:
                self._exec.arg_dict[n]._set_data(v.data)
            elif not allow_extra:
                raise MXNetError(f"unknown parameter {n}")
        for n, v in (aux_params or {}).items():
            if n in self._exec.aux_dict:
                self._exec.aux_dict[n]._set_data(v.data)
            elif not allow_extra:
                raise MXNetError(f"unknown aux state {n}")

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            idx2name = dict(enumerate(self._param_names))
            opt_params = dict(optimizer_params)
            if "rescale_grad" not in opt_params:
                # reference Module.init_optimizer defaults rescale_grad to
                # 1/batch_size (grads are batch sums through SoftmaxOutput)
                batch = next(iter(self._data_shapes.values()))[0] \
                    if getattr(self, "_data_shapes", None) else 1
                opt_params["rescale_grad"] = 1.0 / max(batch, 1)
            optimizer = _opt.create(optimizer, param_idx2name=idx2name,
                                    **opt_params)
        self._optimizer = optimizer
        self._updater_states = {}
        self.optimizer_initialized = True

    # -- compute ----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = arr
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads)

    def update(self):
        assert self.binded and self.params_initialized and self.optimizer_initialized
        for i, name in enumerate(self._param_names):
            if name in self._fixed_param_names:
                continue
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            weight = self._exec.arg_dict[name]
            if i not in self._updater_states:
                self._updater_states[i] = \
                    self._optimizer.create_state_multi_precision(i, weight)
            self._optimizer.update_multi_precision(i, weight, grad,
                                                   self._updater_states[i])

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    # -- checkpointing ----------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._symbol.save(f"{prefix}-symbol.json")
        arg, aux = self.get_params()
        save_dict = {f"arg:{k}": v for k, v in arg.items()}
        save_dict.update({f"aux:{k}": v for k, v in aux.items()})
        from ..ndarray import ndarray as nd

        nd.save(f"{prefix}-{epoch:04d}.params", save_dict)
        if save_optimizer_states:
            import pickle

            with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                pickle.dump(self._updater_states, f)

    def save_optimizer_states(self, fname):
        import pickle

        with open(fname, "wb") as f:
            pickle.dump(self._updater_states, f)

    def load_optimizer_states(self, fname):
        import pickle

        with open(fname, "rb") as f:
            self._updater_states = pickle.load(f)

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = arg
        mod._aux_params = aux
        mod._preloaded = (arg, aux)
        orig_init = mod.init_params

        def init_params(initializer=Uniform(0.01), arg_params=None,
                        aux_params=None, **kw):
            orig_init(initializer, arg_params or arg, aux_params or aux, **kw)

        mod.init_params = init_params
        if load_optimizer_states:
            mod._preload_states = f"{prefix}-{epoch:04d}.states"
        return mod


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    from ..ndarray import ndarray as nd

    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_checkpoint(prefix, epoch):
    from ..symbol import symbol as sym_mod
    from ..ndarray import ndarray as nd

    symbol = sym_mod.load(f"{prefix}-symbol.json")
    saved = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in saved.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        else:
            aux_params[name] = v
    return symbol, arg_params, aux_params


class BucketingModule(BaseModule):
    """Bucketed-sequence training (reference: ``BucketingModule``).

    TPU note: one executable compiles per bucket key — identical to the
    reference's per-bucket executors; prefer padded pipelines on TPU.
    """

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, **kwargs):
        super().__init__(logger)
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._kwargs = kwargs
        self._buckets = {}
        self._curr_module = None
        self._shared_params = None

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None

    def _get_module(self, bucket_key, data_shapes, label_shapes):
        if bucket_key not in self._buckets:
            sym, data_names, label_names = self._sym_gen(bucket_key)
            mod = Module(sym, data_names, label_names, self.logger,
                         self._context, **self._kwargs)
            mod.bind(data_shapes, label_shapes, self.for_training)
            if self._shared_params is not None:
                mod.init_params(None, *self._shared_params, allow_missing=True,
                                force_init=True)
            self._buckets[bucket_key] = mod
        return self._buckets[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             **kwargs):
        self.for_training = for_training
        self._curr_module = self._get_module(self._default_bucket_key,
                                             data_shapes, label_shapes)
        self.binded = True

    def init_params(self, *args, **kwargs):
        self._curr_module.init_params(*args, **kwargs)
        self._shared_params = self._curr_module.get_params()
        self.params_initialized = True

    def init_optimizer(self, *args, **kwargs):
        self._curr_module.init_optimizer(*args, **kwargs)
        self._opt_args = (args, kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key
        prev = self._curr_module
        module = self._get_module(key if key is not None
                                  else self._default_bucket_key,
                                  data_batch.provide_data or
                                  [(n, a.shape) for n, a in
                                   zip(self._curr_module._data_names,
                                       data_batch.data)],
                                  data_batch.provide_label or
                                  ([(n, a.shape) for n, a in
                                    zip(self._curr_module._label_names,
                                        data_batch.label)]
                                   if data_batch.label else None))
        if module is not prev:
            arg, aux = prev.get_params()
            if not module.params_initialized:
                module.init_params(None, arg, aux, allow_missing=True,
                                   force_init=True)
            else:
                module.set_params(arg, aux)
            if self.optimizer_initialized and not module.optimizer_initialized:
                module.init_optimizer(*self._opt_args[0], **self._opt_args[1])
            module._updater_states = prev._updater_states
            module._optimizer = prev._optimizer
        self._curr_module = module
        module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs()

    def get_params(self):
        return self._curr_module.get_params()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)
