"""``mx.mod`` (reference: ``python/mxnet/module/``)."""

from .module import (  # noqa: F401
    BaseModule,
    Module,
    BucketingModule,
    save_checkpoint,
    load_checkpoint,
)
