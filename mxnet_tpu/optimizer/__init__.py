"""``mx.optimizer`` (reference: ``python/mxnet/optimizer/``)."""

from .optimizer import (  # noqa: F401
    Optimizer,
    SGD,
    NAG,
    Signum,
    Adam,
    AdamW,
    AdaGrad,
    AdaDelta,
    RMSProp,
    Ftrl,
    FTML,
    LARS,
    LAMB,
    DCASGD,
    SGLD,
    Updater,
    get_updater,
    create,
    register,
)
