"""Optimizers.

Reference: ``python/mxnet/optimizer/optimizer.py`` + the fused update kernels
in ``src/operator/optimizer_op*`` (symbols ``sgd_update``, ``adam_update``,
``mp_sgd_update``, ``multi_sgd``...).

TPU-native: each update rule is one jitted XLA function taking (weight, grad,
*state, lr, wd) as device arrays — the analog of the reference's fused
kernels, with multi-precision (fp32 master weights) supported the same way.
Scalars (lr/wd) are passed as arrays to avoid retracing per step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

_OPT_REGISTRY = {}


def register(klass):
    _OPT_REGISTRY[klass.__name__.lower()] = klass
    return klass


@functools.lru_cache(maxsize=None)
def _jit(fn, static_items):
    kw = dict(static_items)
    return jax.jit(lambda *a: fn(*a, **kw))


class Optimizer:
    """Base optimizer (reference: ``Optimizer.create_optimizer`` registry)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    # -- registry ---------------------------------------------------------
    @staticmethod
    def register(klass):
        return register(klass)

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() not in _OPT_REGISTRY:
            raise MXNetError(f"unknown optimizer {name}")
        return _OPT_REGISTRY[name.lower()](**kwargs)

    # -- lr/wd ------------------------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is set; cannot set learning_rate")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- state ------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    @staticmethod
    def _is_low_precision(weight) -> bool:
        """Dtypes that get an fp32 master under ``multi_precision``:
        float16 (the reference's case) AND bfloat16 — the TPU-native
        low-precision dtype needs masters for the same reason (8
        mantissa bits lose small updates to rounding)."""
        from ..amp.policy import is_low_precision_dtype

        return is_low_precision_dtype(weight.dtype)

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and self._is_low_precision(weight):
            master = NDArray(weight.data.astype(jnp.float32), ctx=weight.ctx)
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    # -- update -----------------------------------------------------------
    def _preprocess(self, grad):
        g = grad.data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and self._is_low_precision(weight):
            master, st = state
            g32 = NDArray(grad.data.astype(jnp.float32), ctx=grad.ctx)
            self.update(index, master, g32, st)
            weight._set_data(master.data.astype(weight.data.dtype))
        else:
            self.update(index, weight, grad, state)


create = Optimizer.create_optimizer


@register
class SGD(Optimizer):
    """SGD with momentum (reference kernels: ``sgd_update``/``sgd_mom_update``).

    state = momentum buffer; update matches the reference formula:
    ``mom = momentum*mom - lr*(grad + wd*weight); weight += mom``.
    """

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad)
        w = weight.data
        if state is None:
            weight._set_data(w - lr * (g + wd * w.astype(g.dtype)).astype(w.dtype))
        else:
            mom = self.momentum * state.data - lr * (g + wd * w.astype(g.dtype))
            state._set_data(mom)
            weight._set_data(w + mom.astype(w.dtype))


@register
class NAG(SGD):
    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad) + wd * weight.data
        w = weight.data
        if state is None:
            weight._set_data(w - lr * g)
        else:
            mom = self.momentum * state.data + g
            state._set_data(mom)
            weight._set_data(w - lr * (g + self.momentum * mom))


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad)
        w = weight.data
        if state is not None:
            mom = self.momentum * state.data - (1 - self.momentum) * (g + wd * w)
            state._set_data(mom)
            weight._set_data((1 - lr * self.wd_lh) * w + lr * jnp.sign(mom))
        else:
            weight._set_data((1 - lr * self.wd_lh) * w - lr * jnp.sign(g + wd * w))


@register
class Adam(Optimizer):
    """Adam (reference kernel: ``adam_update``)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return (NDArray(z, ctx=weight.ctx), NDArray(z, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr_t = lr * (1.0 - self.beta2 ** t) ** 0.5 / (1.0 - self.beta1 ** t)
        g = self._preprocess(grad) + wd * weight.data
        m, v = state
        m_t = self.beta1 * m.data + (1 - self.beta1) * g
        v_t = self.beta2 * v.data + (1 - self.beta2) * jnp.square(g)
        m._set_data(m_t)
        v._set_data(v_t)
        weight._set_data(weight.data - lr_t * m_t / (jnp.sqrt(v_t) + self.epsilon))


@register
class AdamW(Adam):
    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr_t = lr * (1.0 - self.beta2 ** t) ** 0.5 / (1.0 - self.beta1 ** t)
        g = self._preprocess(grad)
        m, v = state
        m_t = self.beta1 * m.data + (1 - self.beta1) * g
        v_t = self.beta2 * v.data + (1 - self.beta2) * jnp.square(g)
        m._set_data(m_t)
        v._set_data(v_t)
        weight._set_data(
            weight.data - lr_t * m_t / (jnp.sqrt(v_t) + self.epsilon)
            - lr * wd * weight.data
        )


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad) + wd * weight.data
        hist = state.data + jnp.square(g)
        state._set_data(hist)
        weight._set_data(weight.data - lr * g / (jnp.sqrt(hist) + self.float_stable_eps))


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return (NDArray(z, ctx=weight.ctx), NDArray(z, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = self._preprocess(grad) + wd * weight.data
        acc_g, acc_delta = state
        ag = self.rho * acc_g.data + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(acc_delta.data + self.epsilon) / jnp.sqrt(ag + self.epsilon) * g
        ad = self.rho * acc_delta.data + (1 - self.rho) * jnp.square(delta)
        acc_g._set_data(ag)
        acc_delta._set_data(ad)
        weight._set_data(weight.data - delta)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        if self.centered:
            return (NDArray(z, ctx=weight.ctx), NDArray(z, ctx=weight.ctx),
                    NDArray(z, ctx=weight.ctx))
        return (NDArray(z, ctx=weight.ctx),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad) + wd * weight.data
        if not self.centered:
            (n,) = state
            nv = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * n.data
            n._set_data(nv)
            w = weight.data - lr * g / jnp.sqrt(nv + self.epsilon)
        else:
            n, gmean, delta = state
            nv = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * n.data
            gv = (1 - self.gamma1) * g + self.gamma1 * gmean.data
            dv = self.gamma2 * delta.data - lr * g / jnp.sqrt(nv - jnp.square(gv) + self.epsilon)
            n._set_data(nv)
            gmean._set_data(gv)
            delta._set_data(dv)
            w = weight.data + dv
        if self.clip_weights:
            w = jnp.clip(w, -self.clip_weights, self.clip_weights)
        weight._set_data(w)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return (NDArray(z, ctx=weight.ctx), NDArray(z, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad)
        z, n = state
        sigma = (jnp.sqrt(n.data + jnp.square(g)) - jnp.sqrt(n.data)) / lr
        zv = z.data + g - sigma * weight.data
        nv = n.data + jnp.square(g)
        z._set_data(zv)
        n._set_data(nv)
        new_w = jnp.where(
            jnp.abs(zv) <= self.lamda1,
            jnp.zeros_like(zv),
            -(zv - jnp.sign(zv) * self.lamda1)
            / ((self.beta + jnp.sqrt(nv)) / lr + wd),
        )
        weight._set_data(new_w)


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return tuple(NDArray(z, ctx=weight.ctx) for _ in range(3))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        g = self._preprocess(grad) + wd * weight.data
        d, v, zs = state
        vv = self.beta2 * v.data + (1 - self.beta2) * jnp.square(g)
        d_t = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(vv / (1 - self.beta2 ** t)) + self.epsilon
        )
        sigma = d_t - self.beta1 * d.data
        zv = self.beta1 * zs.data + (1 - self.beta1) * g - sigma * weight.data
        v._set_data(vv)
        d._set_data(d_t)
        zs._set_data(zv)
        weight._set_data(-zv / d_t)


@register
class LARS(SGD):
    """Layer-wise adaptive rate scaling (reference: ``lars_*`` kernels)."""

    def __init__(self, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.eta = eta
        self.epsilon = epsilon

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad)
        w = weight.data
        w_norm = jnp.linalg.norm(w)
        g_norm = jnp.linalg.norm(g)
        ratio = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon),
            1.0,
        )
        lr_eff = lr * ratio
        if state is None:
            weight._set_data(w - lr_eff * (g + wd * w))
        else:
            mom = self.momentum * state.data - lr_eff * (g + wd * w)
            state._set_data(mom)
            weight._set_data(w + mom)


@register
class LAMB(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return (NDArray(z, ctx=weight.ctx), NDArray(z, ctx=weight.ctx))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        g = self._preprocess(grad)
        m, v = state
        m_t = self.beta1 * m.data + (1 - self.beta1) * g
        v_t = self.beta2 * v.data + (1 - self.beta2) * jnp.square(g)
        m._set_data(m_t)
        v._set_data(v_t)
        if self.bias_correction:
            m_hat = m_t / (1 - self.beta1 ** t)
            v_hat = v_t / (1 - self.beta2 ** t)
        else:
            m_hat, v_hat = m_t, v_t
        r = m_hat / (jnp.sqrt(v_hat) + self.epsilon) + wd * weight.data
        w_norm = jnp.linalg.norm(weight.data)
        r_norm = jnp.linalg.norm(r)
        if self.lower_bound is not None:
            w_norm = jnp.maximum(w_norm, self.lower_bound)
        if self.upper_bound is not None:
            w_norm = jnp.minimum(w_norm, self.upper_bound)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        weight._set_data(weight.data - lr * ratio * r)


@register
class DCASGD(Optimizer):
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return (
            None if self.momentum == 0.0 else NDArray(z, ctx=weight.ctx),
            NDArray(weight.data, ctx=weight.ctx),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad)
        mom, prev = state
        delta = -lr * (
            g + wd * weight.data
            + self.lamda * g * g * (weight.data - prev.data)
        )
        if mom is not None:
            delta = self.momentum * mom.data + delta
            mom._set_data(delta)
        prev._set_data(weight.data)
        weight._set_data(weight.data + delta)


@register
class SGLD(Optimizer):
    def update(self, index, weight, grad, state):
        from .. import random as _random

        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess(grad) + wd * weight.data
        noise = jax.random.normal(_random._next_key(), weight.shape,
                                  weight.data.dtype) * jnp.sqrt(lr)
        weight._set_data(weight.data - lr / 2 * g + noise)


# Test/updater plumbing (reference: ``optimizer.py:get_updater``/``Updater``)


class Updater:
    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight
            )
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        import pickle

        return pickle.dumps(self.states)

    def set_states(self, states):
        import pickle

        self.states = pickle.loads(states)


@register
class GroupAdaGrad(Optimizer):
    """AdaGrad with one accumulator PER ROW (reference:
    ``contrib/optimizer.py`` ``GroupAdaGrad`` over
    ``_contrib_group_adagrad_update`` — the sparse-embedding optimizer:
    a row's whole history updates together, which keeps row_sparse
    gradients cheap). Weight decay is unsupported, as in the reference
    (which asserts wd == 0)."""

    def __init__(self, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        if weight.data.ndim < 1:
            raise ValueError("GroupAdaGrad needs >= 1-dim weights")
        return NDArray(jnp.zeros((weight.shape[0],), jnp.float32),
                       ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        if self._get_wd(index) != 0.0:
            raise MXNetError("GroupAdaGrad does not support weight decay "
                             "(reference contract: wd must be 0)")
        g = self._preprocess(grad)
        reduce_axes = tuple(range(1, g.ndim))
        hist = state.data + jnp.mean(jnp.square(g), axis=reduce_axes)             if g.ndim > 1 else state.data + jnp.square(g)
        state._set_data(hist)
        # reference kernel: div = sqrt(hist + eps), NOT sqrt(hist) + eps
        div = jnp.sqrt(hist + self.float_stable_eps)
        shape = (-1,) + (1,) * (g.ndim - 1)
        w = weight.data
        weight._set_data(
            (w - lr * g / div.reshape(shape).astype(g.dtype)).astype(w.dtype))


@register
class LBSGD(Optimizer):
    """Large-Batch SGD with layer-wise adaptive rate scaling (reference:
    ``optimizer.py`` ``LBSGD`` — LARS-style trust ratio + warmup for
    large-batch training)."""

    def __init__(self, momentum=0.0, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32,
                 begin_epoch=0, num_epochs=60, **kwargs):
        # multi_precision rides **kwargs into Optimizer.__init__ so the
        # fp32-master-weight machinery engages like every other optimizer
        super().__init__(**kwargs)
        self.momentum = momentum
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = max(batch_scale, 1)
        self.updates_per_epoch = max(updates_per_epoch, 1)
        self.init_updates = begin_epoch * self.updates_per_epoch
        self.num_epochs = num_epochs

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, jnp.float32), ctx=weight.ctx)

    def _warmup_scale(self, nup):
        """Ramp the lr multiplier from 1 to ``batch_scale`` over the
        warmup (the point of large-batch SGD: linear-scaled lr reached
        gradually), then hold at batch_scale."""
        total_warm = self.warmup_epochs * self.updates_per_epoch
        if total_warm <= 0 or nup >= total_warm:
            return float(self.batch_scale)
        frac = nup / total_warm
        if self.warmup_strategy == "power2":
            frac = frac ** 2
        elif self.warmup_strategy == "sqrt":
            frac = frac ** 0.5
        return 1.0 + (self.batch_scale - 1.0) * frac if self.batch_scale > 1             else max(frac, 1.0 / total_warm)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        nup = self._index_update_count.get(index, 1) + self.init_updates
        g = self._preprocess(grad).astype(jnp.float32)
        w32 = weight.data.astype(jnp.float32)
        # LARS trust ratio, fully on device (no host syncs in the step)
        wnorm = jnp.linalg.norm(w32)
        gnorm = jnp.linalg.norm(g)
        lars = jnp.where((wnorm > 0) & (gnorm > 0),
                         jnp.minimum(wnorm / (gnorm + wd * wnorm + 1e-9),
                                     2.0),  # reference clips the ratio
                         1.0)
        eff_lr = lr * self._warmup_scale(nup) * lars
        g = g + wd * w32
        if self.momentum and state is not None:
            m = self.momentum * state.data - eff_lr * g
            state._set_data(m)
            weight._set_data((w32 + m).astype(weight.data.dtype))
        else:
            weight._set_data((w32 - eff_lr * g).astype(weight.data.dtype))


def get_updater(optimizer):
    return Updater(optimizer)
