"""Pipeline parallelism (P10): GPipe / 1F1B / interleaved-1F1B schedules
over a ``pp`` mesh axis.

No reference counterpart (SURVEY.md §2.5 P10 — "does not exist in the
reference"; previously a documented drop). TPU-native design per the
public scaling-book recipe: stages live on devices along the ``pp`` axis
(stage parameters stacked on a leading axis, sharded over ``pp``);
activations hop stage-to-stage with ``lax.ppermute`` riding ICI.

Three schedules, all realized from ONE dependency-simulated tick table
(:func:`build_pipeline_schedule`), so the reported ``bubble_fraction``
is measured from the realized table, not a formula:

- ``gpipe`` — fill-drain: all M forwards, then all M backwards.
  Bubble (S-1)/(M+S-1); the activation stash grows with M (every
  in-flight microbatch's input is held until its backward).
- ``1f1b`` — same bubble as gpipe at the same microbatch count (the
  warmup/drain ramps are identical — that is arithmetic, not an
  implementation artifact), but the steady state interleaves one
  backward after each forward so at most ~S activations are ever
  stashed: the MEMORY schedule. ``stash_slots`` exposes the win.
- ``interleaved`` — 1F1B over v virtual stage chunks per rank
  (stage g lives on rank g mod S), which divides the fill/drain ramp
  by v: bubble ~ ((S-1)/v)/(M + (S-1)/v). The LATENCY schedule, and
  the one that clears the >= 90% pipeline-overlap gate.

The backward is schedule-driven (not autodiff-transposed): each
backward tick recomputes its stage from the stashed input via
``jax.vjp`` (remat semantics) and hands the cotangent to the previous
stage with the reverse ``ppermute`` ring. The legacy fill-drain
``pipeline_apply`` (autodiff through the forward loop) is kept as the
``gpipe`` train-step path and for inference.
"""

from __future__ import annotations

import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError

#: schedule tick tables are built once per (name, S, M, v) — the build
#: is host-side simulation, cached because train steps, probes and
#: gauges all ask for the same table
_SCHEDULE_CACHE = {}
_CACHE_LOCK = threading.Lock()

_GUARDED_BY = {"_SCHEDULE_CACHE": "_CACHE_LOCK"}


def pipeline_apply(stage_fn, stage_params, x, mesh, axis_name="pp",
                   num_microbatches=None):
    """Apply ``S`` pipelined stages to ``x`` (fill-drain forward).

    stage_fn(params_one_stage, activation) -> activation (same shape);
    stage_params: pytree whose leaves carry a leading stage axis of size
    S (sharded over ``axis_name``); x: (B, ...) global batch, B divisible
    by num_microbatches. Returns the (B, ...) output of the last stage.
    """
    S = mesh.shape[axis_name]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != S:
            raise MXNetError(
                f"stage axis {leaf.shape[0]} != mesh {axis_name}={S}: "
                "each device must hold exactly one stage")
    M = num_microbatches or S
    B = x.shape[0]
    if B % M:
        raise MXNetError(
            f"num_microbatches {M} must divide the batch size {B}")
    mb = B // M
    xs = x.reshape(M, mb, *x.shape[1:])

    def per_stage(params_local, xs_local):
        # params_local: (1, ...) this device's stage slice
        params_one = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage = lax.axis_index(axis_name)

        def _vary(v):  # mark as varying over pp (shard_map vma check)
            if hasattr(lax, "pcast"):
                return lax.pcast(v, (axis_name,), to="varying")
            return v  # pragma: no cover (older jax)

        state = _vary(jnp.zeros_like(xs_local[0]))   # in-flight activation
        outputs = _vary(jnp.zeros_like(xs_local))    # filled by last stage
        fwd = [(i, (i + 1) % S) for i in range(S)]

        for t in range(M + S - 1):
            # stage 0 ingests microbatch t; everyone else uses the state
            # handed over from the previous stage
            feed = xs_local[jnp.minimum(t, M - 1)]
            inp = jnp.where(stage == 0, feed, state)
            out = stage_fn(params_one, inp)
            # last stage banks microbatch t-(S-1)
            oidx = t - (S - 1)
            live = (oidx >= 0) & (stage == S - 1)
            banked = outputs.at[jnp.clip(oidx, 0, M - 1)].set(out)
            outputs = jnp.where(live, banked, outputs)
            # hand the activation to the next stage
            state = lax.ppermute(out, axis_name, fwd)
        # activations circulate back to stage 0 from the last hop; only
        # the last stage's banked outputs matter — broadcast them so the
        # (replicated) output spec is consistent
        outputs = lax.psum(
            jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    from .compat import get_shard_map
    shard_map = get_shard_map()

    spec_params = jax.tree_util.tree_map(
        lambda _: P(axis_name), stage_params)
    fn = shard_map(per_stage, mesh=mesh,
                   in_specs=(spec_params, P()),
                   out_specs=P())
    ys = fn(stage_params, xs)
    return ys.reshape(B, *x.shape[1:])


def stack_stage_params(per_stage_params):
    """[pytree_per_stage, ...] -> one pytree with a leading stage axis."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def shard_stages(stacked, mesh, axis_name="pp"):
    """Place stacked stage params with the stage axis over ``pp``."""
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(
            leaf, NamedSharding(mesh, P(axis_name))), stacked)


# ---------------------------------------------------------------------------
# schedule tables: dependency-simulated tick programs
# ---------------------------------------------------------------------------


def stage_permutation(num_ranks, virtual):
    """Stacked position -> global stage, rank-major chunk layout.

    Position ``p = r*v + c`` (rank r's c-th local chunk) holds global
    stage ``g = c*S + r`` — so sharding the permuted stack over ``pp``
    gives rank r exactly its interleaved chunks, and every forward hop
    g -> g+1 is the uniform +1 ring (rank S-1 wraps to rank 0 at chunk
    boundaries)."""
    S, v = num_ranks, virtual
    return [ (p % v) * S + (p // v) for p in range(S * v) ]


class PipelineSchedule:
    """A realized pipeline schedule: per-tick work tables + measured
    bubble. Built by :func:`build_pipeline_schedule`; consumed by the
    schedule executor and the bubble probe/gauges."""

    def __init__(self, name, num_ranks, num_microbatches, virtual,
                 ticks, tables, stash_slots, bstash_slots):
        self.name = name
        self.num_ranks = num_ranks
        self.num_microbatches = num_microbatches
        self.virtual = virtual
        self.num_stages = num_ranks * virtual
        self.ticks = ticks
        self.tables = tables
        #: peak live forward-activation stash entries on any rank — the
        #: 1F1B memory win vs gpipe is this number (S vs M)
        self.stash_slots = stash_slots
        self.bstash_slots = bstash_slots
        busy = 2 * num_microbatches * virtual  # F+B units per rank
        #: measured from the realized table: fraction of (rank, tick)
        #: slots with no scheduled work
        self.bubble_fraction = 1.0 - busy / float(ticks)

    def report(self):
        return {"schedule": self.name, "ranks": self.num_ranks,
                "virtual": self.virtual,
                "microbatches": self.num_microbatches,
                "ticks": self.ticks,
                "bubble_fraction": round(self.bubble_fraction, 6),
                "stash_slots": self.stash_slots}


def _rank_order(name, S, v, M, r):
    """This rank's work order: the classic per-rank sequences."""
    L = S * v
    if name == "gpipe":
        return ([("F", r, m) for m in range(M)] +
                [("B", r, m) for m in reversed(range(M))])
    if name == "1f1b":
        W = min(M, S - 1 - r)
        order = [("F", r, m) for m in range(W)]
        for i in range(M - W):
            order.append(("F", r, W + i))
            order.append(("B", r, i))
        order += [("B", r, i) for i in range(M - W, M)]
        return order
    if name == "interleaved":
        if M % S:
            raise MXNetError(
                f"interleaved schedule needs microbatches ({M}) to be a "
                f"multiple of the pp axis ({S})")
        total = M * v

        def fwd_unit(k):
            rnd, within = divmod(k, S * v)
            return ("F", (within // S) * S + r, rnd * S + within % S)

        def bwd_unit(j):
            rnd, within = divmod(j, S * v)
            c = v - 1 - within // S
            return ("B", c * S + r, rnd * S + within % S)

        W = min(total, (v - 1) * S + 2 * (S - r - 1) + 1)
        order = [fwd_unit(k) for k in range(W)]
        for i in range(total - W):
            order.append(fwd_unit(W + i))
            order.append(bwd_unit(i))
        order += [bwd_unit(j) for j in range(total - W, total)]
        return order
    raise MXNetError(f"unknown pipeline schedule {name!r} "
                     "(gpipe | 1f1b | interleaved)")


class _Slots:
    """Greedy interval slot allocator (per rank): reuse a slot whose
    previous tenant was last read strictly before the new deposit."""

    def __init__(self):
        self.ends = []  # slot -> last read tick of current tenant

    def alloc(self, start, end):
        for i, e in enumerate(self.ends):
            if e <= start:  # last read happens before the new deposit
                self.ends[i] = end
                return i
        self.ends.append(end)
        return len(self.ends) - 1

    @property
    def n(self):
        return len(self.ends)


def build_pipeline_schedule(num_ranks, num_microbatches, name="gpipe",
                            virtual=1):
    """Simulate ``name`` over S ranks / M microbatches / v virtual
    chunks and return the realized :class:`PipelineSchedule`.

    The simulator walks the classic per-rank work orders tick by tick,
    releasing each unit only when its producer finished on an earlier
    tick (cross-rank messages ride the end-of-tick ppermute) — so the
    table, its bubble fraction, and the stash liveness are measured
    properties of the realized schedule.
    """
    key = (name, int(num_ranks), int(num_microbatches), int(virtual))
    with _CACHE_LOCK:
        hit = _SCHEDULE_CACHE.get(key)
    if hit is not None:
        return hit

    S, M, v = int(num_ranks), int(num_microbatches), int(virtual)
    L = S * v
    if name != "interleaved" and v != 1:
        raise MXNetError(f"schedule {name!r} runs one stage per rank; "
                         f"got {L} stages on {S} ranks — use "
                         "schedule='interleaved' for virtual chunks")
    orders = [_rank_order(name, S, v, M, r) for r in range(S)]
    done = {}
    ptr = [0] * S
    exec_at = {}  # (kind, g, m) -> (tick, rank)
    t, limit = 0, 4 * (2 * M * L + L + S) + 16
    while any(ptr[r] < len(orders[r]) for r in range(S)):
        for r in range(S):
            if ptr[r] >= len(orders[r]):
                continue
            kind, g, m = orders[r][ptr[r]]
            if kind == "F":
                dep = None if g == 0 else ("F", g - 1, m)
            else:
                dep = ("F", L - 1, m) if g == L - 1 else ("B", g + 1, m)
            if dep is None or done.get(dep, limit) < t:
                done[(kind, g, m)] = t
                exec_at[(kind, g, m)] = (t, r)
                ptr[r] += 1
        t += 1
        if t > limit:  # pragma: no cover - schedule bug guard
            raise MXNetError(f"pipeline schedule {name!r} deadlocked "
                             f"(S={S}, M={M}, v={v})")
    T = t

    cols = ("f_on f_mb f_chunk f_src f_slot bank_on bank_mb "
            "b_on b_mb b_chunk b_src b_slot bx_src bx_slot "
            "rf_on rf_slot rb_on rb_slot").split()
    tbl = {c: np.zeros((T, S), np.int32) for c in cols}
    fslots = [_Slots() for _ in range(S)]
    bslots = [_Slots() for _ in range(S)]

    for (kind, g, m), (tick, r) in sorted(exec_at.items(),
                                          key=lambda kv: kv[1]):
        c = g // S
        if kind == "F":
            tbl["f_on"][tick, r] = 1
            tbl["f_mb"][tick, r] = m
            tbl["f_chunk"][tick, r] = c
            if g == L - 1:
                tbl["bank_on"][tick, r] = 1
                tbl["bank_mb"][tick, r] = m
            if g > 0:
                arrive = done[("F", g - 1, m)]
                last_read = exec_at[("B", g, m)][0]
                slot = fslots[r].alloc(arrive, last_read)
                tbl["rf_on"][arrive, r] = 1
                tbl["rf_slot"][arrive, r] = slot
                tbl["f_src"][tick, r] = 1
                tbl["f_slot"][tick, r] = slot
                tbl["bx_src"][exec_at[("B", g, m)][0], r] = 1
                tbl["bx_slot"][exec_at[("B", g, m)][0], r] = slot
        else:
            tbl["b_on"][tick, r] = 1
            tbl["b_mb"][tick, r] = m
            tbl["b_chunk"][tick, r] = c
            if g < L - 1:
                arrive = done[("B", g + 1, m)]
                slot = bslots[r].alloc(arrive, tick)
                tbl["rb_on"][arrive, r] = 1
                tbl["rb_slot"][arrive, r] = slot
                tbl["b_src"][tick, r] = 1
                tbl["b_slot"][tick, r] = slot

    n_f = max((s.n for s in fslots), default=0)
    n_b = max((s.n for s in bslots), default=0)
    # idle rows point their slot reads/deposits at the scratch slot
    for slot_col, on_col in (("f_slot", "f_on"), ("b_slot", "b_on"),
                             ("bx_slot", "b_on"), ("rf_slot", "rf_on"),
                             ("rb_slot", "rb_on")):
        scratch = n_f if slot_col in ("f_slot", "bx_slot", "rf_slot") \
            else n_b
        tbl[slot_col][tbl[on_col] == 0] = scratch
    sched = PipelineSchedule(name, S, M, v, T, tbl, n_f, n_b)
    with _CACHE_LOCK:
        _SCHEDULE_CACHE[key] = sched
    return sched


def measure_pipeline_bubble(num_ranks, num_microbatches, virtual=2,
                            schedules=("gpipe", "1f1b", "interleaved")):
    """Realize each schedule's tick table at this config and publish
    the measured bubble fractions + stash depths (the pipeline analog
    of ``measure_overlap``). Returns {schedule: report dict}."""
    out = {}
    for name in schedules:
        v = virtual if name == "interleaved" else 1
        sched = build_pipeline_schedule(num_ranks, num_microbatches,
                                        name, virtual=v)
        out[name] = sched.report()
        from .. import observability as _obs
        _obs.record_pipeline_schedule(name, sched.bubble_fraction,
                                      sched.stash_slots,
                                      ticks=sched.ticks)
    return out


# ---------------------------------------------------------------------------
# schedule executor: one uniform SPMD tick program
# ---------------------------------------------------------------------------


def _run_schedule(stage_fn, loss_fn, sched, axis_name, params_local,
                  xs, ys, head_fn=None, head_params=None,
                  embed_fn=None, embed_params=None):
    """Run one fwd+bwd pass of ``sched`` (inside shard_map over
    ``axis_name``). ``params_local``: leaves [v, ...] (this rank's
    chunks); ``xs``/``ys``: [M, mb, ...] microbatched batch (replicated
    over pp). Optional ``embed_fn(embed_params, x_mb)`` feeds stage 0
    (token embedding — re-applied at stage-0 backward ticks for its
    vjp) and ``head_fn(head_params, h)`` sits between the last stage
    and the loss (folded into the loss seed's vjp). Returns
    (loss, grads_local, {"head": g or None, "embed": g or None}).

    Per tick: at most one forward (reading its input from the feed or
    the activation stash) and one backward (recomputing its stage from
    the stashed input via ``jax.vjp``, seeding from the loss at the
    last stage), then one +1-ring ppermute of activations and one
    -1-ring ppermute of cotangents. Slot/chunk/microbatch indices come
    from the schedule's host-built tables (indexed by this rank's axis
    position), so the traced program is identical on every rank — ticks
    where no rank forwards (or none backwards) skip that half entirely.
    """
    S, v, M, T = (sched.num_ranks, sched.virtual,
                  sched.num_microbatches, sched.ticks)
    tbl = sched.tables
    rank = lax.axis_index(axis_name)
    if embed_fn is None:
        act_shape = xs.shape[1:]
        act_dtype = xs.dtype
    else:
        a0 = jax.eval_shape(embed_fn, embed_params,
                            jax.eval_shape(lambda a: a[0], xs))
        act_shape, act_dtype = a0.shape, a0.dtype

    def _vary(val):
        if hasattr(lax, "pcast"):
            return lax.pcast(val, (axis_name,), to="varying")
        return val  # pragma: no cover (older jax)

    def row(col, t):  # this rank's entry of a [T, S] host table
        return _vary(jnp.asarray(tbl[col][t]))[rank]

    def pick(arr, idx):
        return lax.dynamic_index_in_dim(arr, idx, 0, keepdims=False)

    def put_if(arr, val, idx, on):
        cur = pick(arr, idx)
        return lax.dynamic_update_index_in_dim(
            arr, jnp.where(on, val, cur), idx, 0)

    def chunk_of(idx):
        return jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, idx, 0, keepdims=False),
            params_local)

    def feed(m):
        xm = pick(xs, m)
        if embed_fn is None:
            return xm.astype(act_dtype)
        return embed_fn(embed_params, xm).astype(act_dtype)

    stash = _vary(jnp.zeros((sched.stash_slots + 1,) + act_shape,
                            act_dtype))
    bstash = _vary(jnp.zeros((sched.bstash_slots + 1,) + act_shape,
                             act_dtype))
    out_bank = _vary(jnp.zeros((M,) + act_shape, act_dtype))
    grads = jax.tree_util.tree_map(jnp.zeros_like, params_local)
    if head_params is not None:
        head_grads = jax.tree_util.tree_map(jnp.zeros_like, head_params)
    if embed_params is not None:
        embed_grads = jax.tree_util.tree_map(jnp.zeros_like,
                                             embed_params)
    loss_acc = jnp.zeros((), jnp.float32)
    fwd_ring = [(i, (i + 1) % S) for i in range(S)]
    bwd_ring = [(i, (i - 1) % S) for i in range(S)]
    inv_m = jnp.asarray(1.0 / M, jnp.float32)

    def seed_of(out_m, y_m):
        """Loss value + cotangent at the last stage (head folded in)."""
        if head_fn is not None:
            def lf(o, hp):
                return loss_fn(head_fn(hp, o), y_m)
            val, vjp = jax.vjp(lf, out_m, head_params)
            g_o, g_h = vjp(inv_m.astype(val.dtype))
            return val, g_o.astype(act_dtype), g_h
        val, vjp = jax.vjp(lambda o: loss_fn(o, y_m), out_m)
        (g_o,) = vjp(inv_m.astype(val.dtype))
        return val, g_o.astype(act_dtype), None

    for t in range(T):
        any_f = bool(tbl["f_on"][t].any())
        any_b = bool(tbl["b_on"][t].any())
        any_rf = bool(tbl["rf_on"][t].any())
        any_rb = bool(tbl["rb_on"][t].any())

        f_out = None
        if any_f:
            f_mb = row("f_mb", t)
            f_in = jnp.where(row("f_src", t) == 0, feed(f_mb),
                             pick(stash, row("f_slot", t)))
            f_out = stage_fn(chunk_of(row("f_chunk", t)), f_in)
            if tbl["bank_on"][t].any():
                out_bank = put_if(out_bank, f_out, row("bank_mb", t),
                                  row("bank_on", t) == 1)

        b_msg = None
        if any_b:
            b_mb = row("b_mb", t)
            b_live = row("b_on", t) == 1
            y_m = pick(ys, b_mb)
            if bool((tbl["b_on"][t] & (tbl["b_src"][t] == 0)).any()):
                loss_m, g_seed, g_head = seed_of(pick(out_bank, b_mb),
                                                 y_m)
                seed_live = b_live & (row("b_src", t) == 0)
                loss_acc = loss_acc + jnp.where(
                    seed_live, loss_m.astype(jnp.float32), 0.0) * inv_m
                if head_params is not None and g_head is not None:
                    w = jnp.where(seed_live, 1.0, 0.0)
                    head_grads = jax.tree_util.tree_map(
                        lambda acc, g: acc + w.astype(g.dtype) * g,
                        head_grads, g_head)
                g_out = jnp.where(seed_live, g_seed,
                                  pick(bstash, row("b_slot", t)))
            else:
                g_out = pick(bstash, row("b_slot", t))
            feeds_here = bool(
                (tbl["b_on"][t] & (tbl["bx_src"][t] == 0)).any())
            evjp = None
            if embed_fn is not None and feeds_here:
                bx0, evjp = jax.vjp(
                    lambda ep: embed_fn(ep, pick(xs, b_mb)).astype(
                        act_dtype), embed_params)
            else:
                bx0 = feed(b_mb) if feeds_here else None
            bx = pick(stash, row("bx_slot", t))
            if bx0 is not None:
                bx = jnp.where(row("bx_src", t) == 0, bx0, bx)
            _, stage_vjp = jax.vjp(stage_fn, chunk_of(row("b_chunk", t)),
                                   bx)
            g_p, g_in = stage_vjp(g_out.astype(act_dtype))
            if evjp is not None:
                feed_live = b_live & (row("bx_src", t) == 0)
                g_feed = jnp.where(feed_live, g_in,
                                   jnp.zeros_like(g_in))
                (g_emb,) = evjp(g_feed)
                embed_grads = jax.tree_util.tree_map(
                    lambda acc, g: acc + g, embed_grads, g_emb)
            oh = (jnp.arange(v) == row("b_chunk", t))
            oh = jnp.where(b_live, oh, jnp.zeros_like(oh))
            grads = jax.tree_util.tree_map(
                lambda acc, g: acc + oh.astype(g.dtype).reshape(
                    (v,) + (1,) * g.ndim) * g[None],
                grads, g_p)
            b_msg = g_in

        if any_rf:
            recv_f = lax.ppermute(
                f_out if f_out is not None
                else jnp.zeros(act_shape, act_dtype), axis_name, fwd_ring)
            stash = put_if(stash, recv_f, row("rf_slot", t),
                           row("rf_on", t) == 1)
        if any_rb:
            recv_b = lax.ppermute(
                b_msg if b_msg is not None
                else jnp.zeros(act_shape, act_dtype), axis_name, bwd_ring)
            bstash = put_if(bstash, recv_b, row("rb_slot", t),
                            row("rb_on", t) == 1)

    loss = lax.psum(loss_acc, axis_name)
    aux = {"head": None, "embed": None}
    if head_params is not None:
        aux["head"] = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name), head_grads)
    if embed_params is not None:
        aux["embed"] = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name), embed_grads)
    return loss, grads, aux


def _microbatch(x, y, M):
    B = x.shape[0]
    if B % M:
        raise MXNetError(
            f"num_microbatches {M} must divide the batch size {B}")
    mb = B // M
    return (x.reshape(M, mb, *x.shape[1:]),
            y.reshape(M, mb, *y.shape[1:]))


def _amp_wrap(stage_fn, amp_dtype):
    """bf16 compute wrapper: params + activation cast down for the
    stage matmuls, output restored to the fp32 hop/stash dtype."""
    if not amp_dtype:
        return stage_fn
    dt = jnp.dtype(amp_dtype)

    def wrapped(params_one, h):
        lo = jax.tree_util.tree_map(lambda p: p.astype(dt), params_one)
        return stage_fn(lo, h.astype(dt)).astype(jnp.float32)

    return wrapped


class PipelineTrainStep:
    """Pipelined training over the ``pp`` axis.

    ``schedule``: ``gpipe`` (default; fill-drain via autodiff — the
    legacy path), ``1f1b``, or ``interleaved`` (both run the manual
    tick-table executor; ``interleaved`` wants ``len(stages)`` to be a
    multiple of the pp axis, running v = L/S chunks per rank).
    ``optimizer``: any of the SPMD rule names (sgd, adam, ...).

    >>> step = PipelineTrainStep(stage_fn, stage_params, mesh, loss_fn)
    >>> loss = step(x, y, lr=0.1)
    """

    def __init__(self, stage_fn, stage_params, mesh, loss_fn,
                 axis_name="pp", num_microbatches=None, schedule=None,
                 optimizer="sgd", optimizer_params=None, amp_dtype=None):
        from .. import fusedstep, observability as _obs
        from .spmd import _RULES

        self._mesh = mesh
        self._axis = axis_name
        S = mesh.shape[axis_name]
        L = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        schedule = schedule or fusedstep.pipeline_schedule()
        M = num_microbatches or fusedstep.pipeline_microbatches() or S
        if optimizer not in _RULES:
            raise MXNetError(f"pipeline step supports {sorted(_RULES)}; "
                             f"got {optimizer}")
        rule_init, rule_update = _RULES[optimizer](optimizer_params or {})
        fn = _amp_wrap(stage_fn, amp_dtype)

        if schedule == "gpipe":
            if L != S:
                raise MXNetError(
                    f"gpipe runs one stage per rank: {L} stages != "
                    f"{axis_name}={S} (use schedule='interleaved')")
            self.schedule = build_pipeline_schedule(S, M, "gpipe")
            self._params = shard_stages(stage_params, mesh, axis_name)
            self._opt = jax.tree_util.tree_map(rule_init, self._params)

            def train(params, opt, x, y, lr):
                def loss_of(p):
                    out = pipeline_apply(fn, p, x, mesh, axis_name, M)
                    return loss_fn(out, y)

                loss, grads = jax.value_and_grad(loss_of)(params)
                flat_p, tdef = jax.tree_util.tree_flatten(params)
                flat_g = tdef.flatten_up_to(grads)
                flat_o = tdef.flatten_up_to(opt)
                new_p, new_o = [], []
                for p, g, st in zip(flat_p, flat_g, flat_o):
                    p2, st2 = rule_update(p, g, st, lr)
                    new_p.append(p2)
                    new_o.append(st2)
                return (tdef.unflatten(new_p), tdef.unflatten(new_o),
                        loss)

            self._train = jax.jit(train, donate_argnums=(0, 1))
        else:
            if L % S:
                raise MXNetError(
                    f"{L} stages do not tile the {axis_name}={S} axis")
            v = L // S
            if schedule == "1f1b" and v != 1:
                raise MXNetError(
                    f"1f1b runs one stage per rank: {L} stages != "
                    f"{axis_name}={S} (use schedule='interleaved')")
            sched = build_pipeline_schedule(S, M, schedule, virtual=v)
            self.schedule = sched
            perm = stage_permutation(S, v)
            permuted = jax.tree_util.tree_map(
                lambda a: a[np.asarray(perm)], stage_params)
            self._params = shard_stages(permuted, mesh, axis_name)
            self._opt = jax.tree_util.tree_map(rule_init, self._params)

            from .compat import get_shard_map
            shard_map = get_shard_map()
            spec_p = jax.tree_util.tree_map(lambda _: P(axis_name),
                                            self._params)

            def body(params_block, opt_block, xs, ys, lr):
                # params_block leaves: [v, ...] local chunks
                loss, grads, _ = _run_schedule(
                    fn, loss_fn, sched, axis_name, params_block, xs, ys)
                flat_p, tdef = jax.tree_util.tree_flatten(params_block)
                flat_g = tdef.flatten_up_to(grads)
                flat_o = tdef.flatten_up_to(opt_block)
                new_p, new_o = [], []
                for p, g, st in zip(flat_p, flat_g, flat_o):
                    p2, st2 = rule_update(p, g, st, lr)
                    new_p.append(p2)
                    new_o.append(st2)
                return (tdef.unflatten(new_p), tdef.unflatten(new_o),
                        loss)

            # adam/lamb carry a scalar step counter: replicated, not
            # sharded over pp like the per-stage moment tensors
            spec_o = jax.tree_util.tree_map(
                lambda leaf: P(axis_name)
                if getattr(leaf, "ndim", 0) >= 1 else P(),
                self._opt)
            mapped = shard_map(
                body, mesh=mesh,
                in_specs=(spec_p, spec_o, P(), P(), P()),
                out_specs=(spec_p, spec_o, P()))

            def train(params, opt, x, y, lr):
                xs, ys = _microbatch(x, y, M)
                return mapped(params, opt, xs, ys, lr)

            self._train = jax.jit(train, donate_argnums=(0, 1))

        _obs.record_pipeline_schedule(
            self.schedule.name, self.schedule.bubble_fraction,
            self.schedule.stash_slots, ticks=self.schedule.ticks)

    def schedule_report(self):
        return self.schedule.report()

    def __call__(self, x, y, lr=0.01):
        def _raw(a):
            # mx ndarrays carry the device buffer as .data; a numpy
            # array's .data is a memoryview, not an array
            d = getattr(a, "data", None)
            return d if isinstance(d, jax.Array) else jnp.asarray(a)

        raw_x = _raw(x)
        raw_y = _raw(y)
        self._params, self._opt, loss = self._train(
            self._params, self._opt, raw_x, raw_y,
            jnp.asarray(lr, jnp.float32))
        return loss
