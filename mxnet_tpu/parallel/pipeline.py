"""Pipeline parallelism (P10): GPipe schedule over a ``pp`` mesh axis.

No reference counterpart (SURVEY.md §2.5 P10 — "does not exist in the
reference"; previously a documented drop). TPU-native design per the
public scaling-book recipe: stages live on devices along the ``pp`` axis
(stage parameters stacked on a leading axis, sharded over ``pp``);
activations hop stage-to-stage with ``lax.ppermute`` riding ICI; the
fill-drain (GPipe) schedule runs M microbatches in S + M - 1 ticks.

Everything is pure JAX, so ``jax.grad`` differentiates straight through
the schedule — the transpose of ``ppermute`` is the reverse permute, so
the backward pass is automatically the reverse pipeline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError


def pipeline_apply(stage_fn, stage_params, x, mesh, axis_name="pp",
                   num_microbatches=None):
    """Apply ``S`` pipelined stages to ``x``.

    stage_fn(params_one_stage, activation) -> activation (same shape);
    stage_params: pytree whose leaves carry a leading stage axis of size
    S (sharded over ``axis_name``); x: (B, ...) global batch, B divisible
    by num_microbatches. Returns the (B, ...) output of the last stage.
    """
    S = mesh.shape[axis_name]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != S:
            raise MXNetError(
                f"stage axis {leaf.shape[0]} != mesh {axis_name}={S}: "
                "each device must hold exactly one stage")
    M = num_microbatches or S
    B = x.shape[0]
    if B % M:
        raise MXNetError(
            f"num_microbatches {M} must divide the batch size {B}")
    mb = B // M
    xs = x.reshape(M, mb, *x.shape[1:])

    def per_stage(params_local, xs_local):
        # params_local: (1, ...) this device's stage slice
        params_one = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage = lax.axis_index(axis_name)

        def _vary(v):  # mark as varying over pp (shard_map vma check)
            if hasattr(lax, "pcast"):
                return lax.pcast(v, (axis_name,), to="varying")
            return v  # pragma: no cover (older jax)

        state = _vary(jnp.zeros_like(xs_local[0]))   # in-flight activation
        outputs = _vary(jnp.zeros_like(xs_local))    # filled by last stage
        fwd = [(i, (i + 1) % S) for i in range(S)]

        for t in range(M + S - 1):
            # stage 0 ingests microbatch t; everyone else uses the state
            # handed over from the previous stage
            feed = xs_local[jnp.minimum(t, M - 1)]
            inp = jnp.where(stage == 0, feed, state)
            out = stage_fn(params_one, inp)
            # last stage banks microbatch t-(S-1)
            oidx = t - (S - 1)
            live = (oidx >= 0) & (stage == S - 1)
            banked = outputs.at[jnp.clip(oidx, 0, M - 1)].set(out)
            outputs = jnp.where(live, banked, outputs)
            # hand the activation to the next stage
            state = lax.ppermute(out, axis_name, fwd)
        # activations circulate back to stage 0 from the last hop; only
        # the last stage's banked outputs matter — broadcast them so the
        # (replicated) output spec is consistent
        outputs = lax.psum(
            jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    from .compat import get_shard_map
    shard_map = get_shard_map()

    spec_params = jax.tree_util.tree_map(
        lambda _: P(axis_name), stage_params)
    fn = shard_map(per_stage, mesh=mesh,
                   in_specs=(spec_params, P()),
                   out_specs=P())
    ys = fn(stage_params, xs)
    return ys.reshape(B, *x.shape[1:])


def stack_stage_params(per_stage_params):
    """[pytree_per_stage, ...] -> one pytree with a leading stage axis."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def shard_stages(stacked, mesh, axis_name="pp"):
    """Place stacked stage params with the stage axis over ``pp``."""
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(
            leaf, NamedSharding(mesh, P(axis_name))), stacked)


class PipelineTrainStep:
    """Pipelined training: loss/grads through the GPipe schedule.

    >>> step = PipelineTrainStep(stage_fn, stage_params, mesh, loss_fn)
    >>> loss = step(x, y, lr=0.1)
    """

    def __init__(self, stage_fn, stage_params, mesh, loss_fn,
                 axis_name="pp", num_microbatches=None):
        self._stage_fn = stage_fn
        self._mesh = mesh
        self._axis = axis_name
        self._loss_fn = loss_fn
        self._M = num_microbatches
        self._params = shard_stages(stage_params, mesh, axis_name)

        def train(params, x, y, lr):
            def loss_of(p):
                out = pipeline_apply(stage_fn, p, x, mesh, axis_name,
                                     num_microbatches)
                return loss_fn(out, y)

            loss, grads = jax.value_and_grad(loss_of)(params)
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)
            return new_params, loss

        self._train = jax.jit(train, donate_argnums=(0,))

    def __call__(self, x, y, lr=0.01):
        raw_x = x.data if hasattr(x, "data") else jnp.asarray(x)
        raw_y = y.data if hasattr(y, "data") else jnp.asarray(y)
        self._params, loss = self._train(self._params, raw_x, raw_y,
                                         jnp.asarray(lr, jnp.float32))
        return loss
