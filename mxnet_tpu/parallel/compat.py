"""jax API compatibility accessors for the parallel layer.

One place to absorb upstream moves; every in-repo consumer (parallel
submodules, bench.py, tools/bandwidth/measure.py) goes through here.
"""

from __future__ import annotations


def get_shard_map():
    """``jax.shard_map`` accessor — the API was promoted out of
    ``jax.experimental``; older jax in some containers only has the
    experimental path."""
    try:
        from jax import shard_map
    except ImportError:  # older jax: pre-promotion API
        from jax.experimental.shard_map import shard_map
    return shard_map
