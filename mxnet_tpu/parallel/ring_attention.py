"""Ring attention: sequence/context parallelism over a mesh axis.

No reference counterpart (SURVEY.md §2.5 P11 — "does not exist in the
reference"; §5.7 marks it as the required new capability). Design follows
the public ring-attention recipe: shard Q/K/V along the sequence axis over
the mesh's ``sp`` axis; each device computes blockwise attention against
its local KV shard, then rotates the KV shard around the ring with
``lax.ppermute`` (riding ICI), accumulating with the online-softmax
combine. Peak memory per device is O(T/n) regardless of total context.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.flash_attention import _jnp_flash_fwd, flash_attention_core


def _local_attn_with_lse(q, k, v, scale, mask_fn=None):
    """Blockwise local attention returning (out_unnormalized, m, l)."""
    s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask_fn is not None:
        s = mask_fn(s)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.maximum(m, -1e30)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32))
    return o, m_safe, l


def ring_attention(query, key, value, mesh, axis_name="sp", scale=None,
                   causal=False):
    """Sequence-parallel attention over ``mesh[axis_name]``.

    query/key/value: (B, H, T, D) GLOBAL arrays (host view); T is sharded
    across the axis. Returns the global (B, H, T, D) result with the same
    sharding. Jit-able; collectives lower to ICI ppermute.
    """
    from .compat import get_shard_map
    shard_map = get_shard_map()

    if scale is None:
        scale = 1.0 / (query.shape[-1] ** 0.5)
    n = mesh.shape[axis_name]
    T = query.shape[2]
    assert T % n == 0, f"seq len {T} must divide ring size {n}"
    chunk = T // n

    def per_device(q, k, v):
        # q,k,v: (B, H, T/n, D) local shards
        my = lax.axis_index(axis_name)

        def mask_for(kv_owner_idx):
            if not causal:
                return None

            def mask_fn(s):
                rows = my * chunk + jnp.arange(chunk)[:, None]
                cols = kv_owner_idx * chunk + jnp.arange(chunk)[None, :]
                return jnp.where(rows >= cols, s, -1e30)

            return mask_fn

        def step(carry, r):
            o_acc, m_acc, l_acc, k_cur, v_cur = carry
            owner = (my - r) % n
            o, m, l = _local_attn_with_lse(q, k_cur, v_cur, scale,
                                           mask_for(owner))
            m_new = jnp.maximum(m_acc, m)
            alpha_acc = jnp.exp(m_acc - m_new)
            alpha = jnp.exp(m - m_new)
            o_acc = o_acc * alpha_acc + o * alpha
            l_acc = l_acc * alpha_acc + l * alpha
            # rotate KV around the ring (skip after last step is harmless)
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
            return (o_acc, m_new, l_acc, k_nxt, v_nxt), None

        B, H, Tl, D = q.shape

        def _vary(x):
            # mark constants as varying over the ring axis so the scan
            # carry types match shard_map's varying-axes check; the API
            # was lax.pvary (<=0.8, deprecated) and is lax.pcast in 0.9+
            if hasattr(lax, "pcast"):
                return lax.pcast(x, (axis_name,), to="varying")
            if hasattr(lax, "pvary"):  # pragma: no cover (old jax)
                return lax.pvary(x, axis_name)
            return x  # pragma: no cover

        init = (
            _vary(jnp.zeros((B, H, Tl, D), jnp.float32)),
            _vary(jnp.full((B, H, Tl, 1), -1e30, jnp.float32)),
            _vary(jnp.zeros((B, H, Tl, 1), jnp.float32)),
            k, v,
        )
        (o_acc, m_acc, l_acc, _, _), _ = lax.scan(step, init, jnp.arange(n))
        return (o_acc / jnp.maximum(l_acc, 1e-30)).astype(q.dtype)

    spec = P(None, None, axis_name, None)
    fn = shard_map(per_device, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(query, key, value)


def shard_sequence(arr, mesh, axis_name="sp", seq_axis=2):
    """Place a (B, H, T, D) array with T sharded over the ring axis."""
    ndim = arr.ndim
    spec = [None] * ndim
    spec[seq_axis] = axis_name
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))
