"""Bucket-ready overlapped gradient communication + ZeRO shard math.

The reference hid data-parallel communication behind backward compute by
scheduling per-key push/pull through the dependency engine (SURVEY §2.5
P1/P2): a gradient's allreduce could start the moment that gradient was
produced, while the engine kept executing the rest of backward. The
TPU-native analog lives here: gradient **readiness order** is computed
from the VJP structure (reverse-mode AD produces grads roughly in
reverse order of each parameter's first forward use), buckets are
composed in that order so a bucket's *last* contributor arrives early,
and each bucket's collective is issued inside the SAME compiled step the
backward runs in — XLA's latency-hiding scheduler (async collectives /
start-done pairs on TPU) then overlaps the wire time with the remaining
backward compute. No host round trip ever sits between "gradient ready"
and "collective issued"; mxtpu-lint's ``overlap-window-sync`` rule
machine-checks that invariant.

Three comm flavors over one :class:`BucketPlan`:

- :func:`bucket_allreduce` — ``lax.psum`` per bucket (ZeRO-0/1),
- :func:`bucket_reduce_scatter` — ``lax.psum_scatter`` per bucket,
  handing each rank only its 1/N gradient shard (ZeRO-2/3),
- both optionally behind :func:`jax.lax.optimization_barrier` (the
  ``barrier`` ablation mode: comm can't start before backward ends),
  and both optionally through in-graph 2-bit compression
  (:func:`compress_bucket`) with per-rank residual carry.

Everything here is pure and trace-safe: usable inside ``jax.jit``,
``shard_map`` and ``lax.scan`` bodies (the K-step superstep scans a step
whose body calls these helpers).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

_logger = logging.getLogger("mxnet_tpu.parallel.overlap")


# ---------------------------------------------------------------------------
# readiness order from the VJP structure
# ---------------------------------------------------------------------------

def first_use_order(fn, example_args, n_diff):
    """Gradient readiness order for ``fn(diff_params, *rest)``.

    Traces ``fn`` (``jax.make_jaxpr``) and records, for each of the
    first ``n_diff`` flattened inputs, the index of the first equation
    consuming it. Reverse-mode AD emits each parameter's gradient near
    the (reversed) position of its first forward use, so sorting by
    DESCENDING first-use index approximates the order grads become
    available during backward. Returns a permutation of
    ``range(n_diff)`` (grad index of the earliest-ready gradient
    first), or None when tracing fails or yields no signal (e.g. the
    whole forward collapsed into one fused call) — callers fall back
    to reversed parameter order, the classic DDP heuristic.
    """
    try:
        closed = jax.make_jaxpr(fn)(*example_args)
        jaxpr = closed.jaxpr
        flat_in = jaxpr.invars
        # diff params are the FIRST pytree argument: its leaves are the
        # first n_diff flat invars (callers pass them as a list of raw
        # arrays, each one leaf)
        targets = flat_in[:n_diff]
        first = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for v in eqn.invars:
                if isinstance(v, jax.core.Var) and v not in first:
                    first[v] = i
        idxs = [first.get(v, -1) for v in targets]
        if len(set(idxs)) <= 1:
            return None  # no signal: one mega-equation consumed all
        return sorted(range(n_diff), key=lambda k: (-idxs[k], k))
    except Exception as e:  # pragma: no cover - backend/tracing quirks
        _logger.debug("first_use_order: trace failed (%s: %s)",
                      type(e).__name__, e)
        return None


# ---------------------------------------------------------------------------
# bucket plan
# ---------------------------------------------------------------------------

class BucketPlan:
    """Readiness-ordered, dtype-homogeneous gradient bucketing.

    ``buckets``: tuple of tuples of gradient indices, in ISSUE order
    (bucket 0's collective can go on the wire first). ``shapes`` /
    ``dtypes`` / ``sizes`` are per-gradient (original order);
    ``pad_sizes`` is the per-gradient flat length padded up to a
    multiple of ``dp`` (equal to ``sizes`` when ``dp`` is 1 — padding
    only matters for the reduce-scatter layout).
    """

    __slots__ = ("buckets", "shapes", "dtypes", "sizes", "pad_sizes",
                 "order", "dp")

    def __init__(self, buckets, shapes, dtypes, sizes, pad_sizes, order,
                 dp):
        self.buckets = tuple(tuple(b) for b in buckets)
        self.shapes = tuple(tuple(s) for s in shapes)
        self.dtypes = tuple(dtypes)
        self.sizes = tuple(sizes)
        self.pad_sizes = tuple(pad_sizes)
        self.order = tuple(order)
        self.dp = int(dp)

    def __len__(self):
        return len(self.buckets)


def _ceil_to(n, m):
    return ((int(n) + m - 1) // m) * m if m > 1 else int(n)


def build_bucket_plan(shapes, dtypes, order=None, bucket_bytes=None,
                      dp=1):
    """Greedy ~``bucket_bytes`` dtype-homogeneous packing in readiness
    order. ``order`` is the issue order from :func:`first_use_order`
    (default: reversed index order — last parameter's grad is produced
    first). ``dp`` > 1 additionally pads every gradient's flat length
    to a multiple of ``dp`` so reduce-scatter shards stay aligned
    per-gradient (a gradient never straddles two ranks' chunks)."""
    from .. import fusedstep as _fusedstep

    n = len(shapes)
    if order is None:
        order = list(range(n - 1, -1, -1))
    target = max(int(bucket_bytes if bucket_bytes is not None
                     else _fusedstep.overlap_bucket_bytes()), 1)
    sizes = []
    for shape in shapes:
        c = 1
        for d in shape:
            c *= int(d)
        sizes.append(c)
    pad_sizes = [_ceil_to(s, dp) for s in sizes]
    buckets = []
    open_by_dtype = {}
    for gi in order:
        dt = str(dtypes[gi])
        nbytes = pad_sizes[gi] * jnp.dtype(dtypes[gi]).itemsize
        cur = open_by_dtype.get(dt)
        if cur is None or (cur[1] and cur[1] + nbytes > target):
            cur = [[], 0]
            open_by_dtype[dt] = cur
            buckets.append(cur)
        cur[0].append(gi)
        cur[1] += nbytes
    return BucketPlan([b for b, _ in buckets], shapes, dtypes, sizes,
                      pad_sizes, order, dp)


# ---------------------------------------------------------------------------
# flat-shard math (ZeRO-2/3 layout)
# ---------------------------------------------------------------------------

def pad_flat(arr, pad_size):
    """Flatten + zero-pad one array to ``pad_size`` elements."""
    flat = arr.reshape(-1)
    if pad_size > flat.shape[0]:
        flat = jnp.pad(flat, (0, pad_size - flat.shape[0]))
    return flat

def unpad_reshape(flat, size, shape):
    """Inverse of :func:`pad_flat` (drops the pad tail)."""
    return flat[:size].reshape(shape)


def shard_of(full, plan_or_dp, axis_name, gi=None):
    """This rank's ``[pad/dp]`` flat shard of one full array — inside a
    ``shard_map`` body (``lax.axis_index`` picks the row)."""
    if isinstance(plan_or_dp, BucketPlan):
        dp = plan_or_dp.dp
        pad = plan_or_dp.pad_sizes[gi]
    else:
        dp = int(plan_or_dp)
        pad = _ceil_to(full.size, dp)
    rows = pad_flat(full, pad).reshape(dp, pad // dp)
    return jax.lax.dynamic_index_in_dim(
        rows, jax.lax.axis_index(axis_name), axis=0, keepdims=False)


def _chaos_point(site):
    """Trace-time chaos fault point for the in-graph collectives: these
    helpers run under tracing (inside jit/shard_map/scan bodies), so a
    due one-shot ``collective`` fault (``MXTPU_CHAOS=collective@<site>``)
    surfaces as a LOUD build/step failure at the issue point — never
    wrong numerics, and zero extra dispatches when chaos is off (one
    module-bool read behind a lazy import)."""
    from ..resilience import chaos as _chaos

    if _chaos.ENABLED:
        _chaos.collective_point(site)


def gather_shard(shard, axis_name):
    """All ranks' ``[pad/dp]`` shards -> the full ``[pad]`` flat array
    (``lax.all_gather`` tiled on the existing axis)."""
    _chaos_point("bucket_allgather")
    return jax.lax.all_gather(shard, axis_name, tiled=True)


# ---------------------------------------------------------------------------
# in-graph 2-bit compression (the kvstore 2bit scheme, bucket-shaped)
# ---------------------------------------------------------------------------

def compress_bucket(bucket, threshold, residual):
    """Quantize one flat bucket to ``{-t, 0, +t}`` with error feedback:
    the pre-reduction payload drops to 2 effective bits per element (the
    reference's ``gradient_compression.cc`` scheme, applied to the
    packed bucket instead of per key — elementwise, so bucketing does
    not change the numerics), and the quantization error carries to the
    next step through ``residual``. Returns ``(q, new_residual)``."""
    t = jnp.asarray(threshold, bucket.dtype)
    acc = bucket + residual
    q = jnp.where(acc >= t, t, jnp.where(acc <= -t, -t,
                                         jnp.zeros((), bucket.dtype)))
    return q, acc - q


# ---------------------------------------------------------------------------
# bucketed collectives
# ---------------------------------------------------------------------------

def _maybe_barrier(flats, barrier):
    """``barrier=True`` pins every gradient behind one optimization
    barrier, so no collective can be scheduled before the whole
    backward finished — the ablation/parity baseline for the
    bucket-ready mode (numerics are identical either way; only the
    schedule differs)."""
    if not barrier:
        return flats
    # the ONE sanctioned graph-level barrier: the ablation mode exists
    # to measure what the bucket-ready schedule buys
    return list(jax.lax.optimization_barrier(  # mxtpu-lint: overlap-barrier-ok
        tuple(flats)))


def bucket_allreduce(grads, axis_name, plan, postscale=None,
                     barrier=False, compress=None, residuals=None,
                     wire_dtype=None):
    """One ``lax.psum`` per plan bucket, issued in readiness order;
    returns (reduced grads in original order, new residuals or None).

    ``postscale`` multiplies each bucket AFTER the reduction (the
    1/dp of a mean-loss data-parallel step rides here — one fused
    multiply per bucket instead of one per gradient). ``compress`` is
    a 2-bit threshold applied per bucket pre-reduction with
    ``residuals`` carry (list aligned with ``plan.buckets``).
    ``wire_dtype`` casts each bucket to a reduced precision for the
    collective (summation happens in that dtype) and back afterwards —
    1/2 the wire bytes for bf16 gradients at bf16-sum accuracy."""
    _chaos_point("bucket_psum")
    flat = _maybe_barrier([g.reshape(-1) for g in grads], barrier)
    out = [None] * len(grads)
    new_res = [None] * len(plan.buckets) if compress is not None else None
    for bi, idxs in enumerate(plan.buckets):
        parts = [flat[i] for i in idxs]
        b = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        if compress is not None:
            b, new_res[bi] = compress_bucket(b, compress, residuals[bi])
        odt = b.dtype
        if wire_dtype is not None and b.dtype != jnp.dtype(wire_dtype):
            b = b.astype(wire_dtype)
        red = jax.lax.psum(b, axis_name)
        if red.dtype != odt:
            red = red.astype(odt)
        if postscale is not None:
            red = red * jnp.asarray(postscale, red.dtype)
        off = 0
        for i in idxs:
            n = plan.sizes[i]
            out[i] = jax.lax.slice(red, (off,), (off + n,)).reshape(
                plan.shapes[i])
            off += n
    return out, new_res


def bucket_reduce_scatter(grads, axis_name, plan, postscale=None,
                          barrier=False, compress=None, residuals=None,
                          wire_dtype=None):
    """One ``lax.psum_scatter`` per plan bucket (ZeRO-2/3): each rank
    receives only its 1/dp shard of every summed gradient — 1/dp the
    wire bytes AND 1/dp the gradient memory of an allreduce. Layout:
    each gradient pads to a multiple of ``dp`` and reshapes to
    ``[dp, pad/dp]``; buckets concatenate along axis 1, so scattering
    axis 0 hands rank r row r — the r-th shard of every gradient in
    the bucket, sliceable per gradient without cross-rank straddling.
    Returns (per-gradient ``[pad/dp]`` shards in original order, new
    residuals or None)."""
    _chaos_point("bucket_psum_scatter")
    dp = plan.dp
    flat = _maybe_barrier([g.reshape(-1) for g in grads], barrier)
    out = [None] * len(grads)
    new_res = [None] * len(plan.buckets) if compress is not None else None
    for bi, idxs in enumerate(plan.buckets):
        parts = [pad_flat(flat[i], plan.pad_sizes[i]).reshape(dp, -1)
                 for i in idxs]
        b = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        if compress is not None:
            q, new_res[bi] = compress_bucket(
                b.reshape(-1), compress, residuals[bi])
            b = q.reshape(b.shape)
        odt = b.dtype
        if wire_dtype is not None and b.dtype != jnp.dtype(wire_dtype):
            b = b.astype(wire_dtype)
        red = jax.lax.psum_scatter(b, axis_name, scatter_dimension=0,
                                   tiled=False)
        if red.dtype != odt:
            red = red.astype(odt)
        if postscale is not None:
            red = red * jnp.asarray(postscale, red.dtype)
        off = 0
        for i in idxs:
            n = plan.pad_sizes[i] // dp
            out[i] = jax.lax.slice(red, (off,), (off + n,))
            off += n
    return out, new_res


def residual_shapes(plan, reduce_scatter):
    """Per-bucket residual payload lengths for the compression carry
    (the packed bucket's element count: padded when the bucket feeds a
    reduce-scatter, exact otherwise)."""
    sizes = plan.pad_sizes if reduce_scatter else plan.sizes
    return [sum(sizes[i] for i in idxs) for idxs in plan.buckets]


# ---------------------------------------------------------------------------
# overlap measurement probe
# ---------------------------------------------------------------------------

def measure_overlap(block_factory, loss_fn, optimizer, optimizer_params,
                    mesh, x, y, lr=0.01, steps=20, warmup=3,
                    modes=("nocomm", "ready", "barrier", "staged")):
    """Measure how much gradient-communication time each scheduling
    mode exposes, on the SAME model/batch/mesh.

    ``nocomm`` (collectives dropped — numerically wrong on purpose) is
    the compute-only floor; each mode's exposed comm is its mean step
    wall time minus the floor's. ``hidden_fraction`` is
    ``1 - exposed[ready] / exposed[staged]`` — the share of the
    host-driven baseline's exposed comm the bucket-ready in-graph
    schedule hides. Publishes the result through
    ``observability.record_overlap_probe``; returns a dict with
    ``step_seconds``, ``exposed_comm_seconds`` and ``hidden_fraction``.

    ``block_factory`` must build an identically-initialized fresh block
    per call (each mode compiles its own executable and donates its own
    state)."""
    import time

    from .. import observability as _obs
    from .spmd import SPMDTrainStep

    step_seconds = {}
    for mode in modes:
        block = block_factory()
        # zero_stage pinned to 0: an ambient MXTPU_ZERO_STAGE>=2 would
        # downgrade the staged leg to barrier mode (staged has no ZeRO
        # layout) and change the comm layout under the other legs —
        # the modes would no longer measure the same collectives
        step = SPMDTrainStep(block, loss_fn, optimizer,
                             optimizer_params, mesh, overlap=mode,
                             zero_stage=0)
        out = None
        for _ in range(warmup):
            out = step(x, y, lr=lr, sync=False)
        if out is not None:
            jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = step(x, y, lr=lr, sync=False)
        jax.block_until_ready(out)
        step_seconds[mode] = (time.perf_counter() - t0) / steps
    floor = step_seconds.get("nocomm")
    exposed = {}
    if floor is not None:
        for mode, t in step_seconds.items():
            if mode != "nocomm":
                exposed[mode] = max(t - floor, 0.0)
    hidden = None
    # baseline = the staged leg when it RAN (even if it measured 0.0
    # exposed comm on a noisy host — that means nothing to hide, not
    # "fall back to barrier"); barrier only when staged wasn't probed
    base = exposed.get("staged") if "staged" in exposed \
        else exposed.get("barrier")
    if base is not None and "ready" in exposed:
        hidden = (max(0.0, min(1.0, 1.0 - exposed["ready"] / base))
                  if base > 0.0 else 0.0)
    _obs.record_overlap_probe(exposed, hidden)
    return {"step_seconds": step_seconds,
            "exposed_comm_seconds": exposed,
            "hidden_fraction": hidden}
