"""4D-parallel composed train step: dp x pp x tp with ZeRO on the dp axis.

``Composed4DStep`` is the one-mesh trainer the parallelism contract
(``mesh.MESH_AXES``) exists for. A single ``shard_map`` over the full
``Mesh(dp, pp, tp, sp, ep)`` runs:

* **pp** — the tick-table pipeline executor (``pipeline._run_schedule``)
  with any of the three schedules (``1f1b`` default at one chunk per
  rank, ``interleaved`` default when stages tile the axis more than
  once, ``gpipe`` for comparison runs);
* **tp** — per-stage parameters carry a ``PartitionSpec`` over their
  stage dims (``tp_specs``); the stage function owns its tensor
  collectives (Megatron-style psum/all_gather over ``"tp"``), exactly
  as in the jit path of ``SPMDTrainStep``;
* **dp** — the batch is sharded over ``dp`` and gradients are either
  ``pmean``'d (ZeRO-0/1) or flattened, padded, ``psum_scatter``'d and
  updated shard-wise (ZeRO-2/3) — the same flat-shard layout
  ``SPMDTrainStep``'s overlap path uses, made orthogonal to pp/tp by
  applying it per (pp-rank, tp-index) cell. lamb keeps stage 2/3 via
  the shard-norm rule (one extra psum pair, over ``dp`` alone for
  tp-replicated leaves and ``(dp, tp)`` for tp-sharded ones).

``sp`` and ``ep`` must be 1 inside the step: sequence sharding rides
:func:`ring_attention.ring_attention` and expert parallelism rides
:func:`moe.moe_apply_a2a`, both of which a stage function can call
(they only need their axis to exist in the mesh).

Checkpoints are topology-independent by construction:
``state_snapshot`` emits every tensor in **natural per-stage form**
(key ``param::p<i>::s<g>`` = global stage ``g`` of leaf ``i``), so a
snapshot taken at (dp=4, pp=1) restores bit-exact into (dp=2, pp=2)
and back — the flat ZeRO shards and the stage permutation are a
storage detail undone on the way out and redone on the way in.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from .mesh import axis_size, validate_mesh_axes
from .pipeline import (build_pipeline_schedule, stage_permutation,
                       _run_schedule, _microbatch, _amp_wrap)


def _raw(a):
    """Unwrap an mx ndarray handle; pass numpy/jax arrays through
    (numpy's ``.data`` is a memoryview, not the payload)."""
    d = getattr(a, "data", None)
    return d if isinstance(d, jax.Array) else jnp.asarray(a)


def _prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


def tp_copy(x, axis_name="tp"):
    """Megatron's *f* function: identity forward, ``psum`` backward.

    Put this on a stage input consumed by a column-parallel matmul —
    each tp rank back-propagates only its shard's partial input
    gradient, and the psum on the way back restores the full one."""
    @jax.custom_vjp
    def f(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, g):
        return (lax.psum(g, axis_name),)

    f.defvjp(fwd, bwd)
    return f(x)


def tp_all_gather(x, axis_name="tp", axis=-1):
    """Megatron's *g* function: ``all_gather`` forward, **slice**
    backward. The default transpose of all_gather (psum_scatter) is
    wrong when every tp rank consumes the gathered tensor redundantly
    — each rank would contribute its full cotangent copy, scaling the
    gradient by tp. Slicing back out this rank's block is the correct
    adjoint of gather-then-replicate."""
    ax = axis % x.ndim
    k = x.shape[ax]

    @jax.custom_vjp
    def f(v):
        return lax.all_gather(v, axis_name, axis=ax, tiled=True)

    def fwd(v):
        return lax.all_gather(v, axis_name, axis=ax, tiled=True), None

    def bwd(_, g):
        i = lax.axis_index(axis_name)
        return (lax.dynamic_slice_in_dim(g, i * k, k, axis=ax),)

    f.defvjp(fwd, bwd)
    return f(x)


class Composed4DStep:
    """Train over the composed ``(dp, pp, tp)`` mesh in one step.

    ``stage_params``: pytree whose leaves have a leading stage axis
    ``[L, ...]`` (``L`` a multiple of the ``pp`` size; ``L/pp`` virtual
    chunks per rank). ``tp_specs``: optional matching pytree of
    ``PartitionSpec`` over the *stage* dims (``P(None, "tp")`` etc.);
    unspecified leaves are tp-replicated. ``embed_fn(p, x_mb)`` /
    ``head_fn(p, h)`` bracket the pipeline with replicated params.

    >>> mesh = composed_mesh(dp=2, pp=2, tp=2)
    >>> step = Composed4DStep(stage_fn, params, mesh, loss_fn,
    ...                       optimizer="adam", zero_stage=2)
    >>> loss = step(x, y, lr=1e-3)
    """

    def __init__(self, stage_fn, stage_params, mesh, loss_fn, *,
                 optimizer="sgd", optimizer_params=None,
                 num_microbatches=None, schedule=None, zero_stage=0,
                 amp_dtype=None, tp_specs=None,
                 embed_fn=None, embed_params=None,
                 head_fn=None, head_params=None):
        from .. import fusedstep, observability as _obs
        from .spmd import _RULES, _lamb_rule_sharded
        from .compat import get_shard_map

        validate_mesh_axes(mesh, "Composed4DStep")
        if "pp" not in mesh.shape or "dp" not in mesh.shape:
            raise MXNetError(
                "Composed4DStep wants the composed mesh contract "
                "(dp, pp, ...); build it with composed_mesh()")
        for ax in ("sp", "ep"):
            if axis_size(mesh, ax) != 1:
                raise MXNetError(
                    f"Composed4DStep: {ax}={axis_size(mesh, ax)} — "
                    "sequence sharding rides ring_attention and expert "
                    "parallelism rides moe.moe_apply_a2a (call them "
                    f"from the stage function); keep {ax}=1 here")
        self._mesh = mesh
        S = axis_size(mesh, "pp")
        dp = axis_size(mesh, "dp")
        tp = axis_size(mesh, "tp")
        self._S, self._dp, self._tp = S, dp, tp

        leaves, treedef = jax.tree_util.tree_flatten(stage_params)
        if not leaves:
            raise MXNetError("Composed4DStep: empty stage_params")
        L = int(leaves[0].shape[0])
        for a in leaves:
            if int(a.shape[0]) != L:
                raise MXNetError(
                    "Composed4DStep: every stage_params leaf needs the "
                    f"same leading stage axis (got {a.shape[0]} vs {L})")
        if L % S:
            raise MXNetError(
                f"{L} stages do not tile the pp={S} axis")
        v = L // S
        self._L, self._v = L, v
        self._treedef = treedef

        if schedule is None:
            schedule = "interleaved" if v > 1 else "1f1b"
        if schedule in ("gpipe", "1f1b") and v != 1:
            raise MXNetError(
                f"{schedule} runs one stage per rank: {L} stages != "
                f"pp={S} (use schedule='interleaved')")
        M = num_microbatches or fusedstep.pipeline_microbatches() or S
        sched = build_pipeline_schedule(S, M, schedule, virtual=v)
        self.schedule = sched
        self._M = M

        if optimizer not in _RULES:
            raise MXNetError(
                f"Composed4DStep supports {sorted(_RULES)}; got "
                f"{optimizer}")
        zero_stage = int(zero_stage)
        if zero_stage not in (0, 1, 2, 3):
            raise MXNetError(f"zero_stage must be 0..3; got {zero_stage}")
        self.zero_stage = zero_stage
        hyper = dict(optimizer_params or {})
        rule_init, rule_update = _RULES[optimizer](hyper)
        self._rule_init = rule_init
        fn = _amp_wrap(stage_fn, amp_dtype)

        # --- per-leaf tp layout -------------------------------------
        if tp_specs is None:
            tentries = [()] * len(leaves)
        else:
            tentries = [tuple(s) if s is not None else ()
                        for s in treedef.flatten_up_to(tp_specs)]
        self._tp_dim = []
        self._pspec = []
        self._stage_shapes = []
        self._local_shapes = []
        for i, a in enumerate(leaves):
            ent = tentries[i]
            bad = [e for e in ent if e not in (None, "tp")]
            if bad:
                raise MXNetError(
                    f"tp_specs leaf {i}: only the 'tp' axis may appear "
                    f"in stage specs (got {bad})")
            d = ent.index("tp") if "tp" in ent else None
            stage_shape = tuple(int(s) for s in a.shape[1:])
            local = list(stage_shape)
            if d is not None:
                if "tp" not in mesh.shape:
                    raise MXNetError("tp_specs name 'tp' but the mesh "
                                     "has no tp axis")
                if local[d] % tp:
                    raise MXNetError(
                        f"stage dim {d} ({local[d]}) of leaf {i} does "
                        f"not tile tp={tp}")
                local[d] //= tp
            self._tp_dim.append(d)
            self._stage_shapes.append(stage_shape)
            self._local_shapes.append(tuple(local))
            self._pspec.append(P("pp", *ent))
        self._n_local = [v * _prod(sh) for sh in self._local_shapes]
        self._npad = [-(-n // dp) * dp for n in self._n_local]
        self._shard = [npad // dp for npad in self._npad]

        perm = stage_permutation(S, v)
        self._perm = np.asarray(perm)
        self._inv = np.argsort(self._perm)
        self._flat_spec = P("pp", "tp", "dp")

        # --- initial storage ----------------------------------------
        nat0 = [np.asarray(a) for a in leaves]  # global stage order
        if zero_stage >= 3:
            self._params = [self._put_flat(self._nat_to_flat(i, nat0[i]))
                            for i in range(len(leaves))]
        else:
            self._params = [self._put_nat(i, nat0[i])
                            for i in range(len(leaves))]
        if zero_stage >= 2:
            self._opt = [self._init_flat_opt(i, nat0[i])
                         for i in range(len(leaves))]
        else:
            self._opt = [self._init_nat_opt(i, nat0[i])
                         for i in range(len(leaves))]

        self._extra = {}
        for part, p0 in (("embed", embed_params), ("head", head_params)):
            if p0 is not None:
                pdev = jax.tree_util.tree_map(
                    lambda x: jax.device_put(
                        jnp.asarray(x), NamedSharding(mesh, P())), p0)
                self._extra[part + "_p"] = pdev
                self._extra[part + "_o"] = jax.tree_util.tree_map(
                    rule_init, pdev)
        self._embed_fn, self._head_fn = embed_fn, head_fn

        # --- per-leaf update rules ----------------------------------
        if optimizer == "lamb":
            # trust-ratio norms span the whole stacked leaf: psum over
            # every axis that shards it (pp always; dp once the leaf
            # is flat-scattered; tp when tp_specs shard it) — the same
            # update whatever the topology, and exact under ZeRO-2/3
            leaf_update = []
            for i in range(len(leaves)):
                axes = ["pp"]
                if zero_stage >= 2:
                    axes.append("dp")
                if self._tp_dim[i] is not None and tp > 1:
                    axes.append("tp")
                leaf_update.append(
                    _lamb_rule_sharded(hyper, tuple(axes))[1])
        else:
            leaf_update = [rule_update] * len(leaves)

        n_leaves = len(leaves)
        n_local, npad, shard_len = self._n_local, self._npad, self._shard
        local_shapes = self._local_shapes
        zstage = zero_stage
        run_embed, run_head = embed_fn, head_fn

        def _opt_dev_spec(i, st):
            return tuple(
                (self._flat_spec if zstage >= 2 else self._pspec[i])
                if getattr(x, "ndim", 0) >= 1 else P() for x in st)

        def body(params_dev, opt_dev, extra_dev, xs, ys, lr):
            if zstage >= 3:
                nat = []
                for i in range(n_leaves):
                    flat = lax.all_gather(params_dev[i][0, 0], "dp",
                                          tiled=True)
                    nat.append(flat[: n_local[i]].reshape(
                        (v,) + local_shapes[i]))
            else:
                nat = list(params_dev)
            ep_p = extra_dev.get("embed_p")
            hp_p = extra_dev.get("head_p")
            loss, grads, aux = _run_schedule(
                fn, loss_fn, sched, "pp", nat, xs, ys,
                head_fn=run_head if hp_p is not None else None,
                head_params=hp_p,
                embed_fn=run_embed if ep_p is not None else None,
                embed_params=ep_p)
            loss = lax.pmean(loss, "dp")
            new_p, new_o = [], []
            for i in range(n_leaves):
                g, w, st = grads[i], nat[i], opt_dev[i]
                if zstage < 2:
                    g = lax.pmean(g, "dp")
                    w2, st2 = leaf_update[i](w, g, st, lr)
                    new_p.append(w2)
                    new_o.append(st2)
                    continue
                gflat = jnp.pad(g.reshape(-1),
                                (0, npad[i] - n_local[i]))
                gsh = lax.psum_scatter(gflat, "dp",
                                       scatter_dimension=0,
                                       tiled=True) / dp
                if zstage >= 3:
                    wsh = params_dev[i][0, 0]
                else:
                    wflat = jnp.pad(w.reshape(-1),
                                    (0, npad[i] - n_local[i]))
                    wsh = lax.dynamic_slice(
                        wflat, (lax.axis_index("dp") * shard_len[i],),
                        (shard_len[i],))
                st_loc = tuple(x[0, 0] if getattr(x, "ndim", 0) == 3
                               else x for x in st)
                w2, st2 = leaf_update[i](wsh, gsh, st_loc, lr)
                if zstage >= 3:
                    new_p.append(w2[None, None])
                else:
                    full = lax.all_gather(w2, "dp", tiled=True)
                    new_p.append(full[: n_local[i]].reshape(w.shape))
                new_o.append(tuple(
                    x[None, None] if getattr(x, "ndim", 0) == 1 else x
                    for x in st2))
            new_extra = dict(extra_dev)
            for part, gaux in (("embed", aux["embed"]),
                               ("head", aux["head"])):
                if gaux is None:
                    continue
                pk, ok = part + "_p", part + "_o"
                g = jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, "dp"), gaux)
                fp, tdef = jax.tree_util.tree_flatten(extra_dev[pk])
                fg = tdef.flatten_up_to(g)
                fo = tdef.flatten_up_to(extra_dev[ok])
                np_, no_ = [], []
                for pw, pg, po in zip(fp, fg, fo):
                    w2, st2 = rule_update(pw, pg, po, lr)
                    np_.append(w2)
                    no_.append(st2)
                new_extra[pk] = tdef.unflatten(np_)
                new_extra[ok] = tdef.unflatten(no_)
            return new_p, new_o, new_extra, loss

        shard_map = get_shard_map()
        if zero_stage >= 3:
            pspec_dev = [self._flat_spec] * n_leaves
        else:
            pspec_dev = list(self._pspec)
        ospec_dev = [_opt_dev_spec(i, st)
                     for i, st in enumerate(self._opt)]
        espec = jax.tree_util.tree_map(lambda _: P(), self._extra)
        self._mapped = shard_map(
            body, mesh=mesh,
            in_specs=(pspec_dev, ospec_dev, espec,
                      P(None, "dp"), P(None, "dp"), P()),
            out_specs=(pspec_dev, ospec_dev, espec, P()),
            check_rep=False)

        def train(params, opt, extra, x, y, lr):
            xs, ys = _microbatch(x, y, M)
            return self._mapped(params, opt, extra, xs, ys, lr)

        def superstep(params, opt, extra, xss, yss, lr):
            def scan_body(carry, xy):
                p, o, e = carry
                p, o, e, loss = self._mapped(p, o, e, xy[0], xy[1], lr)
                return (p, o, e), loss

            (p, o, e), losses = lax.scan(
                scan_body, (params, opt, extra), (xss, yss))
            return p, o, e, losses

        self._train = jax.jit(train, donate_argnums=(0, 1, 2))
        self._superstep = jax.jit(superstep, donate_argnums=(0, 1, 2))
        self._registered = set()
        _obs.record_pipeline_schedule(
            sched.name, sched.bubble_fraction, sched.stash_slots,
            ticks=sched.ticks)

    # --- storage layout helpers (host-side numpy) -------------------

    def _put_nat(self, i, nat):
        """Natural global-stage-order [L, ...] -> permuted stacked
        device array sharded (pp, *tp)."""
        return jax.device_put(
            jnp.asarray(nat[self._perm]),
            NamedSharding(self._mesh, self._pspec[i]))

    def _put_flat(self, flat):
        return jax.device_put(
            jnp.asarray(flat), NamedSharding(self._mesh, self._flat_spec))

    def _nat_to_flat(self, i, nat):
        """[L, *stage_shape] -> [S, tp, npad] flat ZeRO cells."""
        S, v, tp = self._S, self._v, self._tp
        d = self._tp_dim[i]
        out = np.zeros((S, tp, self._npad[i]), nat.dtype)
        for r in range(S):
            for j in range(tp):
                parts = []
                for c in range(v):
                    t = nat[c * S + r]
                    if d is not None:
                        k = t.shape[d] // tp
                        t = np.take(t, range(j * k, (j + 1) * k), axis=d)
                    parts.append(np.asarray(t).reshape(-1))
                vec = np.concatenate(parts)
                out[r, j, : vec.size] = vec
        return out

    def _flat_to_nat(self, i, flat):
        """[S, tp, npad] -> [L, *stage_shape] natural stage order."""
        S, v, tp = self._S, self._v, self._tp
        d = self._tp_dim[i]
        nat = np.zeros((self._L,) + self._stage_shapes[i], flat.dtype)
        for r in range(S):
            cells = [flat[r, j, : self._n_local[i]].reshape(
                (v,) + self._local_shapes[i]) for j in range(tp)]
            merged = (np.concatenate(cells, axis=d + 1)
                      if d is not None else cells[0])
            for c in range(v):
                nat[c * S + r] = merged[c]
        return nat

    def _init_nat_opt(self, i, nat):
        st = jax.jit(self._rule_init)(jnp.asarray(nat[self._perm]))
        return tuple(
            jax.device_put(x, NamedSharding(
                self._mesh,
                self._pspec[i] if getattr(x, "ndim", 0) >= 1 else P()))
            for x in st)

    def _init_flat_opt(self, i, nat):
        flat = self._nat_to_flat(i, nat)
        init = jax.jit(self._rule_init)
        cells = [[init(jnp.asarray(flat[r, j]))
                  for j in range(self._tp)] for r in range(self._S)]
        out = []
        for li in range(len(cells[0][0])):
            leaf = cells[0][0][li]
            if getattr(leaf, "ndim", 0) == 0:
                out.append(jax.device_put(
                    leaf, NamedSharding(self._mesh, P())))
            else:
                stacked = np.stack(
                    [np.stack([np.asarray(cells[r][j][li])
                               for j in range(self._tp)])
                     for r in range(self._S)])
                out.append(self._put_flat(stacked))
        return tuple(out)

    # --- stepping ---------------------------------------------------

    def _register(self, site, jit_fn, args):
        if site in self._registered:
            return
        self._registered.add(site)
        try:
            from .. import observability as _obs
            _obs.introspect.register_jit(
                site, jit_fn, _obs.introspect.avals_of(args),
                donated=True)
        except Exception:  # pragma: no cover - introspection is best-effort
            pass

    def __call__(self, x, y, lr=0.01):
        raw_x, raw_y = _raw(x), _raw(y)
        if (raw_x.shape[0] // self._M) % self._dp:
            raise MXNetError(
                f"microbatch size {raw_x.shape[0] // self._M} does not "
                f"tile the dp={self._dp} axis")
        lr = jnp.asarray(lr, jnp.float32)
        args = (self._params, self._opt, self._extra, raw_x, raw_y, lr)
        self._register("composed4d_step", self._train, args)
        self._params, self._opt, self._extra, loss = self._train(*args)
        return loss

    def run_superstep(self, x, y, lr=0.01):
        """Scan ``k`` fused steps on device: ``x``/``y`` lead with the
        step axis ``[k, B, ...]``. Returns the per-step losses."""
        raw_x, raw_y = _raw(x), _raw(y)
        k, B = raw_x.shape[0], raw_x.shape[1]
        M = self._M
        if B % M or (B // M) % self._dp:
            raise MXNetError(
                f"superstep batch {B} must tile microbatches {M} x "
                f"dp={self._dp}")
        xss = raw_x.reshape(k, M, B // M, *raw_x.shape[2:])
        yss = raw_y.reshape(k, M, B // M, *raw_y.shape[2:])
        lr = jnp.asarray(lr, jnp.float32)
        args = (self._params, self._opt, self._extra, xss, yss, lr)
        self._register("composed4d_superstep", self._superstep, args)
        self._params, self._opt, self._extra, losses = \
            self._superstep(*args)
        return losses

    def schedule_report(self):
        return self.schedule.report()

    def memory_report(self):
        """Per-device bytes by storage plane plus the schedule's stash
        cost — the numbers a 4D layout choice trades against."""
        def dev_bytes(arrs):
            total = 0
            for a in jax.tree_util.tree_leaves(arrs):
                try:
                    total += a.addressable_shards[0].data.nbytes
                except Exception:
                    total += a.nbytes // self._mesh.size
            return int(total)

        return {"zero_stage": self.zero_stage,
                "schedule": self.schedule.name,
                "bubble_fraction": round(
                    self.schedule.bubble_fraction, 6),
                "stash_slots": self.schedule.stash_slots,
                "param_bytes_per_device": dev_bytes(self._params),
                "opt_bytes_per_device": dev_bytes(self._opt),
                "extra_bytes_per_device": dev_bytes(self._extra)}

    # --- topology-independent snapshot/restore ----------------------

    def state_snapshot(self):
        """Emit (chunks, extents): every tensor in natural per-stage
        form, keyed topology-independently — ``param::p<i>::s<g>``,
        ``opt::p<i>::s<g>::<li>`` (scalar state leaves live at ``s0``),
        ``embed::p<j>`` / ``head::p<j>`` and their ``_opt`` rows. A
        snapshot from any (dp, pp, tp) restores into any other."""
        chunks, extents = {}, {}

        def put(key, arr):
            arr = np.asarray(arr)
            idx = tuple(slice(0, s) for s in arr.shape)
            # np.ascontiguousarray would promote 0-d scalars to (1,)
            chunks[key] = [(idx, np.array(arr, copy=True))]
            extents[key] = arr.shape

        for i in range(len(self._params)):
            if self.zero_stage >= 3:
                nat = self._flat_to_nat(i, np.asarray(self._params[i]))
            else:
                nat = np.asarray(self._params[i])[self._inv]
            for g in range(self._L):
                put(f"param::p{i}::s{g}", nat[g])
            for li, leaf in enumerate(self._opt[i]):
                a = np.asarray(leaf)
                if a.ndim == 0:
                    put(f"opt::p{i}::s0::{li}", a)
                    continue
                nat_o = (self._flat_to_nat(i, a)
                         if self.zero_stage >= 2 else a[self._inv])
                for g in range(self._L):
                    put(f"opt::p{i}::s{g}::{li}", nat_o[g])
        for part in ("embed", "head"):
            if part + "_p" not in self._extra:
                continue
            fp = jax.tree_util.tree_leaves(self._extra[part + "_p"])
            fo = jax.tree_util.tree_leaves(self._extra[part + "_o"])
            for j, leaf in enumerate(fp):
                put(f"{part}::p{j}", leaf)
            for j, leaf in enumerate(fo):
                put(f"{part}_opt::p{j}", leaf)
        return chunks, extents

    def restore_chunks(self, chunks, extents=None):
        """Load a :meth:`state_snapshot` (possibly taken on a different
        (dp, pp, tp) topology) into this step's storage layout."""
        del extents  # extents are implied by this step's own shapes

        def paste(key, shape, dtype):
            if key not in chunks:
                raise MXNetError(f"restore: missing snapshot key {key}")
            if shape == ():
                return np.asarray(chunks[key][0][1])
            out = np.zeros(shape, dtype)
            for idx, data in chunks[key]:
                out[idx] = data
            return out

        for i in range(len(self._params)):
            dt = np.asarray(
                jax.tree_util.tree_leaves(self._params[i])[0]).dtype
            nat = np.stack([
                paste(f"param::p{i}::s{g}", self._stage_shapes[i], dt)
                for g in range(self._L)])
            if self.zero_stage >= 3:
                self._params[i] = self._put_flat(
                    self._nat_to_flat(i, nat))
            else:
                self._params[i] = self._put_nat(i, nat)
            new_st = []
            for li, leaf in enumerate(self._opt[i]):
                a = np.asarray(leaf)
                if a.ndim == 0:
                    val = paste(f"opt::p{i}::s0::{li}", (), a.dtype)
                    new_st.append(jax.device_put(
                        jnp.asarray(val, a.dtype),
                        NamedSharding(self._mesh, P())))
                    continue
                nat_o = np.stack([
                    paste(f"opt::p{i}::s{g}::{li}",
                          self._stage_shapes[i], a.dtype)
                    for g in range(self._L)])
                if self.zero_stage >= 2:
                    new_st.append(self._put_flat(
                        self._nat_to_flat(i, nat_o)))
                else:
                    new_st.append(jax.device_put(
                        jnp.asarray(nat_o[self._perm]),
                        NamedSharding(self._mesh, self._pspec[i])))
            self._opt[i] = tuple(new_st)
        for part in ("embed", "head"):
            if part + "_p" not in self._extra:
                continue
            for token, store in ((part, part + "_p"),
                                 (part + "_opt", part + "_o")):
                fl, tdef = jax.tree_util.tree_flatten(self._extra[store])
                out = []
                for j, leaf in enumerate(fl):
                    a = np.asarray(leaf)
                    out.append(jax.device_put(
                        jnp.asarray(paste(f"{token}::p{j}", a.shape,
                                          a.dtype)),
                        NamedSharding(self._mesh, P())))
                self._extra[store] = tdef.unflatten(out)
