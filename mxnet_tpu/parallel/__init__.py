"""``mxnet_tpu.parallel`` — SPMD mesh parallelism.

No reference counterpart: MXNet 1.x scales via KVStore push/pull (SURVEY.md
§2.5). This package is the TPU-native replacement: a device Mesh +
sharding-annotated fused train step. Data parallel ≈ batch-axis sharding
(grads psum'd by XLA over ICI); tensor/ZeRO sharding are sharding
annotations on the same step (P9/P13 in SURVEY.md §2.5).
"""

from .mesh import (make_mesh, current_mesh, data_parallel_mesh,  # noqa: F401
                   composed_mesh, axis_size, validate_mesh_axes,
                   MESH_AXES)
from .spmd import (SPMDTrainStep, shard_batch, replicate,  # noqa: F401
                   bucketed_psum,  # noqa: F401
                   spmd_save_states, spmd_load_states,  # noqa: F401
                   spmd_state_snapshot, spmd_restore_chunks)  # noqa: F401
from . import overlap  # noqa: F401
from .overlap import (BucketPlan, build_bucket_plan,  # noqa: F401
                      bucket_allreduce, bucket_reduce_scatter,
                      first_use_order, measure_overlap)
from .ring_attention import ring_attention, shard_sequence  # noqa: F401
from .pipeline import (PipelineTrainStep, pipeline_apply,  # noqa: F401,E402
                       shard_stages, stack_stage_params,
                       build_pipeline_schedule, stage_permutation,
                       measure_pipeline_bubble)
from .composed import (Composed4DStep, tp_copy,  # noqa: F401,E402
                       tp_all_gather)
from . import moe  # noqa: F401,E402
from .moe import (top2_routing, moe_apply_a2a,  # noqa: F401,E402
                  measure_moe_overlap)
