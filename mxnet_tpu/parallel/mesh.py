"""Device mesh helpers."""

from __future__ import annotations

import numpy as _np

import jax
from jax.sharding import Mesh

_CURRENT = [None]


def make_mesh(axes=None, devices=None):
    """Create a ``jax.sharding.Mesh``.

    ``axes``: dict of axis name -> size, e.g. ``{"dp": 4, "tp": 2}``.
    Sizes must multiply to the device count (-1 allowed once to infer).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        sizes[sizes.index(-1)] = n // known
    total = 1
    for s in sizes:
        total *= s
    assert total == n, f"mesh {dict(zip(names, sizes))} != {n} devices"
    dev_array = _np.array(devices).reshape(sizes)
    mesh = Mesh(dev_array, tuple(names))
    _CURRENT[0] = mesh
    return mesh


def data_parallel_mesh():
    return make_mesh({"dp": len(jax.devices())})


def current_mesh():
    return _CURRENT[0]
