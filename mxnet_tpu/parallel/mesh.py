"""Device mesh helpers.

The 4D-parallel trainer composes every parallelism axis over ONE mesh
whose axis names come from a fixed contract (``MESH_AXES``): ``dp``
(data/batch — gradients reduce here, ZeRO shards optimizer state here),
``pp`` (pipeline stages), ``tp`` (tensor/model sharding inside a
stage), ``sp`` (sequence — ring attention), ``ep`` (MoE experts).
``composed_mesh`` builds a canonically-ordered mesh from per-axis
sizes; every consumer (``SPMDTrainStep``, ``Composed4DStep``, the MoE
all-to-all, ring attention) addresses axes by these names only, so the
axes stay orthogonal by construction.
"""

from __future__ import annotations

import numpy as _np

import jax
from jax.sharding import Mesh

_CURRENT = [None]

#: The axis-name contract, in canonical order: data, pipeline, tensor,
#: sequence, expert. A mesh may carry any subset (missing = size 1).
MESH_AXES = ("dp", "pp", "tp", "sp", "ep")


def make_mesh(axes=None, devices=None):
    """Create a ``jax.sharding.Mesh``.

    ``axes``: dict of axis name -> size, e.g. ``{"dp": 4, "tp": 2}``.
    Sizes must multiply to the device count (-1 allowed once to infer).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        sizes[sizes.index(-1)] = n // known
    total = 1
    for s in sizes:
        total *= s
    assert total == n, f"mesh {dict(zip(names, sizes))} != {n} devices"
    dev_array = _np.array(devices).reshape(sizes)
    mesh = Mesh(dev_array, tuple(names))
    _CURRENT[0] = mesh
    return mesh


def composed_mesh(dp=1, pp=1, tp=1, sp=1, ep=1, devices=None):
    """Build the canonical 4D-parallel mesh ``(dp, pp, tp, sp, ep)``.

    Axes are ordered per ``MESH_AXES`` regardless of call order; size-1
    axes are kept in the mesh so SPMD programs can name them uniformly
    (a collective over a size-1 axis is a no-op). ``dp=-1`` infers the
    data axis from the device count.
    """
    sizes = {"dp": dp, "pp": pp, "tp": tp, "sp": sp, "ep": ep}
    for name, s in sizes.items():
        if name != "dp" and (not isinstance(s, int) or s < 1):
            raise ValueError(f"composed_mesh: axis {name}={s!r} must be "
                             "a positive int (-1 inference is dp-only)")
    return make_mesh({name: sizes[name] for name in MESH_AXES},
                     devices=devices)


def axis_size(mesh, name):
    """Size of ``name`` in ``mesh`` (1 when the axis is absent)."""
    return int(mesh.shape[name]) if name in mesh.shape else 1


def validate_mesh_axes(mesh, where="mesh"):
    """Loudly reject axis names outside the ``MESH_AXES`` contract.

    Returns the mesh for chaining. Legacy single-purpose names used by
    tests and internal probes (``batch``, ``model``, ``x``/``y``) stay
    accepted — the contract governs the composed trainer path.
    """
    legacy = {"batch", "model", "x", "y", "devices"}
    unknown = [a for a in mesh.axis_names
               if a not in MESH_AXES and a not in legacy]
    if unknown:
        raise ValueError(
            f"{where}: unknown mesh axes {unknown}; the 4D-parallel "
            f"contract is {MESH_AXES} (see docs/performance.md "
            "\"choosing a 4D layout\")")
    return mesh


def data_parallel_mesh():
    return make_mesh({"dp": len(jax.devices())})


def current_mesh():
    return _CURRENT[0]
