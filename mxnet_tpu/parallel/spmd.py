"""Fused SPMD train step over a device mesh.

This is the TPU-native training fast path (SURVEY.md §7.4): the whole
forward + backward + optimizer update compiles into ONE XLA executable with
sharding annotations; gradients are psum'd by XLA over the mesh's ``dp``
axis (replacing KVStore push/pull entirely). Tensor-parallel and
ZeRO-style state sharding are expressed as alternative param shardings on
the same step.

Uses the same "functionalize the imperative frontend" trick as CachedOp:
the Gluon block's Python forward runs once under tracing with parameter
handles bound to tracers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import autograd
from .. import observability as _obs
from .. import random as _random
from ..base import MXNetError
from ..gluon.block import _TRACE_STATE
from ..ndarray.ndarray import NDArray


def _put_global(raw, sharding):
    """Build a global array under ``sharding`` with each PROCESS serving
    its own addressable shards from ``raw`` (device_put would need
    cross-host transfers on a multi-process mesh, which CPU/DCN-less
    backends refuse). On a single process this degenerates to a plain
    sharded placement."""
    import numpy as onp

    host = onp.asarray(raw)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


def shard_batch(arr, mesh, axis_name="dp"):
    """Place a host batch sharded along its leading axis. On a
    multi-process mesh every process passes an array of the GLOBAL batch
    shape and contributes the rows its devices own (identical arrays
    everywhere -> the natural single-program semantics; per-rank data ->
    the global batch is the concatenation of each rank's owned rows)."""
    raw = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
    sharding = NamedSharding(mesh, P(axis_name, *([None] * (raw.ndim - 1))))
    if isinstance(raw, jax.Array):
        try:
            if raw.sharding.is_equivalent_to(sharding, raw.ndim):
                # already placed (e.g. staged ahead by DevicePrefetcher):
                # re-sharding would gather the global batch to host
                return raw
        except Exception:
            pass
    return _put_global(raw, sharding)


def replicate(arr, mesh):
    raw = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
    return _put_global(raw, NamedSharding(mesh, P()))


def _state_dtype(w):
    """Multi-precision rule (reference: ``mp_sgd_update``/``mp_adam_update``
    in optimizer_op): low-precision weights carry f32 optimizer state and
    update in f32 master math, casting back on write. This is also what
    keeps the step's avals STABLE: without it, ``lr(f32) * m(bf16)``
    promotes the new params to f32, every aval flips after step 1, and
    jit recompiles the whole train step (observed: 2 extra 60s compiles
    on BERT-base)."""
    return jnp.float32 if w.dtype in (jnp.bfloat16, jnp.float16) else w.dtype


def _sgd_rule(hyper):
    mom = hyper.get("momentum", 0.0)
    wd_const = hyper.get("wd", 0.0)

    def init(w):
        return (jnp.zeros(w.shape, _state_dtype(w)),) if mom else ()

    # ``wd`` defaults to the hyper constant but also accepts a traced
    # scalar operand (gluon.Trainer's fused update passes per-param
    # wd*wd_mult that way, so changing wd never retraces)
    def update(w, g, state, lr, wd=wd_const):
        dt = _state_dtype(w)
        w32, g32, lr32 = w.astype(dt), g.astype(dt), lr.astype(dt)
        g32 = g32 + wd * w32
        if mom:
            m = mom * state[0] - lr32 * g32
            return (w32 + m).astype(w.dtype), (m,)
        return (w32 - lr32 * g32).astype(w.dtype), ()

    return init, update


def _adam_rule(hyper):
    beta1 = hyper.get("beta1", 0.9)
    beta2 = hyper.get("beta2", 0.999)
    eps = hyper.get("epsilon", 1e-8)
    wd_const = hyper.get("wd", 0.0)

    def init(w):
        dt = _state_dtype(w)
        return (jnp.zeros(w.shape, dt), jnp.zeros(w.shape, dt),
                jnp.zeros((), jnp.int32))

    def update(w, g, state, lr, wd=wd_const):
        dt = _state_dtype(w)
        m, v, t = state
        t = t + 1
        w32, g32, lr32 = w.astype(dt), g.astype(dt), lr.astype(dt)
        g32 = g32 + wd * w32
        m = beta1 * m + (1 - beta1) * g32
        v = beta2 * v + (1 - beta2) * jnp.square(g32)
        tf = t.astype(dt)
        lr_t = lr32 * jnp.sqrt(1 - beta2 ** tf) / (1 - beta1 ** tf)
        return (w32 - lr_t * m / (jnp.sqrt(v) + eps)).astype(w.dtype), \
            (m, v, t)

    return init, update


def _lamb_rule(hyper):
    beta1 = hyper.get("beta1", 0.9)
    beta2 = hyper.get("beta2", 0.999)
    eps = hyper.get("epsilon", 1e-6)
    wd_const = hyper.get("wd", 0.0)

    def init(w):
        dt = _state_dtype(w)
        return (jnp.zeros(w.shape, dt), jnp.zeros(w.shape, dt),
                jnp.zeros((), jnp.int32))

    def update(w, g, state, lr, wd=wd_const):
        dt = _state_dtype(w)
        m, v, t = state
        t = t + 1
        w32, g32, lr32 = w.astype(dt), g.astype(dt), lr.astype(dt)
        m = beta1 * m + (1 - beta1) * g32
        v = beta2 * v + (1 - beta2) * jnp.square(g32)
        tf = t.astype(dt)
        m_hat = m / (1 - beta1 ** tf)
        v_hat = v / (1 - beta2 ** tf)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * w32
        w_norm = jnp.linalg.norm(w32)
        r_norm = jnp.linalg.norm(r)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (w32 - lr32 * ratio * r).astype(w.dtype), (m, v, t)

    return init, update


def _nag_rule(hyper):
    """Nesterov momentum, matching ``optimizer.NAG.update``."""
    mom = hyper.get("momentum", 0.0)
    wd_const = hyper.get("wd", 0.0)

    def init(w):
        return (jnp.zeros(w.shape, _state_dtype(w)),) if mom else ()

    def update(w, g, state, lr, wd=wd_const):
        dt = _state_dtype(w)
        w32, g32, lr32 = w.astype(dt), g.astype(dt), lr.astype(dt)
        g32 = g32 + wd * w32
        if mom:
            m = mom * state[0] + g32
            return (w32 - lr32 * (g32 + mom * m)).astype(w.dtype), (m,)
        return (w32 - lr32 * g32).astype(w.dtype), ()

    return init, update


_RULES = {"sgd": _sgd_rule, "nag": _nag_rule, "adam": _adam_rule,
          "adamw": _adam_rule, "lamb": _lamb_rule}

_MP_SENTINEL = object()


def mp_rule(rule_init, rule_update):
    """fp32 master-weight wrapper around a ``_RULES`` pair (reference:
    ``mp_sgd_update``/``mp_adam_update``): for bf16/fp16 params the
    fp32 master copy becomes STATE LEAF 0, so it lives (and is donated)
    in the same optimizer-state pytree as the moments — updates
    accumulate in the master across steps and the stored weight is a
    rounded VIEW of it, instead of being re-derived from the rounded
    weight every step (which loses updates smaller than one bf16 ulp).
    fp32 params pass through untouched, so one wrapped rule serves a
    mixed-precision param set."""

    from ..amp.policy import is_low_precision_dtype

    def init(w):
        if not is_low_precision_dtype(w.dtype):
            return rule_init(w)
        master = w.astype(jnp.float32)
        return (master,) + tuple(rule_init(master))

    def update(w, g, state, lr, wd=_MP_SENTINEL):
        kw = {} if wd is _MP_SENTINEL else {"wd": wd}
        if not is_low_precision_dtype(w.dtype):
            return rule_update(w, g, state, lr, **kw)
        master, inner = state[0], tuple(state[1:])
        new_master, new_inner = rule_update(
            master, g.astype(jnp.float32), inner, lr, **kw)
        return new_master.astype(w.dtype), \
            (new_master,) + tuple(new_inner)

    return init, update


def bucketed_psum(grads, axis_name, bucket_bytes=None):
    """Scan-compatible bucketed gradient allreduce: one ``lax.psum`` per
    ~``bucket_bytes`` dtype-homogeneous flat bucket instead of one per
    gradient tensor — the in-graph analog of the kvstore's bucketed
    pushpull (PR 3), usable inside ``shard_map``/``lax.scan`` bodies
    (pure, no host round trip, stable avals across iterations). Returns
    the reduced gradients in the original order/shapes/dtypes.

    This is what a K-step superstep body calls per iteration on a
    multi-device mesh: K iterations x one-psum-per-bucket, all inside a
    single dispatched executable."""
    from .. import fusedstep as _fusedstep

    target = int(bucket_bytes if bucket_bytes is not None
                 else _fusedstep.bucket_bytes())
    flat = [g.reshape(-1) for g in grads]
    # greedy dtype-homogeneous fill, preserving order within a dtype
    buckets = []  # [idx list, payload bytes], one per bucket
    open_by_dtype = {}
    for i, f in enumerate(flat):
        dt = f.dtype
        nbytes = f.size * f.dtype.itemsize
        cur = open_by_dtype.get(dt)
        if cur is None or (cur[1] + nbytes > target and cur[0]):
            cur = [[], 0]
            open_by_dtype[dt] = cur
            buckets.append(cur)
        cur[0].append(i)
        cur[1] += nbytes
    out = [None] * len(grads)
    for idxs, _ in buckets:
        if len(idxs) == 1:
            i = idxs[0]
            out[i] = jax.lax.psum(grads[i], axis_name)
            continue
        packed = jnp.concatenate([flat[i] for i in idxs])
        red = jax.lax.psum(packed, axis_name)
        off = 0
        for i in idxs:
            n = flat[i].size
            out[i] = red[off:off + n].reshape(grads[i].shape)
            off += n
    return out


class SPMDTrainStep:
    """One-executable train step for a Gluon block over a mesh.

    >>> step = SPMDTrainStep(net, loss_fn, "sgd", {"momentum": 0.9}, mesh)
    >>> loss = step(batch_x, batch_y, lr=0.1)
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, batch_axis="dp", param_sharding=None,
                 shard_opt_states=False, grad_dtype=None, donate=True,
                 multi_precision=False):
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.batch_axis = batch_axis
        hyper = dict(optimizer_params or {})
        if optimizer not in _RULES:
            raise MXNetError(
                f"SPMD step supports {sorted(_RULES)}; got {optimizer}. "
                "Use gluon.Trainer for other optimizers.")
        self._rule_init, self._rule_update = _RULES[optimizer](hyper)
        if multi_precision:
            # bf16/fp16 params carry fp32 masters as state leaf 0 —
            # sharded/donated with the rest of the opt-state pytree
            self._rule_init, self._rule_update = mp_rule(
                self._rule_init, self._rule_update)
        self._param_sharding = param_sharding or {}
        self._shard_opt_states = shard_opt_states
        self._donate = donate
        self._compiled = None
        self._state = None  # (params, aux, opt_states) raw pytrees
        self._names = None
        self._diff = None
        self._io_avals = None
        self._run_many = None
        self._last_loss = None

    # -- state management -------------------------------------------------
    def _collect(self):
        items = sorted(self.block.collect_params().items())
        names = [n for n, _ in items]
        handles = [p.data() for _, p in items]
        diff = [p.grad_req != "null" for _, p in items]
        return names, handles, diff

    def _sharding_for(self, name, raw):
        if self.mesh is None:
            return None
        spec = self._param_sharding.get(name, P())
        return NamedSharding(self.mesh, spec)

    def _opt_state_spec(self, name, raw):
        """ZeRO-1 (SURVEY P13): moment tensors shard along dim 0 over the
        data axis, unless the param itself is already sharded on dim 0
        (tensor parallel) or dim 0 doesn't divide, in which case they
        follow the param's sharding."""
        pspec = self._param_sharding.get(name, P())
        if not self._shard_opt_states or self.mesh is None:
            return pspec
        dp = dict(zip(self.mesh.axis_names, self.mesh.devices.shape)).get(
            self.batch_axis)
        if (dp and raw.ndim >= 1 and raw.shape[0] % dp == 0
                and not (len(pspec) > 0 and pspec[0] is not None)):
            return P(self.batch_axis, *([None] * (raw.ndim - 1)))
        if (dp and raw.ndim >= 1 and raw.shape[0] % dp != 0
                and not (len(pspec) > 0 and pspec[0] is not None)):
            # visible fallback: on a real pod a silently replicated moment
            # is an invisible memory-budget surprise
            import logging

            logging.getLogger(__name__).warning(
                "ZeRO-1: opt state for %r (shape %s) not divisible by "
                "dp=%d; falling back to the param sharding %s",
                name, tuple(raw.shape), dp, pspec)
        return pspec

    def init_state(self):
        names, handles, diff = self._collect()
        self._names, self._handles, self._diff = names, handles, diff
        params = []
        opt_states = []
        opt_specs = []
        commit_dev = None
        if self.mesh is None:
            # commit to the default device: eager-built arrays are
            # UNCOMMITTED while jit outputs are committed, and that
            # sharding flip alone recompiles the step after call 1
            commit_dev = jax.devices()[0]
        for n, h, d in zip(names, handles, diff):
            raw = h.data
            if self.mesh is not None:
                # per-process shard feeding (works across hosts) + a fresh
                # buffer: the compiled step DONATES its param buffers, and
                # a donated alias of the Gluon handle's array kills it (a
                # second step on the same block then dies with "Array has
                # been deleted")
                raw = _put_global(raw, self._sharding_for(n, raw))
            else:
                raw = jnp.copy(jax.device_put(raw, commit_dev))
            params.append(raw)
            if not d:
                opt_states.append(())
                opt_specs.append(())
                continue
            state = self._rule_init(raw)
            spec = self._opt_state_spec(n, raw)
            # only moment-shaped leaves get the ZeRO spec; scalars (step
            # counters) stay replicated
            leaf_specs = tuple(
                spec if getattr(leaf, "shape", ()) == raw.shape else P()
                for leaf in state)
            if self.mesh is not None:
                state = tuple(
                    _put_global(leaf, NamedSharding(self.mesh, sp))
                    for leaf, sp in zip(state, leaf_specs))
            else:
                state = tuple(jax.device_put(leaf, commit_dev)
                              for leaf in state)
            opt_states.append(state)
            opt_specs.append(leaf_specs)
        self._opt_specs = opt_specs
        self._state = (params, opt_states)

    # -- compiled step ----------------------------------------------------
    def _build(self, x_shape_dtype, y_shape_dtype):
        block, loss_fn = self.block, self.loss_fn
        handles, diff = self._handles, self._diff
        rule_update = self._rule_update

        def run_forward(param_raws, x, y, key):
            _TRACE_STATE.active = True
            _random.push_trace_key(key)
            saved = [h._data_ for h in handles]
            try:
                for h, raw in zip(handles, param_raws):
                    h._data_ = raw
                xin = NDArray(x)
                yin = NDArray(y)
                with autograd._RecordingStateScope(False, True):
                    out = block(xin)
                    loss = loss_fn(out, yin)
                loss_raw = jnp.mean(loss.data)
                mutated = [h._data_ for h in handles]
                return loss_raw, mutated
            finally:
                for h, s in zip(handles, saved):
                    h._data_ = s
                _random.pop_trace_key()
                _TRACE_STATE.active = False

        mesh = self.mesh
        opt_specs = getattr(self, "_opt_specs", None)

        def step(params, opt_states, x, y, lr, key):
            diff_idx = [i for i, d in enumerate(diff) if d]

            def loss_of(diff_params):
                full = list(params)
                for i, p in zip(diff_idx, diff_params):
                    full[i] = p
                loss, mutated = run_forward(full, x, y, key)
                return loss, mutated

            (loss, mutated), grads = jax.value_and_grad(loss_of, has_aux=True)(
                [params[i] for i in diff_idx]
            )
            new_params = list(mutated)  # aux (BN stats) updates carried here
            new_states = list(opt_states)
            for k, i in enumerate(diff_idx):
                w, s = rule_update(params[i], grads[k], opt_states[i], lr)
                if mesh is not None and opt_specs is not None and opt_specs[i]:
                    # pin ZeRO-1 shardings so XLA keeps moments sharded
                    # across steps instead of replicating them
                    s = tuple(
                        jax.lax.with_sharding_constraint(
                            leaf, NamedSharding(mesh, sp))
                        for leaf, sp in zip(s, opt_specs[i]))
                new_params[i] = w
                new_states[i] = s
            return new_params, new_states, loss

        donate = (0, 1) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)

    def __call__(self, x, y, lr=0.01, sync=True):
        if self._state is None:
            # resolve deferred init with one tiny eager pass. The probe
            # runs on a HOST copy of one row: the incoming batch may
            # already be mesh-sharded (DevicePrefetcher stages ahead),
            # and an eager forward mixing an 8-device input with
            # single-device params dies in dispatch.
            import numpy as onp

            raw = x.data if isinstance(x, NDArray) else jnp.asarray(x)
            if isinstance(raw, jax.Array) and raw.addressable_shards:
                host = onp.asarray(raw.addressable_shards[0].data)
            else:
                host = onp.asarray(raw)
            xin = NDArray(jnp.asarray(host[0:1] if host.shape[0] > 1
                                      else host))
            with autograd.predict_mode():
                self.block(xin)
            self.init_state()
        raw_x = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        raw_y = y.data if isinstance(y, NDArray) else jnp.asarray(y)
        if self.mesh is not None:
            raw_x = shard_batch(NDArray(raw_x), self.mesh, self.batch_axis)
            raw_y = shard_batch(NDArray(raw_y), self.mesh, self.batch_axis)
        if self._compiled is None:
            self._compiled = self._build(None, None)
        key = _random._next_key()
        params, opt_states = self._state
        lr_arr = jnp.asarray(lr, raw_x.dtype
                             if raw_x.dtype in (jnp.float32, jnp.bfloat16)
                             else jnp.float32)
        # only the small call-arg avals are kept; param/state avals are
        # rebuilt lazily from _state in cost_analysis() (keeps this hot
        # path free of an O(n_params) tree_map per step)
        self._io_avals = (raw_x.shape, raw_x.dtype, raw_y.shape, raw_y.dtype,
                          lr_arr.dtype, key)
        args = (params, opt_states, raw_x, raw_y, lr_arr, key)
        if _obs.introspect.ENABLED \
                and not _obs.introspect.registered("spmd_step"):
            _obs.introspect.register_jit(
                "spmd_step", self._compiled,
                _obs.introspect.avals_of(args), donated=self._donate)
        if _obs.flight.INSTALLED:
            with _obs.flight.dispatch("spmd_step"):
                new_params, new_states, loss = self._compiled(*args)
        else:
            new_params, new_states, loss = self._compiled(*args)
        if _obs.ENABLED:
            _obs.record_xla_dispatch("spmd_step")
        self._state = (new_params, new_states)
        return float(loss) if sync else loss

    def run_steps(self, x, y, n, lr=0.01):
        """Run ``n`` steps on one batch inside a single executable
        (``lax.fori_loop`` over the compiled step) — the analog of the
        reference's bulked execution (``MXNET_EXEC_BULK_EXEC_TRAIN``):
        one dispatch instead of n, which matters on dispatch-latency-
        bound backends (the axon relay adds ~10ms/step to the Python
        loop). Per-step RNG keys are folded from one base key. Returns
        the final loss (device scalar)."""
        if self._state is None or self._compiled is None \
                or self._last_loss is None:
            # one plain step: resolves deferred init, compiles the inner
            # step, and seeds the loss carry with the right dtype
            self._last_loss = self(x, y, lr=lr, sync=False)
            n -= 1
            if n <= 0:
                return self._last_loss
        raw_x = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        raw_y = y.data if isinstance(y, NDArray) else jnp.asarray(y)
        if self.mesh is not None:
            raw_x = shard_batch(NDArray(raw_x), self.mesh, self.batch_axis)
            raw_y = shard_batch(NDArray(raw_y), self.mesh, self.batch_axis)
        lr_arr = jnp.asarray(lr, raw_x.dtype
                             if raw_x.dtype in (jnp.float32, jnp.bfloat16)
                             else jnp.float32)
        base_key = _random._next_key()
        inner = self._compiled

        if self._run_many is None:
            def many(params, opt_states, xx, yy, lr_a, key, loss0, n_steps):
                def body(i, c):
                    p, s, _ = c
                    return inner(p, s, xx, yy, lr_a,
                                 jax.random.fold_in(key, i))

                # n_steps is a TRACED bound (lowers to while_loop): one
                # compile covers every n
                return jax.lax.fori_loop(0, n_steps, body,
                                         (params, opt_states, loss0))

            donate = (0, 1) if self._donate else ()
            self._run_many = jax.jit(many, donate_argnums=donate)
        params, opt_states = self._state
        new_params, new_states, loss = self._run_many(
            params, opt_states, raw_x, raw_y, lr_arr, base_key,
            self._last_loss, jnp.asarray(n, jnp.int32))
        if _obs.ENABLED:
            _obs.record_xla_dispatch("spmd_step")
        self._state = (new_params, new_states)
        self._last_loss = loss
        return loss

    def run_superstep(self, xs, ys, lr=0.01):
        """K DISTINCT batches in one dispatch: ``lax.scan`` of the
        compiled step over stacked ``[K, ...]`` operands. ``run_steps``
        re-consumes ONE batch (a bulked micro-benchmark); this is the
        training superstep — each scan iteration consumes its own batch
        slot, so a real input pipeline (``gluon.data.SuperstepRing``)
        feeds it with the host touching the loop once per K steps.
        Per-iteration RNG keys fold from one base key. Returns the
        per-iteration losses as a length-K device array (lazy)."""
        raw_x = xs.data if isinstance(xs, NDArray) else jnp.asarray(xs)
        raw_y = ys.data if isinstance(ys, NDArray) else jnp.asarray(ys)
        if self._state is None:
            # resolve deferred init + build state WITHOUT consuming an
            # update (a priming step would apply slot 0 twice): same
            # host-row predict probe as __call__
            import numpy as onp

            # one-time deferred-init probe (self._state is None exactly
            # once), never on the per-superstep path
            if isinstance(raw_x, jax.Array) and raw_x.addressable_shards:
                host = onp.asarray(  # mxtpu-lint: host-sync-ok
                    raw_x.addressable_shards[0].data)
            else:
                host = onp.asarray(raw_x)  # mxtpu-lint: host-sync-ok
            xin = NDArray(jnp.asarray(host[0][0:1] if host[0].ndim and
                                      host[0].shape[0] > 1 else host[0]))
            with autograd.predict_mode():
                self.block(xin)
            self.init_state()
        if self._compiled is None:
            self._compiled = self._build(None, None)
        if self.mesh is not None:
            # slot axis 0 stays unsharded; the per-iteration batch axis
            # (dim 1) shards over the mesh exactly like a single step's
            raw_x = _put_global(raw_x, NamedSharding(
                self.mesh, P(None, self.batch_axis,
                             *([None] * (raw_x.ndim - 2)))))
            raw_y = _put_global(raw_y, NamedSharding(
                self.mesh, P(None, self.batch_axis,
                             *([None] * (raw_y.ndim - 2)))))
        lr_arr = jnp.asarray(lr, raw_x.dtype
                             if raw_x.dtype in (jnp.float32, jnp.bfloat16)
                             else jnp.float32)
        base_key = _random._next_key()
        inner = self._compiled

        if getattr(self, "_run_super", None) is None:
            def many(params, opt_states, xxs, yys, lr_a, keys):
                def body(carry, slot):
                    p, s = carry
                    xx, yy, key = slot
                    p2, s2, loss = inner(p, s, xx, yy, lr_a, key)
                    return (p2, s2), loss

                (p, s), losses = jax.lax.scan(
                    body, (params, opt_states), (xxs, yys, keys))
                return p, s, losses

            donate = (0, 1) if self._donate else ()
            self._run_super = jax.jit(many, donate_argnums=donate)
        k = int(raw_x.shape[0])
        keys = jax.random.split(base_key, k)
        params, opt_states = self._state
        args = (params, opt_states, raw_x, raw_y, lr_arr, keys)
        if _obs.introspect.ENABLED \
                and not _obs.introspect.registered("spmd_superstep"):
            _obs.introspect.register_jit(
                "spmd_superstep", self._run_super,
                _obs.introspect.avals_of(args), donated=self._donate)
        if _obs.flight.INSTALLED:
            with _obs.flight.dispatch("spmd_superstep"):
                new_params, new_states, losses = self._run_super(*args)
        else:
            new_params, new_states, losses = self._run_super(*args)
        if _obs.ENABLED:
            _obs.record_xla_dispatch("spmd_superstep")
            # per-iteration in-scan loss series, stored whole and lazy
            _obs.record_superstep_series(losses)
        self._state = (new_params, new_states)
        self._last_loss = losses[-1]
        return losses

    def cost_analysis(self):
        """XLA's cost analysis for the compiled step (``{"flops": ...}``),
        or None when the backend doesn't expose it (some PJRT plugins).
        NB: re-lowers and recompiles; on remote-compile backends this can
        take as long as the first step."""
        if self._compiled is None or self._io_avals is None:
            return None
        try:
            xs, xd, ys, yd, lrd, key = self._io_avals
            aval = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
            avals = (jax.tree_util.tree_map(aval, self._state[0]),
                     jax.tree_util.tree_map(aval, self._state[1]),
                     jax.ShapeDtypeStruct(xs, xd),
                     jax.ShapeDtypeStruct(ys, yd),
                     jax.ShapeDtypeStruct((), lrd), aval(key))
            cost = self._compiled.lower(*avals).compile().cost_analysis()
            return cost[0] if isinstance(cost, (list, tuple)) else cost
        except Exception:
            return None

    def sync_to_block(self):
        """Write the step's param state back into the Gluon parameters
        (copies — the compiled step donates its param buffers, and a
        handle aliasing a donated buffer dies on the next step)."""
        params, _ = self._state
        for h, raw in zip(self._handles, params):
            h._set_data(jnp.copy(raw))


# ---------------------------------------------------------------------------
# sharded checkpointing (reference: Module.save_checkpoint /
# Trainer.save_states, re-designed for SPMD: each process writes only its
# ADDRESSABLE shards — on a pod no host ever materializes a full tensor)
# ---------------------------------------------------------------------------


def _shard_key(name, arr, index):
    spans = []
    for sl, dim in zip(index, arr.shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        spans.append(f"{start}:{stop}")
    return name + "|" + ";".join(spans) if spans else name + "|"


def _iter_state_tensors(step):
    """Stable (key, raw_array) walk over params + optimizer states."""
    params, opt_states = step._state
    for n, p in zip(step._names, params):
        yield f"param::{n}", p
    for n, state in zip(step._names, opt_states):
        for li, leaf in enumerate(state):
            yield f"opt::{n}::{li}", leaf


def spmd_save_states(step, prefix):
    """Write this process's shards of the step's params + opt states to
    ``{prefix}.shard{process_index}.npz``. On a multi-host mesh every
    process writes its own file into a shared filesystem; together the
    files tile every global tensor exactly once (replicated tensors are
    written by their first replica only)."""
    import numpy as onp

    if step._state is None:
        raise MXNetError("save_states: call init_state()/step first")
    store = {}
    for key, raw in _iter_state_tensors(step):
        for shard in raw.addressable_shards:
            if shard.replica_id != 0:
                continue
            store[_shard_key(key, raw, shard.index)] = onp.asarray(shard.data)
    fname = f"{prefix}.shard{jax.process_index()}.npz"
    onp.savez(fname, **store)
    return fname


def spmd_load_states(step, prefix):
    """Restore a checkpoint written by ``spmd_save_states`` into the
    step's (already initialized) state, re-sharding each tensor with its
    CURRENT sharding — the mesh/spec layout may differ from save time
    (elastic restart, changed dp/tp split)."""
    import glob as _glob

    import numpy as onp

    if step._state is None:
        step.init_state()
    files = sorted(_glob.glob(f"{prefix}.shard*.npz"))
    if not files:
        raise MXNetError(f"no checkpoint shards match {prefix}.shard*.npz")
    # local-shard index map per tensor: only chunks overlapping THIS
    # process's shards are decompressed (the whole point of the sharded
    # format — no host materializes the full state)
    def _local_spans(like):
        spans = []
        for idx in like.sharding.addressable_devices_indices_map(
                like.shape).values():
            spans.append(tuple(
                (0 if sl.start is None else sl.start,
                 dim if sl.stop is None else sl.stop)
                for sl, dim in zip(idx, like.shape)))
        return spans

    wanted = {}
    for key, raw in _iter_state_tensors(step):
        wanted[key] = _local_spans(raw)

    chunks = {}
    for f in files:
        with onp.load(f) as z:
            for k in z.files:
                name, _, spans = k.rpartition("|")
                idx = tuple(slice(int(a), int(b)) for a, b in
                            (s.split(":") for s in spans.split(";") if s))
                local = wanted.get(name)
                if local is not None and idx:
                    src = [(sl.start, sl.stop) for sl in idx]
                    if not any(all(sb > ta and sa < tb for (sa, sb), (ta, tb)
                                   in zip(src, tgt)) for tgt in local):
                        continue  # chunk entirely on other hosts
                chunks.setdefault(name, []).append((idx, z[k]))
    params, opt_states = step._state
    new_params = []
    for n, p in zip(step._names, params):
        new_params.append(_reassemble(f"param::{n}", p, chunks))
    new_opt = []
    for n, state in zip(step._names, opt_states):
        new_opt.append(tuple(
            _reassemble(f"opt::{n}::{li}", leaf, chunks)
            for li, leaf in enumerate(state)))
    step._state = (new_params, new_opt)
    # push restored params back into the Gluon parameter handles so
    # eval/export paths see the checkpoint too. COPIES, not the state
    # arrays themselves: the compiled step donates its param buffers, and
    # a handle aliasing a donated buffer dies with it (observed as
    # "Array has been deleted" on the next init_state()).
    for h, raw in zip(step._handles, new_params):
        h._set_data(jnp.copy(raw))


def _reassemble(key, like, chunks):
    """Rebuild one global tensor under ``like``'s CURRENT sharding,
    materializing only this process's addressable shards (never the full
    tensor — that is the point of the sharded format on a pod)."""
    import numpy as onp

    if key not in chunks:
        raise MXNetError(f"checkpoint missing tensor {key!r}")

    def _span(sl, dim):
        return (0 if sl.start is None else sl.start,
                dim if sl.stop is None else sl.stop)

    sharding = like.sharding
    idx_map = sharding.addressable_devices_indices_map(like.shape)
    arrays = []
    for dev, tgt_idx in idx_map.items():
        tgt = [_span(sl, dim) for sl, dim in zip(tgt_idx, like.shape)]             if tgt_idx else []
        shard_shape = tuple(b - a for a, b in tgt)
        buf = onp.zeros(shard_shape, like.dtype)
        for src_idx, data in chunks[key]:
            src = [_span(sl, dim) for sl, dim in zip(src_idx, like.shape)]
            # overlap of the saved chunk and this target shard
            inter = [(max(sa, ta), min(sb, tb))
                     for (sa, sb), (ta, tb) in zip(src, tgt)]
            if any(b <= a for a, b in inter):
                continue
            dst_sl = tuple(slice(a - ta, b - ta)
                           for (a, b), (ta, _) in zip(inter, tgt))
            src_sl = tuple(slice(a - sa, b - sa)
                           for (a, b), (sa, _) in zip(inter, src))
            buf[dst_sl] = data[src_sl]
        arrays.append(jax.device_put(buf, dev))
    return jax.make_array_from_single_device_arrays(
        like.shape, sharding, arrays)


# method-style access, matching Trainer.save_states naming
SPMDTrainStep.save_states = spmd_save_states
SPMDTrainStep.load_states = spmd_load_states
