"""Fused SPMD train step over a device mesh.

This is the TPU-native training fast path (SURVEY.md §7.4): the whole
forward + backward + optimizer update compiles into ONE XLA executable with
sharding annotations; gradients are psum'd by XLA over the mesh's ``dp``
axis (replacing KVStore push/pull entirely). Tensor-parallel and
ZeRO-style state sharding are expressed as alternative param shardings on
the same step.

Uses the same "functionalize the imperative frontend" trick as CachedOp:
the Gluon block's Python forward runs once under tracing with parameter
handles bound to tracers.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import autograd
from .. import fusedstep as _fusedstep
from .. import observability as _obs
from .. import random as _random
from ..base import MXNetError
from ..gluon.block import _TRACE_STATE
from ..ndarray.ndarray import NDArray
from . import overlap as _overlap
from .compat import get_shard_map


def _put_global(raw, sharding):
    """Build a global array under ``sharding`` with each PROCESS serving
    its own addressable shards from ``raw`` (device_put would need
    cross-host transfers on a multi-process mesh, which CPU/DCN-less
    backends refuse). On a single process this degenerates to a plain
    sharded placement."""
    import numpy as onp

    host = onp.asarray(raw)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


def shard_batch(arr, mesh, axis_name="dp"):
    """Place a host batch sharded along its leading axis. On a
    multi-process mesh every process passes an array of the GLOBAL batch
    shape and contributes the rows its devices own (identical arrays
    everywhere -> the natural single-program semantics; per-rank data ->
    the global batch is the concatenation of each rank's owned rows)."""
    raw = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
    sharding = NamedSharding(mesh, P(axis_name, *([None] * (raw.ndim - 1))))
    if isinstance(raw, jax.Array):
        try:
            if raw.sharding.is_equivalent_to(sharding, raw.ndim):
                # already placed (e.g. staged ahead by DevicePrefetcher):
                # re-sharding would gather the global batch to host
                return raw
        except Exception:
            pass
    return _put_global(raw, sharding)


def replicate(arr, mesh):
    raw = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
    return _put_global(raw, NamedSharding(mesh, P()))


def _state_dtype(w):
    """Multi-precision rule (reference: ``mp_sgd_update``/``mp_adam_update``
    in optimizer_op): low-precision weights carry f32 optimizer state and
    update in f32 master math, casting back on write. This is also what
    keeps the step's avals STABLE: without it, ``lr(f32) * m(bf16)``
    promotes the new params to f32, every aval flips after step 1, and
    jit recompiles the whole train step (observed: 2 extra 60s compiles
    on BERT-base)."""
    return jnp.float32 if w.dtype in (jnp.bfloat16, jnp.float16) else w.dtype


def _sgd_rule(hyper):
    mom = hyper.get("momentum", 0.0)
    wd_const = hyper.get("wd", 0.0)

    def init(w):
        return (jnp.zeros(w.shape, _state_dtype(w)),) if mom else ()

    # ``wd`` defaults to the hyper constant but also accepts a traced
    # scalar operand (gluon.Trainer's fused update passes per-param
    # wd*wd_mult that way, so changing wd never retraces)
    def update(w, g, state, lr, wd=wd_const):
        dt = _state_dtype(w)
        w32, g32, lr32 = w.astype(dt), g.astype(dt), lr.astype(dt)
        g32 = g32 + wd * w32
        if mom:
            m = mom * state[0] - lr32 * g32
            return (w32 + m).astype(w.dtype), (m,)
        return (w32 - lr32 * g32).astype(w.dtype), ()

    return init, update


def _adam_rule(hyper):
    beta1 = hyper.get("beta1", 0.9)
    beta2 = hyper.get("beta2", 0.999)
    eps = hyper.get("epsilon", 1e-8)
    wd_const = hyper.get("wd", 0.0)

    def init(w):
        dt = _state_dtype(w)
        return (jnp.zeros(w.shape, dt), jnp.zeros(w.shape, dt),
                jnp.zeros((), jnp.int32))

    def update(w, g, state, lr, wd=wd_const):
        dt = _state_dtype(w)
        m, v, t = state
        t = t + 1
        w32, g32, lr32 = w.astype(dt), g.astype(dt), lr.astype(dt)
        g32 = g32 + wd * w32
        m = beta1 * m + (1 - beta1) * g32
        v = beta2 * v + (1 - beta2) * jnp.square(g32)
        tf = t.astype(dt)
        lr_t = lr32 * jnp.sqrt(1 - beta2 ** tf) / (1 - beta1 ** tf)
        return (w32 - lr_t * m / (jnp.sqrt(v) + eps)).astype(w.dtype), \
            (m, v, t)

    return init, update


def _lamb_rule(hyper):
    beta1 = hyper.get("beta1", 0.9)
    beta2 = hyper.get("beta2", 0.999)
    eps = hyper.get("epsilon", 1e-6)
    wd_const = hyper.get("wd", 0.0)

    def init(w):
        dt = _state_dtype(w)
        return (jnp.zeros(w.shape, dt), jnp.zeros(w.shape, dt),
                jnp.zeros((), jnp.int32))

    def update(w, g, state, lr, wd=wd_const):
        dt = _state_dtype(w)
        m, v, t = state
        t = t + 1
        w32, g32, lr32 = w.astype(dt), g.astype(dt), lr.astype(dt)
        m = beta1 * m + (1 - beta1) * g32
        v = beta2 * v + (1 - beta2) * jnp.square(g32)
        tf = t.astype(dt)
        m_hat = m / (1 - beta1 ** tf)
        v_hat = v / (1 - beta2 ** tf)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * w32
        w_norm = jnp.linalg.norm(w32)
        r_norm = jnp.linalg.norm(r)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (w32 - lr32 * ratio * r).astype(w.dtype), (m, v, t)

    return init, update


def _lamb_rule_sharded(hyper, axis_name):
    """lamb over a flat dp-shard (ZeRO-2/3): identical math to
    :func:`_lamb_rule` except the trust-ratio norms are computed as
    local-shard sums of squares reduced with ONE extra psum pair over
    the data axis — each flat array is one parameter, and its pad
    region is zeros in both w and r, so the reduced norms are the
    whole-parameter norms. This is what lets lamb keep stage 2/3
    instead of declining to stage 1."""
    beta1 = hyper.get("beta1", 0.9)
    beta2 = hyper.get("beta2", 0.999)
    eps = hyper.get("epsilon", 1e-6)
    wd_const = hyper.get("wd", 0.0)

    def init(w):
        dt = _state_dtype(w)
        return (jnp.zeros(w.shape, dt), jnp.zeros(w.shape, dt),
                jnp.zeros((), jnp.int32))

    def update(w, g, state, lr, wd=wd_const):
        dt = _state_dtype(w)
        m, v, t = state
        t = t + 1
        w32, g32, lr32 = w.astype(dt), g.astype(dt), lr.astype(dt)
        m = beta1 * m + (1 - beta1) * g32
        v = beta2 * v + (1 - beta2) * jnp.square(g32)
        tf = t.astype(dt)
        m_hat = m / (1 - beta1 ** tf)
        v_hat = v / (1 - beta2 ** tf)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * w32
        w_norm = jnp.sqrt(jax.lax.psum(jnp.sum(w32 * w32), axis_name))
        r_norm = jnp.sqrt(jax.lax.psum(jnp.sum(r * r), axis_name))
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (w32 - lr32 * ratio * r).astype(w.dtype), (m, v, t)

    return init, update


def _nag_rule(hyper):
    """Nesterov momentum, matching ``optimizer.NAG.update``."""
    mom = hyper.get("momentum", 0.0)
    wd_const = hyper.get("wd", 0.0)

    def init(w):
        return (jnp.zeros(w.shape, _state_dtype(w)),) if mom else ()

    def update(w, g, state, lr, wd=wd_const):
        dt = _state_dtype(w)
        w32, g32, lr32 = w.astype(dt), g.astype(dt), lr.astype(dt)
        g32 = g32 + wd * w32
        if mom:
            m = mom * state[0] + g32
            return (w32 - lr32 * (g32 + mom * m)).astype(w.dtype), (m,)
        return (w32 - lr32 * g32).astype(w.dtype), ()

    return init, update


_RULES = {"sgd": _sgd_rule, "nag": _nag_rule, "adam": _adam_rule,
          "adamw": _adam_rule, "lamb": _lamb_rule}

_MP_SENTINEL = object()


def mp_rule(rule_init, rule_update):
    """fp32 master-weight wrapper around a ``_RULES`` pair (reference:
    ``mp_sgd_update``/``mp_adam_update``): for bf16/fp16 params the
    fp32 master copy becomes STATE LEAF 0, so it lives (and is donated)
    in the same optimizer-state pytree as the moments — updates
    accumulate in the master across steps and the stored weight is a
    rounded VIEW of it, instead of being re-derived from the rounded
    weight every step (which loses updates smaller than one bf16 ulp).
    fp32 params pass through untouched, so one wrapped rule serves a
    mixed-precision param set."""

    from ..amp.policy import is_low_precision_dtype

    def init(w):
        if not is_low_precision_dtype(w.dtype):
            return rule_init(w)
        master = w.astype(jnp.float32)
        return (master,) + tuple(rule_init(master))

    def update(w, g, state, lr, wd=_MP_SENTINEL):
        kw = {} if wd is _MP_SENTINEL else {"wd": wd}
        if not is_low_precision_dtype(w.dtype):
            return rule_update(w, g, state, lr, **kw)
        master, inner = state[0], tuple(state[1:])
        new_master, new_inner = rule_update(
            master, g.astype(jnp.float32), inner, lr, **kw)
        return new_master.astype(w.dtype), \
            (new_master,) + tuple(new_inner)

    return init, update


def bucketed_psum(grads, axis_name, bucket_bytes=None):
    """Scan-compatible bucketed gradient allreduce: one ``lax.psum`` per
    ~``bucket_bytes`` dtype-homogeneous flat bucket instead of one per
    gradient tensor — the in-graph analog of the kvstore's bucketed
    pushpull (PR 3), usable inside ``shard_map``/``lax.scan`` bodies
    (pure, no host round trip, stable avals across iterations). Returns
    the reduced gradients in the original order/shapes/dtypes.

    This is what a K-step superstep body calls per iteration on a
    multi-device mesh: K iterations x one-psum-per-bucket, all inside a
    single dispatched executable."""
    from .. import fusedstep as _fusedstep

    target = int(bucket_bytes if bucket_bytes is not None
                 else _fusedstep.bucket_bytes())
    flat = [g.reshape(-1) for g in grads]
    # greedy dtype-homogeneous fill, preserving order within a dtype
    buckets = []  # [idx list, payload bytes], one per bucket
    open_by_dtype = {}
    for i, f in enumerate(flat):
        dt = f.dtype
        nbytes = f.size * f.dtype.itemsize
        cur = open_by_dtype.get(dt)
        if cur is None or (cur[1] + nbytes > target and cur[0]):
            cur = [[], 0]
            open_by_dtype[dt] = cur
            buckets.append(cur)
        cur[0].append(i)
        cur[1] += nbytes
    out = [None] * len(grads)
    for idxs, _ in buckets:
        if len(idxs) == 1:
            i = idxs[0]
            out[i] = jax.lax.psum(grads[i], axis_name)
            continue
        packed = jnp.concatenate([flat[i] for i in idxs])
        red = jax.lax.psum(packed, axis_name)
        off = 0
        for i in idxs:
            n = flat[i].size
            out[i] = red[off:off + n].reshape(grads[i].shape)
            off += n
    return out


class SPMDTrainStep:
    """One-executable train step for a Gluon block over a mesh.

    >>> step = SPMDTrainStep(net, loss_fn, "sgd", {"momentum": 0.9}, mesh)
    >>> loss = step(batch_x, batch_y, lr=0.1)
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, batch_axis="dp", param_sharding=None,
                 shard_opt_states=False, grad_dtype=None, donate=True,
                 multi_precision=False, zero_stage=None, overlap=None,
                 compression_params=None):
        self.block = block
        self.loss_fn = loss_fn
        if mesh is not None:
            from .mesh import validate_mesh_axes, axis_size
            validate_mesh_axes(mesh, "SPMDTrainStep")
            if axis_size(mesh, "pp") > 1:
                raise MXNetError(
                    "SPMDTrainStep shards data/tensor axes only; a "
                    f"pp={axis_size(mesh, 'pp')} mesh needs the "
                    "pipeline executor — use Composed4DStep (or "
                    "PipelineTrainStep for pp alone)")
        self.mesh = mesh
        self.batch_axis = batch_axis
        hyper = dict(optimizer_params or {})
        if optimizer not in _RULES:
            raise MXNetError(
                f"SPMD step supports {sorted(_RULES)}; got {optimizer}. "
                "Use gluon.Trainer for other optimizers.")
        self._optimizer_name = optimizer
        self._rule_init, self._rule_update = _RULES[optimizer](hyper)
        if multi_precision:
            # bf16/fp16 params carry fp32 masters as state leaf 0 —
            # sharded/donated with the rest of the opt-state pytree
            self._rule_init, self._rule_update = mp_rule(
                self._rule_init, self._rule_update)
        self._param_sharding = param_sharding or {}
        # ZeRO stage (SURVEY P13 / docs/performance.md "scale-out"):
        # 0 replicated, 1 sharded opt state (legacy shard_opt_states),
        # 2 reduce-scattered grads + flat-sharded opt state, 3 params
        # sharded at rest too (gathered just-in-time inside the step)
        if zero_stage is None:
            zero_stage = 1 if shard_opt_states else _fusedstep.zero_stage()
        if int(zero_stage) not in (0, 1, 2, 3):
            raise MXNetError(f"zero_stage must be 0-3, got {zero_stage}")
        self.zero_stage = int(zero_stage)
        # lamb + ZeRO-2/3: the overlap build swaps in _lamb_rule_sharded
        # (shard-local trust-ratio norms + one psum pair), so the stage
        # is kept — the factory inputs are stashed for that rebuild
        self._hyper = hyper
        self._multi_precision = bool(multi_precision)
        self._shard_opt_states = shard_opt_states or self.zero_stage == 1
        self._overlap_explicit = overlap is not None
        if overlap is None:
            self._overlap_mode = _fusedstep.overlap_mode()
        elif overlap is True:
            self._overlap_mode = "ready"
        elif overlap is False:
            self._overlap_mode = "barrier"
        else:
            self._overlap_mode = str(overlap)
        if self._overlap_mode not in ("ready", "barrier", "staged",
                                      "nocomm"):
            raise MXNetError(f"overlap mode {overlap!r} not one of "
                             "ready/barrier/staged (True/False ok)")
        # reduced-precision gradient communication: buckets are cast to
        # this dtype for the collective (summed in it) and back after
        self._grad_dtype = None if grad_dtype is None \
            else jnp.dtype(grad_dtype)
        self._compress_thr = None
        if compression_params:
            ctype = compression_params.get("type", "2bit")
            if ctype != "2bit":
                raise MXNetError(f"unsupported compression type {ctype}")
            self._compress_thr = float(
                compression_params.get("threshold", 0.5))
        self._donate = donate
        self._compiled = None
        self._state = None  # (params, aux, opt_states) raw pytrees
        self._names = None
        self._diff = None
        self._io_avals = None
        self._run_many = None
        self._last_loss = None
        self._mode = None  # resolved at init_state: jit|overlap|staged
        self._shapes = None  # logical per-param shapes (handle order)
        self._logical = {}  # checkpoint key -> logical flat length
        self._bucket_plan = None
        self._residuals = None  # per-bucket 2-bit compression carry
        self._staged = None  # staged-mode executables (bwd/comm/upd)

    # -- mode resolution ---------------------------------------------------
    def _dp_size(self):
        if self.mesh is None:
            return 1
        return dict(zip(self.mesh.axis_names,
                        self.mesh.devices.shape)).get(self.batch_axis, 1)

    def _nontrivial_sharding(self):
        return any(len(tuple(spec)) and any(s is not None for s in spec)
                   for spec in self._param_sharding.values())

    def _mesh_mode(self):
        """``jit`` (the GSPMD single-executable path: single device,
        tensor-parallel shardings, or ZeRO-1 constraints), ``overlap``
        (explicit ``shard_map`` step with bucket-ready collectives —
        ZeRO 0/2/3), or ``staged`` (host-driven backward/comm/update
        dispatches — the legacy architecture, kept for the exposed-comm
        ablation)."""
        def _jit(reason):
            # an explicitly requested non-default schedule has no
            # meaning on the GSPMD single-executable path — say so
            # instead of silently measuring the wrong thing
            if self._overlap_explicit and self._overlap_mode != "ready":
                _fusedstep.log_fallback(
                    "spmd", f"overlap={self._overlap_mode!r} has no "
                    f"effect on the {reason} GSPMD path; running the "
                    "single-executable step")
            return "jit"

        if self.mesh is None or self._dp_size() <= 1:
            return _jit("single-device")
        if self.zero_stage == 1:
            return _jit("ZeRO-1")
        if self._nontrivial_sharding():
            if self.zero_stage >= 2:
                # dp-axis opt-state sharding composes with the tensor
                # partition on the GSPMD path: each moment rides the
                # param's tp spec extended along its first free
                # dp-divisible dim (see _opt_state_spec) — GSPMD emits
                # the equivalent reduce-scatter/allgather itself, so
                # the stage-2 memory layout survives tp
                self._shard_opt_states = True
            return _jit("tensor-parallel")
        if self._overlap_mode == "staged":
            if self.zero_stage >= 2:
                _fusedstep.log_fallback(
                    "spmd", "staged mode has no ZeRO-2/3 layout; "
                    "running the in-graph barrier mode instead")
                # make the log true: collectives pinned behind the
                # whole backward, not the bucket-ready schedule
                self._overlap_mode = "barrier"
                return "overlap"
            if self._compress_thr is not None:
                _fusedstep.log_fallback(
                    "spmd", "staged mode has no compressed-comm path "
                    "(it is the uncompressed measurement baseline); "
                    "running the in-graph barrier mode instead")
                self._overlap_mode = "barrier"
                return "overlap"
            return "staged"
        return "overlap"

    # -- state management -------------------------------------------------
    def _collect(self):
        items = sorted(self.block.collect_params().items())
        names = [n for n, _ in items]
        handles = [p.data() for _, p in items]
        diff = [p.grad_req != "null" for _, p in items]
        return names, handles, diff

    def _sharding_for(self, name, raw):
        if self.mesh is None:
            return None
        spec = self._param_sharding.get(name, P())
        return NamedSharding(self.mesh, spec)

    def _opt_state_spec(self, name, raw):
        """ZeRO-1 (SURVEY P13): moment tensors shard along dim 0 over the
        data axis, unless the param itself is already sharded on dim 0
        (tensor parallel) or dim 0 doesn't divide, in which case they
        follow the param's sharding."""
        pspec = self._param_sharding.get(name, P())
        if not self._shard_opt_states or self.mesh is None:
            return pspec
        dp = dict(zip(self.mesh.axis_names, self.mesh.devices.shape)).get(
            self.batch_axis)
        if dp and len(pspec) > 0 and any(s is not None for s in pspec):
            # tensor-parallel param: compose the dp shard ORTHOGONALLY —
            # extend the tp spec along the first free dp-divisible dim
            # (the tp split already divides sharded dims, so the check
            # uses the tp-local extent)
            dims = list(pspec) + [None] * (raw.ndim - len(pspec))
            sizes = dict(zip(self.mesh.axis_names,
                             self.mesh.devices.shape))
            for d in range(raw.ndim):
                local = raw.shape[d] // sizes.get(dims[d], 1) \
                    if dims[d] is not None else raw.shape[d]
                if dims[d] is None and local % dp == 0:
                    dims[d] = self.batch_axis
                    return P(*dims)
            import logging

            logging.getLogger(__name__).warning(
                "ZeRO-%d: opt state for %r (shape %s, tp spec %s) has "
                "no free dp-divisible dim; this moment stays on the "
                "param sharding (replicated over dp)", self.zero_stage,
                name, tuple(raw.shape), pspec)
            return pspec
        if (dp and raw.ndim >= 1 and raw.shape[0] % dp == 0
                and not (len(pspec) > 0 and pspec[0] is not None)):
            return P(self.batch_axis, *([None] * (raw.ndim - 1)))
        if (dp and raw.ndim >= 1 and raw.shape[0] % dp != 0
                and not (len(pspec) > 0 and pspec[0] is not None)):
            # visible fallback: on a real pod a silently replicated moment
            # is an invisible memory-budget surprise
            import logging

            logging.getLogger(__name__).warning(
                "ZeRO-1: opt state for %r (shape %s) not divisible by "
                "dp=%d; falling back to the param sharding %s",
                name, tuple(raw.shape), dp, pspec)
        return pspec

    def init_state(self):
        names, handles, diff = self._collect()
        self._names, self._handles, self._diff = names, handles, diff
        self._shapes = [tuple(h.data.shape) for h in handles]
        self._mode = self._mesh_mode()
        if self._mode in ("overlap", "staged"):
            return self._init_state_overlap()
        params = []
        opt_states = []
        opt_specs = []
        commit_dev = None
        if self.mesh is None:
            # commit to the default device: eager-built arrays are
            # UNCOMMITTED while jit outputs are committed, and that
            # sharding flip alone recompiles the step after call 1
            commit_dev = jax.devices()[0]
        for n, h, d in zip(names, handles, diff):
            raw = h.data
            if self.mesh is not None:
                # per-process shard feeding (works across hosts) + a fresh
                # buffer: the compiled step DONATES its param buffers, and
                # a donated alias of the Gluon handle's array kills it (a
                # second step on the same block then dies with "Array has
                # been deleted")
                raw = _put_global(raw, self._sharding_for(n, raw))
            else:
                raw = jnp.copy(jax.device_put(raw, commit_dev))
            params.append(raw)
            if not d:
                opt_states.append(())
                opt_specs.append(())
                continue
            state = self._rule_init(raw)
            spec = self._opt_state_spec(n, raw)
            # only moment-shaped leaves get the ZeRO spec; scalars (step
            # counters) stay replicated
            leaf_specs = tuple(
                spec if getattr(leaf, "shape", ()) == raw.shape else P()
                for leaf in state)
            if self.mesh is not None:
                state = tuple(
                    _put_global(leaf, NamedSharding(self.mesh, sp))
                    for leaf, sp in zip(state, leaf_specs))
            else:
                state = tuple(jax.device_put(leaf, commit_dev)
                              for leaf in state)
            opt_states.append(state)
            opt_specs.append(leaf_specs)
        self._opt_specs = opt_specs
        self._state = (params, opt_states)

    def _init_state_overlap(self):
        """State layout for the shard_map (overlap/staged) modes:

        - ZeRO-0 / staged: params + opt states replicated on the mesh;
        - ZeRO-2: params replicated; every diff param's optimizer-state
          moment (and fp32 master) lives as a flat ``[pad]`` array
          zero-padded to a multiple of dp and SHARDED over the batch
          axis — each rank owns 1/dp of every optimizer tensor;
        - ZeRO-3: the diff params themselves take the same flat-sharded
          layout at rest and are allgathered just-in-time in the step.

        ``self._logical`` records the unpadded flat length per
        checkpoint key so sharded saves clip the pad and elastic
        restores re-pad for the NEW dp (the pad is layout, not state).
        """
        names, handles, diff = self._names, self._handles, self._diff
        dp = self._dp_size()
        axis = self.batch_axis
        stage = self.zero_stage
        repl = NamedSharding(self.mesh, P())
        shard1d = NamedSharding(self.mesh, P(axis))
        params, opt_states, opt_specs = [], [], []
        self._logical = {}
        for n, h, d in zip(names, handles, diff):
            raw = jnp.asarray(h.data)
            flat_pad = None
            if d and stage >= 2:
                pad = _overlap._ceil_to(raw.size, dp)
                flat_pad = _overlap.pad_flat(raw, pad)
            if d and stage == 3:
                params.append(_put_global(flat_pad, shard1d))
                self._logical[f"param::{n}"] = int(raw.size)
            else:
                params.append(_put_global(raw, repl))
            if not d:
                opt_states.append(())
                opt_specs.append(())
                continue
            basis = flat_pad if stage >= 2 else raw
            state = self._rule_init(basis)
            leaf_specs = tuple(
                P(axis) if (stage >= 2
                            and getattr(leaf, "shape", ()) == basis.shape)
                else P() for leaf in state)
            placed = []
            for li, (leaf, sp) in enumerate(zip(state, leaf_specs)):
                if len(sp) and sp[0] is not None:
                    placed.append(_put_global(leaf, shard1d))
                    self._logical[f"opt::{n}::{li}"] = int(raw.size)
                else:
                    placed.append(_put_global(leaf, repl))
            opt_states.append(tuple(placed))
            opt_specs.append(leaf_specs)
        self._opt_specs = opt_specs
        self._state = (params, opt_states)
        if _obs.ENABLED:
            rep = self.zero_memory_report()
            _obs.ZERO_STATE_BYTES.set(rep["opt_bytes_per_device"],
                                      kind="opt")
            _obs.ZERO_STATE_BYTES.set(rep["param_bytes_per_device"],
                                      kind="param")

    def zero_memory_report(self):
        """Per-device at-rest memory accounting for the current state
        layout vs a fully replicated baseline: what ZeRO actually buys.
        ``grad_bytes_per_device`` is the gradient footprint the step's
        communication output materializes (full grads under allreduce,
        1/dp shards under the ZeRO-2/3 reduce-scatter)."""
        params, opt_states = self._state
        diff = self._diff

        def dev_bytes(a):
            """Bytes ONE device holds: a replicated tensor costs its
            full size per device, a sharded one just its shard."""
            try:
                sh = a.addressable_shards
                if sh:
                    return int(sh[0].data.size) * a.dtype.itemsize
            except Exception:
                pass
            return int(a.size) * a.dtype.itemsize

        opt_dev = sum(dev_bytes(leaf) for st in opt_states for leaf in st)
        opt_full = sum(int(leaf.size) * leaf.dtype.itemsize
                       for st in opt_states for leaf in st)
        par_dev = sum(dev_bytes(p) for p in params)
        par_full = sum(int(p.size) * p.dtype.itemsize for p in params)
        dp = self._dp_size()
        grad_full = sum(int(p.size) * p.dtype.itemsize
                        for p, d in zip(params, diff) if d)
        grad_dev = grad_full // dp if self.zero_stage >= 2 and dp > 1 \
            else grad_full
        return {"zero_stage": self.zero_stage, "dp": dp,
                "opt_bytes_per_device": opt_dev,
                "opt_bytes_replicated": opt_full,
                "param_bytes_per_device": par_dev,
                "param_bytes_replicated": par_full,
                "grad_bytes_per_device": grad_dev,
                "grad_bytes_replicated": grad_full}

    def _diff_idx(self):
        return [i for i, d in enumerate(self._diff) if d]

    def _plan_buckets(self, x_aval, y_aval, run_forward):
        """Readiness order from the VJP structure + the bucket plan.
        The order probe traces ONE extra forward (host-side, build
        time); a failed trace falls back to reversed parameter order
        (the DDP heuristic) — never a build failure."""
        diff_idx = self._diff_idx()
        shapes = [self._shapes[i] for i in diff_idx]
        handles = self._handles
        dtypes = [jnp.asarray(handles[i].data).dtype for i in diff_idx]
        dp = self._dp_size()
        params = [jnp.asarray(h.data) for h in handles]

        def probe(diff_params, x, y, key):
            full = list(params)
            for i, p in zip(diff_idx, diff_params):
                full[i] = p
            lmean, _ = run_forward(full, x, y, key)
            return lmean

        diff_avals = [jax.ShapeDtypeStruct(s, dt)
                      for s, dt in zip(shapes, dtypes)]
        order = _overlap.first_use_order(
            probe, (diff_avals, x_aval, y_aval, jax.random.PRNGKey(0)),
            len(diff_idx))
        plan = _overlap.build_bucket_plan(
            shapes, dtypes, order=order,
            dp=dp if self.zero_stage >= 2 else 1)
        if _obs.ENABLED:
            _obs.OVERLAP_BUCKETS.set(len(plan), site="spmd_step")
        return plan

    def _init_residuals(self, plan):
        """Per-bucket 2-bit compression carry: one flat zeros array per
        bucket, ``[dp * payload]`` sharded over the batch axis so each
        rank owns exactly its own error-feedback state."""
        dp = self._dp_size()
        lens = _overlap.residual_shapes(plan, self.zero_stage >= 2)
        shard1d = NamedSharding(self.mesh, P(self.batch_axis))
        res = []
        for bi, (L, idxs) in enumerate(zip(lens, plan.buckets)):
            dt = jnp.dtype(plan.dtypes[idxs[0]])
            res.append(_put_global(jnp.zeros(dp * L, dt), shard1d))
        self._residuals = tuple(res)
        pending = getattr(self, "_pending_residual_chunks", None)
        if pending is not None:
            # checkpoint loaded before the first step compiled: the
            # saved carry was stashed by spmd_load_states
            self._pending_residual_chunks = None
            _restore_residuals(self, *pending)

    # -- compiled step ----------------------------------------------------
    def _make_run_forward(self):
        """The functionalized Gluon forward shared by every mode: binds
        raw arrays into the parameter handles, runs block + loss under
        tracing, returns (mean loss, mutated handle list). Under the
        shard_map modes ``x`` is this rank's batch shard, so the mean
        is the LOCAL mean — callers psum/dp it back to the global one."""
        block, loss_fn, handles = self.block, self.loss_fn, self._handles

        def run_forward(param_raws, x, y, key):
            _TRACE_STATE.active = True
            _random.push_trace_key(key)
            saved = [h._data_ for h in handles]
            try:
                for h, raw in zip(handles, param_raws):
                    h._data_ = raw
                xin = NDArray(x)
                yin = NDArray(y)
                with autograd._RecordingStateScope(False, True):
                    out = block(xin)
                    loss = loss_fn(out, yin)
                loss_raw = jnp.mean(loss.data)
                mutated = [h._data_ for h in handles]
                return loss_raw, mutated
            finally:
                for h, s in zip(handles, saved):
                    h._data_ = s
                _random.pop_trace_key()
                _TRACE_STATE.active = False

        return run_forward

    def _build(self, raw_x, raw_y):
        if self._mode in ("overlap", "staged") and self._bucket_plan \
                is None:
            dp = self._dp_size()
            xs = (raw_x.shape[0] // dp,) + tuple(raw_x.shape[1:])
            ys = (raw_y.shape[0] // dp,) + tuple(raw_y.shape[1:])
            self._bucket_plan = self._plan_buckets(
                jax.ShapeDtypeStruct(xs, raw_x.dtype),
                jax.ShapeDtypeStruct(ys, raw_y.dtype),
                self._make_run_forward())
            if self._compress_thr is not None \
                    and self._residuals is None:
                self._init_residuals(self._bucket_plan)
        if self._mode == "overlap":
            return self._build_overlap(raw_x.ndim, raw_y.ndim)
        if self._mode == "staged":
            return self._build_staged(raw_x.ndim, raw_y.ndim)
        return self._build_jit()

    def _in_out_specs(self):
        """shard_map in/out specs mirroring the state pytrees: flat
        ZeRO shards ride P(batch_axis), everything else replicated."""
        axis = self.batch_axis
        stage = self.zero_stage
        pspec = [P(axis) if (d and stage == 3) else P()
                 for d in self._diff]
        sspec = [tuple(sp for sp in specs) for specs in self._opt_specs]
        rspec = tuple([P(axis)] * (len(self._residuals)
                                   if self._residuals is not None else 0))
        return pspec, sspec, rspec

    def _build_overlap(self, ndim_x, ndim_y):
        """ONE executable: forward + backward + bucket-ready gradient
        collectives + (ZeRO-sharded) update, as an explicit shard_map
        over the batch axis. Each bucket's psum / psum_scatter depends
        only on its own gradients, so XLA's scheduler can start it the
        moment the bucket's last contributor exists — while the rest of
        backward still computes (``barrier`` mode pins an
        optimization_barrier in front of the collectives instead: same
        numerics, no early start; ``nocomm`` drops the collectives for
        the exposed-comm measurement and is numerically WRONG on
        purpose)."""
        mesh, axis = self.mesh, self.batch_axis
        dp = self._dp_size()
        stage = self.zero_stage
        barrier = self._overlap_mode == "barrier"
        nocomm = self._overlap_mode == "nocomm"
        diff_idx = self._diff_idx()
        diff_set = set(diff_idx)
        rule_update = self._rule_update
        if self._optimizer_name == "lamb" and stage >= 2:
            # flat-sharded update: swap in the trust-ratio rule that
            # reduces its norms over the data axis (the decline to
            # stage 1 this used to force is gone)
            ri, ru = _lamb_rule_sharded(self._hyper, axis)
            if self._multi_precision:
                ri, ru = mp_rule(ri, ru)
            rule_update = ru
        run_forward = self._make_run_forward()
        plan = self._bucket_plan
        comp = self._compress_thr
        wdt = self._grad_dtype
        inv_dp = 1.0 / dp

        def body(params, opt_states, residuals, x, y, lr, key):
            full = list(params)
            if stage == 3:
                # just-in-time param gather: each all_gather depends
                # only on its own shard, so XLA schedules it right
                # before the layer's first use (and the buffer dies
                # after backward) — params are 1/dp at rest
                for k, i in enumerate(diff_idx):
                    fl = _overlap.gather_shard(params[i], axis)
                    full[i] = _overlap.unpad_reshape(
                        fl, plan.sizes[k], plan.shapes[k])

            def loss_of(diff_params):
                f2 = list(full)
                for i, p in zip(diff_idx, diff_params):
                    f2[i] = p
                lmean, mutated = run_forward(f2, x, y, key)
                return lmean, mutated

            (lmean, mutated), grads = jax.value_and_grad(
                loss_of, has_aux=True)([full[i] for i in diff_idx])
            loss = jax.lax.psum(lmean, axis) * inv_dp
            res_in = list(residuals) if comp is not None else None
            if nocomm:
                if stage >= 2:
                    gparts = [_overlap.shard_of(g, plan, axis, k) * inv_dp
                              for k, g in enumerate(grads)]
                else:
                    gparts = [g * jnp.asarray(inv_dp, g.dtype)
                              for g in grads]
                new_res = res_in
            elif stage >= 2:
                gparts, new_res = _overlap.bucket_reduce_scatter(
                    grads, axis, plan, postscale=inv_dp, barrier=barrier,
                    compress=comp, residuals=res_in, wire_dtype=wdt)
            else:
                gparts, new_res = _overlap.bucket_allreduce(
                    grads, axis, plan, postscale=inv_dp, barrier=barrier,
                    compress=comp, residuals=res_in, wire_dtype=wdt)
            new_params = list(mutated)
            for i in range(len(new_params)):
                if i not in diff_set and new_params[i] is not full[i]:
                    # aux state the forward mutated (BN batch stats):
                    # average the per-shard updates so every rank keeps
                    # identical replicas
                    new_params[i] = jax.lax.psum(
                        new_params[i], axis) * jnp.asarray(
                            inv_dp, new_params[i].dtype)
            new_states = list(opt_states)
            for k, i in enumerate(diff_idx):
                if stage >= 2:
                    wsh = params[i] if stage == 3 \
                        else _overlap.shard_of(full[i], plan, axis, k)
                    w2, s2 = rule_update(wsh, gparts[k],
                                         opt_states[i], lr)
                    if stage == 2:
                        fl = _overlap.gather_shard(w2, axis)
                        new_params[i] = _overlap.unpad_reshape(
                            fl, plan.sizes[k], plan.shapes[k])
                    else:
                        new_params[i] = w2
                else:
                    w2, s2 = rule_update(full[i], gparts[k],
                                         opt_states[i], lr)
                    new_params[i] = w2
                new_states[i] = s2
            new_res_out = tuple(new_res) if comp is not None else ()
            return new_params, new_states, new_res_out, loss

        pspec, sspec, rspec = self._in_out_specs()
        shard_map = get_shard_map()
        in_specs = (pspec, sspec, rspec,
                    P(axis, *([None] * (ndim_x - 1))),
                    P(axis, *([None] * (ndim_y - 1))), P(), P())
        out_specs = (pspec, sspec, rspec, P())
        return jax.jit(
            shard_map(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False),
            donate_argnums=(0, 1, 2) if self._donate else ())

    def _build_staged(self, ndim_x, ndim_y):
        """The legacy three-dispatch architecture, kept as the
        exposed-comm baseline: (A) backward executable producing
        per-rank gradients, (B) one bucketed-allreduce executable
        (same per-bucket psum as the overlap mode — numerics
        identical), (C) replicated fused update. The host sits between
        every stage, so communication time is fully exposed — exactly
        what the overlap mode hides."""
        mesh, axis = self.mesh, self.batch_axis
        dp = self._dp_size()
        diff_idx = self._diff_idx()
        diff_set = set(diff_idx)
        rule_update = self._rule_update
        run_forward = self._make_run_forward()
        plan = self._bucket_plan
        inv_dp = 1.0 / dp
        nondiff_idx = [i for i in range(len(self._diff))
                       if i not in diff_set]
        shard_map = get_shard_map()

        def bwd_body(params, x, y, key):
            def loss_of(diff_params):
                f2 = list(params)
                for i, p in zip(diff_idx, diff_params):
                    f2[i] = p
                lmean, mutated = run_forward(f2, x, y, key)
                return lmean, mutated

            (lmean, mutated), grads = jax.value_and_grad(
                loss_of, has_aux=True)([params[i] for i in diff_idx])
            aux = [mutated[i][None] for i in nondiff_idx]
            return [g[None] for g in grads], aux, lmean[None]

        wdt = self._grad_dtype

        def comm_body(gstack, austack, lstack):
            gs = [g.reshape(g.shape[1:]) for g in gstack]
            reds, _ = _overlap.bucket_allreduce(gs, axis, plan,
                                                postscale=inv_dp,
                                                wire_dtype=wdt)
            auxs = [jax.lax.psum(a.reshape(a.shape[1:]), axis)
                    * jnp.asarray(inv_dp, a.dtype) for a in austack]
            loss = jax.lax.psum(lstack.reshape(()), axis) * inv_dp
            return reds, auxs, loss

        def upd(params, opt_states, grads, auxs, lr):
            new_params = list(params)
            for i, a in zip(nondiff_idx, auxs):
                new_params[i] = a
            new_states = list(opt_states)
            for k, i in enumerate(diff_idx):
                w2, s2 = rule_update(params[i], grads[k],
                                     opt_states[i], lr)
                new_params[i] = w2
                new_states[i] = s2
            return new_params, new_states

        pspec = [P()] * len(self._diff)
        bwd = jax.jit(shard_map(
            bwd_body, mesh=mesh,
            in_specs=(pspec, P(axis, *([None] * (ndim_x - 1))),
                      P(axis, *([None] * (ndim_y - 1))), P()),
            out_specs=([P(axis)] * len(diff_idx),
                       [P(axis)] * len(nondiff_idx), P(axis)),
            check_rep=False))
        comm = jax.jit(shard_map(
            comm_body, mesh=mesh,
            in_specs=([P(axis)] * len(diff_idx),
                      [P(axis)] * len(nondiff_idx), P(axis)),
            out_specs=([P()] * len(diff_idx),
                       [P()] * len(nondiff_idx), P()),
            check_rep=False))
        updj = jax.jit(upd, donate_argnums=(0, 1)
                       if self._donate else ())
        return {"bwd": bwd, "comm": comm, "upd": updj}

    def _build_jit(self):
        handles, diff = self._handles, self._diff
        rule_update = self._rule_update
        run_forward = self._make_run_forward()
        mesh = self.mesh
        opt_specs = getattr(self, "_opt_specs", None)

        def step(params, opt_states, x, y, lr, key):
            diff_idx = [i for i, d in enumerate(diff) if d]

            def loss_of(diff_params):
                full = list(params)
                for i, p in zip(diff_idx, diff_params):
                    full[i] = p
                loss, mutated = run_forward(full, x, y, key)
                return loss, mutated

            (loss, mutated), grads = jax.value_and_grad(loss_of, has_aux=True)(
                [params[i] for i in diff_idx]
            )
            new_params = list(mutated)  # aux (BN stats) updates carried here
            new_states = list(opt_states)
            for k, i in enumerate(diff_idx):
                w, s = rule_update(params[i], grads[k], opt_states[i], lr)
                if mesh is not None and opt_specs is not None and opt_specs[i]:
                    # pin ZeRO-1 shardings so XLA keeps moments sharded
                    # across steps instead of replicating them
                    s = tuple(
                        jax.lax.with_sharding_constraint(
                            leaf, NamedSharding(mesh, sp))
                        for leaf, sp in zip(s, opt_specs[i]))
                new_params[i] = w
                new_states[i] = s
            return new_params, new_states, loss

        donate = (0, 1) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)

    def __call__(self, x, y, lr=0.01, sync=True):
        if self._state is None:
            # resolve deferred init with one tiny eager pass. The probe
            # runs on a HOST copy of one row: the incoming batch may
            # already be mesh-sharded (DevicePrefetcher stages ahead),
            # and an eager forward mixing an 8-device input with
            # single-device params dies in dispatch.
            import numpy as onp

            raw = x.data if isinstance(x, NDArray) else jnp.asarray(x)
            if isinstance(raw, jax.Array) and raw.addressable_shards:
                host = onp.asarray(raw.addressable_shards[0].data)
            else:
                host = onp.asarray(raw)
            xin = NDArray(jnp.asarray(host[0:1] if host.shape[0] > 1
                                      else host))
            with autograd.predict_mode():
                self.block(xin)
            self.init_state()
        raw_x = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        raw_y = y.data if isinstance(y, NDArray) else jnp.asarray(y)
        if self.mesh is not None:
            raw_x = shard_batch(NDArray(raw_x), self.mesh, self.batch_axis)
            raw_y = shard_batch(NDArray(raw_y), self.mesh, self.batch_axis)
        if self._compiled is None and self._staged is None:
            built = self._build(raw_x, raw_y)
            if self._mode == "staged":
                self._staged = built
            else:
                self._compiled = built
        key = _random._next_key()
        lr_arr = jnp.asarray(lr, raw_x.dtype
                             if raw_x.dtype in (jnp.float32, jnp.bfloat16)
                             else jnp.float32)
        if self._mode == "staged":
            loss = self._call_staged(raw_x, raw_y, lr_arr, key)
            return float(loss) if sync else loss
        params, opt_states = self._state
        # only the small call-arg avals are kept; param/state avals are
        # rebuilt lazily from _state in cost_analysis() (keeps this hot
        # path free of an O(n_params) tree_map per step)
        self._io_avals = (raw_x.shape, raw_x.dtype, raw_y.shape, raw_y.dtype,
                          lr_arr.dtype, key)
        if self._mode == "overlap":
            res = self._residuals if self._residuals is not None else ()
            args = (params, opt_states, res, raw_x, raw_y, lr_arr, key)
        else:
            args = (params, opt_states, raw_x, raw_y, lr_arr, key)
        if _obs.introspect.ENABLED \
                and not _obs.introspect.registered("spmd_step"):
            _obs.introspect.register_jit(
                "spmd_step", self._compiled,
                _obs.introspect.avals_of(args), donated=self._donate)
        att = _obs.ENABLED and _obs.attribution.ENABLED
        t0 = time.perf_counter() if att else 0.0
        if _obs.flight.INSTALLED:
            with _obs.flight.dispatch("spmd_step"):
                out = self._compiled(*args)
        else:
            out = self._compiled(*args)
        if _obs.ENABLED:
            _obs.record_xla_dispatch("spmd_step")
            if att:
                # comm is in-graph here — the overlap probe's hint (by
                # mode) stands in for the unobservable wire time
                _obs.attribution.record_step(
                    t0, time.perf_counter(), site="spmd",
                    comm_mode=self._mode)
        if self._mode == "overlap":
            new_params, new_states, new_res, loss = out
            if self._compress_thr is not None:
                self._residuals = new_res
        else:
            new_params, new_states, loss = out
        self._state = (new_params, new_states)
        return float(loss) if sync else loss

    def _call_staged(self, raw_x, raw_y, lr_arr, key):
        """Three host-driven dispatches (backward / bucketed allreduce /
        update): communication is fully serialized behind the backward —
        the exposed-comm baseline the overlap mode is measured against."""
        st = self._staged
        params, opt_states = self._state
        att = _obs.ENABLED and _obs.attribution.ENABLED
        t0 = time.perf_counter() if att else 0.0
        gstack, austack, lstack = st["bwd"](params, raw_x, raw_y, key)
        tc = time.perf_counter() if att else 0.0
        reds, auxs, loss = st["comm"](gstack, austack, lstack)
        if att:
            # the comm leg is a separate host-driven dispatch here —
            # its host-side span IS observable, so attribution gets a
            # measured figure instead of the overlap-probe hint
            _obs.attribution.note_comm(time.perf_counter() - tc)
        new_params, new_states = st["upd"](params, opt_states, reds,
                                           auxs, lr_arr)
        if _obs.ENABLED:
            _obs.record_xla_dispatch("spmd_step", 3)
            if att:
                _obs.attribution.record_step(
                    t0, time.perf_counter(), site="spmd_staged")
        self._state = (new_params, new_states)
        return loss

    def run_steps(self, x, y, n, lr=0.01):
        """Run ``n`` steps on one batch inside a single executable
        (``lax.fori_loop`` over the compiled step) — the analog of the
        reference's bulked execution (``MXNET_EXEC_BULK_EXEC_TRAIN``):
        one dispatch instead of n, which matters on dispatch-latency-
        bound backends (the axon relay adds ~10ms/step to the Python
        loop). Per-step RNG keys are folded from one base key. Returns
        the final loss (device scalar)."""
        if self._state is None \
                or (self._compiled is None and self._staged is None) \
                or self._last_loss is None:
            # one plain step: resolves deferred init, compiles the inner
            # step, and seeds the loss carry with the right dtype
            self._last_loss = self(x, y, lr=lr, sync=False)
            n -= 1
            if n <= 0:
                return self._last_loss
        if self._mode == "staged":
            # the staged baseline is host-driven by definition: n
            # single steps, 3 dispatches each
            for _ in range(int(n)):
                self._last_loss = self(x, y, lr=lr, sync=False)
            return self._last_loss
        raw_x = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        raw_y = y.data if isinstance(y, NDArray) else jnp.asarray(y)
        if self.mesh is not None:
            raw_x = shard_batch(NDArray(raw_x), self.mesh, self.batch_axis)
            raw_y = shard_batch(NDArray(raw_y), self.mesh, self.batch_axis)
        lr_arr = jnp.asarray(lr, raw_x.dtype
                             if raw_x.dtype in (jnp.float32, jnp.bfloat16)
                             else jnp.float32)
        base_key = _random._next_key()
        inner = self._compiled
        has_res = self._mode == "overlap"

        if self._run_many is None:
            if has_res:
                def many(params, opt_states, residuals, xx, yy, lr_a,
                         key, loss0, n_steps):
                    def body(i, c):
                        p, s, r, _ = c
                        return inner(p, s, r, xx, yy, lr_a,
                                     jax.random.fold_in(key, i))

                    return jax.lax.fori_loop(
                        0, n_steps, body,
                        (params, opt_states, residuals, loss0))

                donate = (0, 1, 2) if self._donate else ()
            else:
                def many(params, opt_states, xx, yy, lr_a, key, loss0,
                         n_steps):
                    def body(i, c):
                        p, s, _ = c
                        return inner(p, s, xx, yy, lr_a,
                                     jax.random.fold_in(key, i))

                    # n_steps is a TRACED bound (lowers to while_loop):
                    # one compile covers every n
                    return jax.lax.fori_loop(0, n_steps, body,
                                             (params, opt_states, loss0))

                donate = (0, 1) if self._donate else ()
            self._run_many = jax.jit(many, donate_argnums=donate)
        params, opt_states = self._state
        if has_res:
            res = self._residuals if self._residuals is not None else ()
            new_params, new_states, new_res, loss = self._run_many(
                params, opt_states, res, raw_x, raw_y, lr_arr, base_key,
                self._last_loss, jnp.asarray(n, jnp.int32))
            if self._compress_thr is not None:
                self._residuals = new_res
        else:
            new_params, new_states, loss = self._run_many(
                params, opt_states, raw_x, raw_y, lr_arr, base_key,
                self._last_loss, jnp.asarray(n, jnp.int32))
        if _obs.ENABLED:
            _obs.record_xla_dispatch("spmd_step")
        self._state = (new_params, new_states)
        self._last_loss = loss
        return loss

    def run_superstep(self, xs, ys, lr=0.01):
        """K DISTINCT batches in one dispatch: ``lax.scan`` of the
        compiled step over stacked ``[K, ...]`` operands. ``run_steps``
        re-consumes ONE batch (a bulked micro-benchmark); this is the
        training superstep — each scan iteration consumes its own batch
        slot, so a real input pipeline (``gluon.data.SuperstepRing``)
        feeds it with the host touching the loop once per K steps.
        Per-iteration RNG keys fold from one base key. ``lr`` may be a
        scalar or a length-K vector (a per-iteration in-graph schedule:
        iteration i applies ``lr[i]``). Returns the per-iteration
        losses as a length-K device array (lazy)."""
        raw_x = xs.data if isinstance(xs, NDArray) else jnp.asarray(xs)
        raw_y = ys.data if isinstance(ys, NDArray) else jnp.asarray(ys)
        if self._state is None:
            # resolve deferred init + build state WITHOUT consuming an
            # update (a priming step would apply slot 0 twice): same
            # host-row predict probe as __call__
            import numpy as onp

            # one-time deferred-init probe (self._state is None exactly
            # once), never on the per-superstep path
            if isinstance(raw_x, jax.Array) and raw_x.addressable_shards:
                host = onp.asarray(  # mxtpu-lint: host-sync-ok
                    raw_x.addressable_shards[0].data)
            else:
                host = onp.asarray(raw_x)  # mxtpu-lint: host-sync-ok
            xin = NDArray(jnp.asarray(host[0][0:1] if host[0].ndim and
                                      host[0].shape[0] > 1 else host[0]))
            with autograd.predict_mode():
                self.block(xin)
            self.init_state()
        if self._compiled is None and self._staged is None:
            built = self._build(raw_x[0], raw_y[0])
            if self._mode == "staged":
                self._staged = built
            else:
                self._compiled = built
        k = int(raw_x.shape[0])
        lr_arr = jnp.asarray(lr, raw_x.dtype
                             if raw_x.dtype in (jnp.float32, jnp.bfloat16)
                             else jnp.float32)
        # per-iteration lr: a scalar broadcasts to all K slots; a
        # length-K vector applies lr[i] at scan iteration i (how the
        # Superstep's in-graph scheduler samples per step)
        lrs = jnp.full((k,), lr_arr) if lr_arr.ndim == 0 else lr_arr
        if lrs.shape != (k,):
            raise MXNetError(
                f"run_superstep: lr must be scalar or shape ({k},); "
                f"got {tuple(lr_arr.shape)}")
        if self._mode == "staged":
            # host-driven baseline: K staged steps
            losses = [self._call_staged(
                shard_batch(NDArray(raw_x[i]), self.mesh, self.batch_axis),
                shard_batch(NDArray(raw_y[i]), self.mesh, self.batch_axis),
                lrs[i], _random._next_key()) for i in range(k)]
            losses = jnp.stack(losses)
            self._last_loss = losses[-1]
            return losses
        if self.mesh is not None:
            # slot axis 0 stays unsharded; the per-iteration batch axis
            # (dim 1) shards over the mesh exactly like a single step's
            raw_x = _put_global(raw_x, NamedSharding(
                self.mesh, P(None, self.batch_axis,
                             *([None] * (raw_x.ndim - 2)))))
            raw_y = _put_global(raw_y, NamedSharding(
                self.mesh, P(None, self.batch_axis,
                             *([None] * (raw_y.ndim - 2)))))
        base_key = _random._next_key()
        inner = self._compiled
        has_res = self._mode == "overlap"

        if getattr(self, "_run_super", None) is None:
            if has_res:
                def many(params, opt_states, residuals, xxs, yys, lr_s,
                         keys):
                    def body(carry, slot):
                        p, s, r = carry
                        xx, yy, key, lr_i = slot
                        p2, s2, r2, loss = inner(p, s, r, xx, yy, lr_i,
                                                 key)
                        return (p2, s2, r2), loss

                    (p, s, r), losses = jax.lax.scan(
                        body, (params, opt_states, residuals),
                        (xxs, yys, keys, lr_s))
                    return p, s, r, losses

                donate = (0, 1, 2) if self._donate else ()
            else:
                def many(params, opt_states, xxs, yys, lr_s, keys):
                    def body(carry, slot):
                        p, s = carry
                        xx, yy, key, lr_i = slot
                        p2, s2, loss = inner(p, s, xx, yy, lr_i, key)
                        return (p2, s2), loss

                    (p, s), losses = jax.lax.scan(
                        body, (params, opt_states), (xxs, yys, keys, lr_s))
                    return p, s, losses

                donate = (0, 1) if self._donate else ()
            self._run_super = jax.jit(many, donate_argnums=donate)
        keys = jax.random.split(base_key, k)
        params, opt_states = self._state
        if has_res:
            res = self._residuals if self._residuals is not None else ()
            args = (params, opt_states, res, raw_x, raw_y, lrs, keys)
        else:
            args = (params, opt_states, raw_x, raw_y, lrs, keys)
        if _obs.introspect.ENABLED \
                and not _obs.introspect.registered("spmd_superstep"):
            _obs.introspect.register_jit(
                "spmd_superstep", self._run_super,
                _obs.introspect.avals_of(args), donated=self._donate)
        att = _obs.ENABLED and _obs.attribution.ENABLED
        t0 = time.perf_counter() if att else 0.0
        if _obs.flight.INSTALLED:
            with _obs.flight.dispatch("spmd_superstep"):
                out = self._run_super(*args)
        else:
            out = self._run_super(*args)
        if has_res:
            new_params, new_states, new_res, losses = out
            if self._compress_thr is not None:
                self._residuals = new_res
        else:
            new_params, new_states, losses = out
        if _obs.ENABLED:
            _obs.record_xla_dispatch("spmd_superstep")
            # per-iteration in-scan loss series, stored whole and lazy
            _obs.record_superstep_series(losses)
            if att:
                _obs.attribution.record_step(
                    t0, time.perf_counter(), k=k, site="spmd_superstep",
                    comm_mode=self._mode)
        self._state = (new_params, new_states)
        self._last_loss = losses[-1]
        return losses

    def cost_analysis(self):
        """XLA's cost analysis for the compiled step (``{"flops": ...}``),
        or None when the backend doesn't expose it (some PJRT plugins).
        NB: re-lowers and recompiles; on remote-compile backends this can
        take as long as the first step."""
        if self._compiled is None or self._io_avals is None:
            return None
        try:
            xs, xd, ys, yd, lrd, key = self._io_avals
            aval = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
            avals = (jax.tree_util.tree_map(aval, self._state[0]),
                     jax.tree_util.tree_map(aval, self._state[1]))
            if self._mode == "overlap":
                res = self._residuals if self._residuals is not None \
                    else ()
                avals += (jax.tree_util.tree_map(aval, res),)
            avals += (jax.ShapeDtypeStruct(xs, xd),
                      jax.ShapeDtypeStruct(ys, yd),
                      jax.ShapeDtypeStruct((), lrd), aval(key))
            cost = self._compiled.lower(*avals).compile().cost_analysis()
            return cost[0] if isinstance(cost, (list, tuple)) else cost
        except Exception:
            return None

    def _logical_view(self, i, raw):
        """A ZeRO-3 flat-padded param back in its logical shape (no-op
        for naturally shaped entries)."""
        shape = self._shapes[i] if self._shapes is not None else None
        if shape is not None and tuple(raw.shape) != tuple(shape):
            size = 1
            for d in shape:
                size *= int(d)
            return raw.reshape(-1)[:size].reshape(shape)
        return raw

    def sync_to_block(self):
        """Write the step's param state back into the Gluon parameters
        (copies — the compiled step donates its param buffers, and a
        handle aliasing a donated buffer dies on the next step). ZeRO-3
        flat-sharded params are gathered back to their logical shapes."""
        params, _ = self._state
        for i, (h, raw) in enumerate(zip(self._handles, params)):
            h._set_data(jnp.copy(self._logical_view(i, raw)))


# ---------------------------------------------------------------------------
# sharded checkpointing (reference: Module.save_checkpoint /
# Trainer.save_states, re-designed for SPMD: each process writes only its
# ADDRESSABLE shards — on a pod no host ever materializes a full tensor)
# ---------------------------------------------------------------------------


def _shard_key(name, arr, index):
    spans = []
    for sl, dim in zip(index, arr.shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        spans.append(f"{start}:{stop}")
    return name + "|" + ";".join(spans) if spans else name + "|"


def _iter_state_tensors(step):
    """Stable (key, raw_array) walk over params + optimizer states +
    any 2-bit compression residual carry."""
    params, opt_states = step._state
    for n, p in zip(step._names, params):
        yield f"param::{n}", p
    for n, state in zip(step._names, opt_states):
        for li, leaf in enumerate(state):
            yield f"opt::{n}::{li}", leaf
    res = getattr(step, "_residuals", None)
    if res:
        for bi, r in enumerate(res):
            yield f"residual::{bi}", r


def _clipped_shard_chunks(raw, logical):
    """Pad-clipped ``(index, host_array)`` chunks of one state tensor:
    one chunk per replica-0 addressable shard, with flat ZeRO spans
    clipped to the tensor's LOGICAL length (the pad is LAYOUT — a
    function of this mesh's dp — not state, so an elastic restore with
    a different dp/pad reads pure-logical coordinates). Slice bounds
    are normalized to concrete ints."""
    import numpy as onp

    out = []
    for shard in raw.addressable_shards:
        if shard.replica_id != 0:
            continue
        idx = tuple(
            slice(0 if sl.start is None else int(sl.start),
                  int(dim) if sl.stop is None else int(sl.stop))
            for sl, dim in zip(shard.index, raw.shape))
        data = onp.asarray(shard.data)
        if logical is not None and idx:
            start, stop = idx[0].start, idx[0].stop
            if start >= logical:
                continue  # shard is entirely pad
            if stop > logical:
                data = data[:logical - start]
                idx = (slice(start, logical),) + tuple(idx[1:])
        out.append((idx, data))
    return out


def spmd_state_snapshot(step, copy=True):
    """Checkpoint-in-memory: the step's complete state as pad-clipped
    LOGICAL-span host chunks ``{key: [(index, np.ndarray), ...]}`` plus
    the residual-extent map — exactly what :func:`spmd_save_states`
    writes to disk, minus the disk leg. With ``copy`` (the default)
    every leaf is first snapshotted in ONE donation-safe jit copy
    dispatch (the PR-8 snapshot protocol) with the device->host
    transfers kicked off asynchronously, so the live state can keep
    being stepped (and donated) while the handoff drains. This is the
    elastic-resize handoff format: :func:`spmd_restore_chunks` re-pads
    and re-shards it onto ANY new mesh/stage layout. On a multi-host
    mesh each process snapshots only its addressable shards."""
    if step._state is None:
        raise MXNetError("state_snapshot: call init_state()/step first")
    items = list(_iter_state_tensors(step))
    if copy:
        from ..resilience.checkpoint import _copy_leaves

        copies = _copy_leaves([jnp.asarray(raw) for _, raw in items])
        for c in copies:
            try:  # start the device->host transfer now
                c.copy_to_host_async()
            except Exception:
                pass
        items = [(k, c) for (k, _), c in zip(items, copies)]
    logical = getattr(step, "_logical", None) or {}
    chunks = {}
    extents = {}
    for key, raw in items:
        chunks[key] = _clipped_shard_chunks(raw, logical.get(key))
        if key.startswith("residual::"):
            extents[key] = int(raw.shape[0])
    return chunks, extents


def spmd_save_states(step, prefix):
    """Write this process's shards of the step's params + opt states to
    ``{prefix}.shard{process_index}.npz``. On a multi-host mesh every
    process writes its own file into a shared filesystem; together the
    files tile every global tensor exactly once (replicated tensors are
    written by their first replica only)."""
    import numpy as onp

    if step._state is None:
        raise MXNetError("save_states: call init_state()/step first")
    store = {}
    logical = getattr(step, "_logical", None) or {}
    for key, raw in _iter_state_tensors(step):
        for idx, data in _clipped_shard_chunks(raw, logical.get(key)):
            store[_shard_key(key, raw, idx)] = data
    fname = f"{prefix}.shard{jax.process_index()}.npz"
    onp.savez(fname, **store)
    return fname


def spmd_load_states(step, prefix):
    """Restore a checkpoint written by ``spmd_save_states`` into the
    step's (already initialized) state, re-sharding each tensor with its
    CURRENT sharding — the mesh/spec layout may differ from save time
    (elastic restart, changed dp/tp split)."""
    import glob as _glob

    import numpy as onp

    if step._state is None:
        step.init_state()
    files = sorted(_glob.glob(f"{prefix}.shard*.npz"))
    if not files:
        raise MXNetError(f"no checkpoint shards match {prefix}.shard*.npz")
    # local-shard index map per tensor: only chunks overlapping THIS
    # process's shards are decompressed (the whole point of the sharded
    # format — no host materializes the full state)
    def _local_spans(like):
        spans = []
        for idx in like.sharding.addressable_devices_indices_map(
                like.shape).values():
            spans.append(tuple(
                (0 if sl.start is None else sl.start,
                 dim if sl.stop is None else sl.stop)
                for sl, dim in zip(idx, like.shape)))
        return spans

    logical = getattr(step, "_logical", None) or {}
    wanted = {}
    all_pad = set()
    for key, raw in _iter_state_tensors(step):
        spans = _local_spans(raw)
        lg = logical.get(key)
        if lg is not None:
            # padded flat shards only want their LOGICAL sub-span (the
            # pad region reassembles to zeros, its init value)
            spans = [((s0, min(s1, lg)),) + tuple(rest)
                     for (s0, s1), *rest in spans if s0 < lg]
            if not spans:
                # every shard THIS process holds is pure pad (a tensor
                # smaller than the new dp on a multi-host mesh): there
                # is legitimately nothing to read — reassemble zeros
                all_pad.add(key)
        wanted[key] = spans

    chunks = {}
    res_extent = {}
    for f in files:
        with onp.load(f) as z:
            for k in z.files:
                name, _, spans = k.rpartition("|")
                idx = tuple(slice(int(a), int(b)) for a, b in
                            (s.split(":") for s in spans.split(";") if s))
                if name.startswith("residual::") and idx:
                    # saved GLOBAL length, recorded before the local-span
                    # filter below can discard out-of-range chunks — the
                    # dp-layout guard in _restore_residuals needs it
                    res_extent[name] = max(res_extent.get(name, 0),
                                           idx[0].stop)
                local = wanted.get(name)
                if local is not None and idx:
                    src = [(sl.start, sl.stop) for sl in idx]
                    # only span-filter chunks saved in the SAME layout
                    # as the target (zip would silently truncate a
                    # flat-vs-natural rank mismatch); layout-crossing
                    # chunks all flow to _reassemble_cross
                    if all(len(t) == len(src) for t in local) and \
                            not any(all(sb > ta and sa < tb
                                        for (sa, sb), (ta, tb)
                                        in zip(src, tgt))
                                    for tgt in local):
                        continue  # chunk entirely on other hosts
                chunks.setdefault(name, []).append((idx, z[k]))
    spmd_restore_chunks(step, chunks, extents=res_extent,
                        allow_empty=all_pad)


def spmd_restore_chunks(step, chunks, extents=None, allow_empty=()):
    """Restore a logical-coordinate chunk set — an in-memory
    :func:`spmd_state_snapshot` (the elastic-resize handoff) or the
    span-filtered contents of a shard-file set — into the step's
    CURRENT state layout: every tensor is reassembled, re-padded and
    re-sharded for the mesh/stage the step has NOW, entirely
    host/device-side. ``extents`` maps ``residual::N`` keys to their
    saved global lengths (the dp-layout guard for the compression
    carry); ``allow_empty`` names keys whose local shards are entirely
    pad (multi-host flat tensors smaller than dp)."""
    if step._state is None:
        step.init_state()
    extents = extents or {}
    params, opt_states = step._state
    new_params = []
    for n, p in zip(step._names, params):
        new_params.append(_reassemble(f"param::{n}", p, chunks,
                                      allow_empty=f"param::{n}"
                                      in allow_empty))
    new_opt = []
    for n, state in zip(step._names, opt_states):
        new_opt.append(tuple(
            _reassemble(f"opt::{n}::{li}", leaf, chunks,
                        allow_empty=f"opt::{n}::{li}" in allow_empty)
            for li, leaf in enumerate(state)))
    step._state = (new_params, new_opt)
    res = getattr(step, "_residuals", None)
    res_chunks = {k: v for k, v in chunks.items()
                  if k.startswith("residual::")}
    if res:
        _restore_residuals(step, res_chunks, extents)
    elif res_chunks and getattr(step, "_compress_thr", None) is not None:
        # the carry tensors are created lazily by _init_residuals at
        # the first compiled step (the bucket plan needs a batch):
        # stash the saved chunks so they restore there instead of
        # being silently zeroed
        step._pending_residual_chunks = (res_chunks, extents)
    # push restored params back into the Gluon parameter handles so
    # eval/export paths see the checkpoint too. COPIES, not the state
    # arrays themselves: the compiled step donates its param buffers, and
    # a handle aliasing a donated buffer dies with it (observed as
    # "Array has been deleted" on the next init_state()). ZeRO-3 flat
    # entries go back in their logical shapes.
    for i, (h, raw) in enumerate(zip(step._handles, new_params)):
        h._set_data(jnp.copy(step._logical_view(i, raw)))


def _reassemble_cross(key, like, saved):
    """Layout-crossing restore: flat padded ZeRO shards into a
    natural-layout target (elastic shrink to a single device, or
    loading into a lower zero_stage) or natural shards into a flat
    target (raising the stage). Rebuilds the full LOGICAL tensor on
    the host first — the elastic fallback path, not the steady-state
    sharded format."""
    import numpy as onp

    src_nd = {len(idx) for idx, _ in saved if idx}
    if len(src_nd) != 1:
        raise MXNetError(
            f"checkpoint tensor {key!r}: mixed chunk layouts {src_nd}")
    if src_nd == {1}:
        # flat-saved -> natural target: everything past the logical
        # length (= the natural element count) is dp pad
        logical = int(onp.prod(like.shape, dtype=onp.int64)) \
            if like.shape else 1
        flat = onp.zeros((logical,), like.dtype)
        for idx, data in saved:
            a = idx[0].start or 0
            b = min(idx[0].stop, logical)
            if a < b:
                flat[a:b] = data[: b - a]
        full = flat.reshape(like.shape)
    else:
        # natural-saved -> flat target: the shard files tile the
        # natural tensor exactly, so its shape is the span union
        nd = src_nd.pop()
        shape = tuple(max(idx[d].stop for idx, _ in saved)
                      for d in range(nd))
        nat = onp.zeros(shape, like.dtype)
        for idx, data in saved:
            nat[idx] = data
        if nat.size > like.shape[0]:
            raise MXNetError(
                f"checkpoint tensor {key!r}: natural size {nat.size} "
                f"exceeds the flat layout length {like.shape[0]}")
        full = onp.zeros(like.shape, like.dtype)
        full[:nat.size] = nat.reshape(-1)
    sharding = like.sharding
    idx_map = sharding.addressable_devices_indices_map(like.shape)
    arrays = [jax.device_put(onp.ascontiguousarray(full[tgt_idx]), dev)
              for dev, tgt_idx in idx_map.items()]
    return jax.make_array_from_single_device_arrays(
        like.shape, sharding, arrays)


def _restore_residuals(step, chunks, extents):
    """Restore the 2-bit error-feedback carry (``residual::N``).
    PER-RANK state with a dp-interleaved ``[dp, payload/dp]`` element
    layout: it only restores exactly onto the same dp layout; an
    elastic restart restarts the carry from zeros (one warning,
    bounded error — one quantization step's worth). ``extents`` maps
    each key to its saved GLOBAL length — compared against the current
    length because ``chunks`` was pre-filtered to this process's
    current spans, which would otherwise hide a dp-shrink mismatch."""
    import logging

    new_res = []
    for bi, r in enumerate(step._residuals):
        key = f"residual::{bi}"
        saved = chunks.get(key, [])
        fits = saved and extents.get(key) == r.shape[0] and all(
            (idx[0].stop or r.shape[0]) <= r.shape[0]
            for idx, _ in saved if idx)
        if fits:
            new_res.append(_reassemble(key, r, chunks))
        else:
            logging.getLogger(__name__).warning(
                "load_states: compression residual %s does not "
                "match the current dp layout; restarting the "
                "error-feedback carry from zeros", key)
            new_res.append(r)
    step._residuals = tuple(new_res)


def _reassemble(key, like, chunks, allow_empty=False):
    """Rebuild one global tensor under ``like``'s CURRENT sharding,
    materializing only this process's addressable shards (never the full
    tensor — that is the point of the sharded format on a pod).
    ``allow_empty``: this process's shards are entirely pad (a flat
    ZeRO tensor smaller than dp), so a missing chunk set means zeros,
    not a corrupt checkpoint."""
    import numpy as onp

    if key not in chunks and not allow_empty:
        raise MXNetError(f"checkpoint missing tensor {key!r}")

    saved = chunks.get(key, [])
    src_nd = {len(idx) for idx, _ in saved if idx}
    if src_nd and src_nd != {len(like.shape)}:
        # saved layout differs from the target layout (flat ZeRO
        # shards vs the natural GSPMD/jit shapes)
        return _reassemble_cross(key, like, saved)

    def _span(sl, dim):
        return (0 if sl.start is None else sl.start,
                dim if sl.stop is None else sl.stop)

    sharding = like.sharding
    idx_map = sharding.addressable_devices_indices_map(like.shape)
    arrays = []
    for dev, tgt_idx in idx_map.items():
        tgt = [_span(sl, dim) for sl, dim in zip(tgt_idx, like.shape)]             if tgt_idx else []
        shard_shape = tuple(b - a for a, b in tgt)
        buf = onp.zeros(shard_shape, like.dtype)
        for src_idx, data in chunks.get(key, []):
            src = [_span(sl, dim) for sl, dim in zip(src_idx, like.shape)]
            # overlap of the saved chunk and this target shard
            inter = [(max(sa, ta), min(sb, tb))
                     for (sa, sb), (ta, tb) in zip(src, tgt)]
            if any(b <= a for a, b in inter):
                continue
            dst_sl = tuple(slice(a - ta, b - ta)
                           for (a, b), (ta, _) in zip(inter, tgt))
            src_sl = tuple(slice(a - sa, b - sa)
                           for (a, b), (sa, _) in zip(inter, src))
            buf[dst_sl] = data[src_sl]
        arrays.append(jax.device_put(buf, dev))
    return jax.make_array_from_single_device_arrays(
        like.shape, sharding, arrays)


# method-style access, matching Trainer.save_states naming
SPMDTrainStep.save_states = spmd_save_states
SPMDTrainStep.load_states = spmd_load_states
