"""Mixture-of-Experts with expert parallelism (P12).

No reference counterpart (SURVEY.md §2.5 P12 — "does not exist in the
reference"; previously a documented drop). TPU-native design: the
classic mesh-tensorflow/GShard algorithm — top-1/top-2 gating with
capacity, einsum dispatch/combine, experts sharded over an ``ep`` mesh
axis inside ``shard_map`` so each device runs only its local experts.

Two dispatch paths:

- :func:`moe_apply` — tokens replicated, the dispatch einsum reshards
  onto locally-sharded expert tensors (XLA lowers the movement to an
  all-to-all over ICI). Simple, but the whole exchange is one opaque
  collective.
- :func:`moe_apply_a2a` — tokens sharded over ``ep``; each shard routes
  its own tokens, then an EXPLICIT ``lax.all_to_all`` carries the
  per-expert queues to their owners, the experts run, and a second
  all-to-all brings results home. The capacity axis is split into
  ``MXTPU_MOE_A2A_CHUNKS`` segments so the compiler can hide segment
  k+1's exchange behind segment k's expert matmuls — the same
  bucket-style overlap the PR-10 gradient path uses. The win is
  measured, not assumed: :func:`measure_moe_overlap` times
  nocomm/chunked/serial variants and publishes
  ``mxtpu_moe_a2a_hidden_fraction``.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError


def top1_routing(gate_logits, num_experts, capacity):
    """Top-1 router with capacity (GShard): returns (dispatch (T,E,C),
    combine (T,E,C), aux_loss). Tokens beyond an expert's capacity drop
    (standard semantics)."""
    T = gate_logits.shape[0]
    probs = jax.nn.softmax(gate_logits, axis=-1)           # (T, E)
    expert = jnp.argmax(probs, axis=-1)                    # (T,)
    onehot = jax.nn.one_hot(expert, num_experts)           # (T, E)
    # position of each token within its expert's queue (0-based; the
    # onehot factor keeps non-selected experts from contributing)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot      # (T, E)
    pos_in_expert = jnp.sum(pos, axis=-1)                  # (T,)
    keep = pos_in_expert < capacity
    pos_oh = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity)
    dispatch = onehot[:, :, None] * pos_oh[:, None, :] \
        * keep[:, None, None]                              # (T, E, C)
    gate_val = jnp.sum(probs * onehot, axis=-1)            # (T,)
    combine = dispatch * gate_val[:, None, None]
    # load-balance auxiliary loss (Shazeer et al.): E * <fraction, prob>
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def top2_routing(gate_logits, num_experts, capacity):
    """Top-2 router with capacity (GShard §3.2): each token goes to its
    two highest-probability experts with renormalized combine weights;
    first choices take queue priority (second choices fill in behind
    ALL first choices, so congestion drops them first). Returns
    (dispatch (T,E,C), combine (T,E,C), aux_loss) — aux is the same
    load-balance form as top-1, over first-choice assignments."""
    probs = jax.nn.softmax(gate_logits, axis=-1)           # (T, E)
    e1 = jnp.argmax(probs, axis=-1)
    oh1 = jax.nn.one_hot(e1, num_experts)                  # (T, E)
    e2 = jnp.argmax(probs * (1.0 - oh1), axis=-1)
    oh2 = jax.nn.one_hot(e2, num_experts)

    # first-choice queue positions; second choices queue behind every
    # first choice of the same expert (GShard's priority rule)
    pos1 = jnp.sum((jnp.cumsum(oh1, axis=0) - 1.0) * oh1, axis=-1)
    cnt1 = jnp.sum(oh1, axis=0)                            # (E,)
    pos2 = jnp.sum(((jnp.cumsum(oh2, axis=0) - 1.0)
                    + cnt1[None, :]) * oh2, axis=-1)
    keep1 = pos1 < capacity
    keep2 = pos2 < capacity
    # out-of-range positions one_hot to a zero row, but mask anyway
    d1 = oh1[:, :, None] * jax.nn.one_hot(
        pos1.astype(jnp.int32), capacity)[:, None, :] * keep1[:, None, None]
    d2 = oh2[:, :, None] * jax.nn.one_hot(
        pos2.astype(jnp.int32), capacity)[:, None, :] * keep2[:, None, None]
    dispatch = d1 + d2
    g1 = jnp.sum(probs * oh1, axis=-1)
    g2 = jnp.sum(probs * oh2, axis=-1)
    denom = g1 + g2 + 1e-9
    combine = d1 * (g1 / denom)[:, None, None] \
        + d2 * (g2 / denom)[:, None, None]
    frac = jnp.mean(oh1, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


_ROUTERS = {"top1": top1_routing, "top2": top2_routing}


def _router_fn(router):
    from .. import fusedstep
    name = router or fusedstep.moe_router()
    if name not in _ROUTERS:
        raise MXNetError(f"unknown MoE router {name!r} "
                         f"(one of {sorted(_ROUTERS)})")
    return name, _ROUTERS[name]


def init_moe_params(key, d_model, d_hidden, num_experts):
    k1, k2, k3 = jax.random.split(key, 3)
    scale = 1.0 / jnp.sqrt(d_model)
    return {
        "gate": jax.random.normal(k1, (d_model, num_experts)) * scale,
        "w1": jax.random.normal(k2, (num_experts, d_model, d_hidden))
        * scale,
        "w2": jax.random.normal(k3, (num_experts, d_hidden, d_model))
        * (1.0 / jnp.sqrt(d_hidden)),
    }


def moe_apply(params, x, mesh=None, axis_name="ep", capacity_factor=1.5,
              router="top1"):
    """MoE FFN over tokens x (T, d). Experts shard over ``axis_name``
    when a mesh is given (expert parallelism); single-device otherwise.
    ``router``: ``top1`` (default) or ``top2``; ``None`` reads
    ``MXTPU_MOE_ROUTER``. Returns (out (T, d), aux_loss)."""
    E = params["w1"].shape[0]
    T, D = x.shape
    capacity = int(max(1, (T / E) * capacity_factor))
    gate_logits = x @ params["gate"]
    _, route = _router_fn(router)
    dispatch, combine, aux = route(gate_logits, E, capacity)
    expert_in = jnp.einsum("td,tec->ecd", x, dispatch)      # (E, C, d)

    def run_experts(w1, w2, ein):
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", ein, w1))
        return jnp.einsum("ech,ehd->ecd", h, w2)

    if mesh is None:
        expert_out = run_experts(params["w1"], params["w2"], expert_in)
    else:
        if E % mesh.shape[axis_name]:
            raise MXNetError(
                f"experts {E} must divide mesh axis {axis_name} "
                f"({mesh.shape[axis_name]})")
        from .compat import get_shard_map
        shard_map = get_shard_map()

        expert_out = shard_map(
            run_experts, mesh=mesh,
            in_specs=(P(axis_name), P(axis_name), P(axis_name)),
            out_specs=P(axis_name),
        )(params["w1"], params["w2"], expert_in)
    out = jnp.einsum("ecd,tec->td", expert_out, combine)
    return out, aux


def shard_moe_params(params, mesh, axis_name="ep"):
    """Place expert tensors with the expert axis over ``ep``; the gate is
    replicated."""
    out = dict(params)
    out["w1"] = jax.device_put(params["w1"],
                               NamedSharding(mesh, P(axis_name)))
    out["w2"] = jax.device_put(params["w2"],
                               NamedSharding(mesh, P(axis_name)))
    out["gate"] = jax.device_put(params["gate"], NamedSharding(mesh, P()))
    return out


def _run_experts(w1, w2, ein):
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", ein, w1))
    return jnp.einsum("ech,ehd->ecd", h, w2)


def moe_apply_a2a(params, x, mesh, axis_name="ep", capacity_factor=None,
                  router=None, chunks=None, comm="chunked"):
    """MoE FFN with tokens sharded over ``axis_name`` and the expert
    exchange as explicit chunked ``lax.all_to_all`` inside the compiled
    step.

    Each token shard routes locally (capacity is per shard per expert),
    builds its (E, C, d) per-expert queues, and the all-to-all regroups
    them so each rank holds the full inbound queue of its own E/ep
    experts. The capacity axis is cut into ``chunks`` segments — one
    all-to-all + expert matmul + return all-to-all per segment — so the
    scheduler can run segment k+1's exchange under segment k's compute.

    ``comm``: ``chunked`` (default) | ``serial`` (one exchange) |
    ``nocomm`` (probe baseline: the exchange is replaced by a local
    relayout of identical shape, measuring pure compute).
    Returns (out (T, d), aux_loss); out rides the same token sharding.
    """
    from .. import fusedstep

    E = params["w1"].shape[0]
    T, D = x.shape
    ep = mesh.shape[axis_name]
    cf = capacity_factor if capacity_factor is not None \
        else fusedstep.moe_capacity_factor()
    k = chunks if chunks is not None else fusedstep.moe_a2a_chunks()
    if comm != "chunked":
        k = 1
    if E % ep:
        raise MXNetError(f"experts {E} must divide mesh axis "
                         f"{axis_name} ({ep})")
    if T % ep:
        raise MXNetError(f"tokens {T} must divide mesh axis "
                         f"{axis_name} ({ep}) for a2a dispatch")
    E_l, T_l = E // ep, T // ep
    cap = int(max(1, (T_l / E) * cf))
    cap = -(-cap // k) * k  # pad to the chunk count
    _, route = _router_fn(router)

    def local_fn(gate, w1, w2, xl):
        logits = xl @ gate
        dispatch, combine, aux = route(logits, E, cap)
        ein = jnp.einsum("td,tec->ecd", xl, dispatch)      # (E, cap, d)
        segs = jnp.reshape(ein, (E, k, cap // k, D))
        outs = []
        for i in range(k):
            seg = segs[:, i]                               # (E, cap/k, d)
            if comm == "nocomm":
                # shape-identical local relayout: pure-compute baseline
                inb = jnp.transpose(
                    jnp.reshape(seg, (ep, E_l, cap // k, D)),
                    (1, 0, 2, 3)).reshape(E_l, ep * cap // k, D)
            else:
                inb = lax.all_to_all(seg, axis_name, 0, 1, tiled=True)
            o = _run_experts(w1, w2, inb)       # (E_l, ep*cap/k, d)
            if comm == "nocomm":
                o = jnp.transpose(
                    jnp.reshape(o, (E_l, ep, cap // k, D)),
                    (1, 0, 2, 3)).reshape(E, cap // k, D)
            else:
                o = lax.all_to_all(o, axis_name, 1, 0, tiled=True)
            outs.append(o)
        expert_out = jnp.stack(outs, axis=1).reshape(E, cap, D)
        out = jnp.einsum("ecd,tec->td", expert_out, combine)
        return out, lax.pmean(aux, axis_name)

    from .compat import get_shard_map
    shard_map = get_shard_map()
    fn = shard_map(local_fn, mesh=mesh,
                   in_specs=(P(), P(axis_name), P(axis_name),
                             P(axis_name)),
                   out_specs=(P(axis_name), P()))
    return fn(params["gate"], params["w1"], params["w2"], x)


def measure_moe_overlap(mesh, axis_name="ep", d_model=64, d_hidden=128,
                        num_experts=None, tokens=None, steps=10,
                        warmup=3, chunks=None, seed=0):
    """Time the a2a MoE step under nocomm / chunked / serial dispatch
    and publish the hidden fraction (the MoE analog of
    ``measure_overlap``): exposed(mode) = step(mode) - step(nocomm),
    hidden = 1 - exposed(chunked)/exposed(serial).

    Returns {"exposed": {mode: seconds}, "hidden_fraction": float,
    "step_seconds": {mode: seconds}}.
    """
    ep = mesh.shape[axis_name]
    E = num_experts or 2 * ep
    T = tokens or 128 * ep
    key = jax.random.PRNGKey(seed)
    params = init_moe_params(key, d_model, d_hidden, E)
    params = shard_moe_params(params, mesh, axis_name)
    x = jax.device_put(
        jax.random.normal(key, (T, d_model)),
        NamedSharding(mesh, P(axis_name)))

    step_s = {}
    for mode in ("nocomm", "chunked", "serial"):
        fn = jax.jit(lambda p, xx, m=mode: moe_apply_a2a(
            p, xx, mesh, axis_name, chunks=chunks, comm=m)[0])
        for _ in range(warmup):
            fn(params, x).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(params, x)
        out.block_until_ready()
        step_s[mode] = (time.perf_counter() - t0) / steps

    exposed = {m: max(0.0, step_s[m] - step_s["nocomm"])
               for m in ("chunked", "serial")}
    if exposed["serial"] > 1e-9:
        hidden = 1.0 - exposed["chunked"] / exposed["serial"]
    else:
        hidden = 0.0
    hidden = max(-1.0, min(1.0, hidden))
    from .. import observability as _obs
    _obs.record_moe_probe(exposed, hidden)
    return {"exposed": exposed, "hidden_fraction": hidden,
            "step_seconds": step_s}
