"""Mixture-of-Experts with expert parallelism (P12).

No reference counterpart (SURVEY.md §2.5 P12 — "does not exist in the
reference"; previously a documented drop). TPU-native design: the
classic mesh-tensorflow/GShard algorithm — top-1 gating with capacity,
einsum dispatch/combine, experts sharded over an ``ep`` mesh axis inside
``shard_map`` so each device runs only its local experts; tokens reach
their expert's device via the dispatch einsum on locally-sharded expert
tensors (XLA lowers the resharding to an all-to-all over ICI).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError


def top1_routing(gate_logits, num_experts, capacity):
    """Top-1 router with capacity (GShard): returns (dispatch (T,E,C),
    combine (T,E,C), aux_loss). Tokens beyond an expert's capacity drop
    (standard semantics)."""
    T = gate_logits.shape[0]
    probs = jax.nn.softmax(gate_logits, axis=-1)           # (T, E)
    expert = jnp.argmax(probs, axis=-1)                    # (T,)
    onehot = jax.nn.one_hot(expert, num_experts)           # (T, E)
    # position of each token within its expert's queue (0-based; the
    # onehot factor keeps non-selected experts from contributing)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot      # (T, E)
    pos_in_expert = jnp.sum(pos, axis=-1)                  # (T,)
    keep = pos_in_expert < capacity
    pos_oh = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity)
    dispatch = onehot[:, :, None] * pos_oh[:, None, :] \
        * keep[:, None, None]                              # (T, E, C)
    gate_val = jnp.sum(probs * onehot, axis=-1)            # (T,)
    combine = dispatch * gate_val[:, None, None]
    # load-balance auxiliary loss (Shazeer et al.): E * <fraction, prob>
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def init_moe_params(key, d_model, d_hidden, num_experts):
    k1, k2, k3 = jax.random.split(key, 3)
    scale = 1.0 / jnp.sqrt(d_model)
    return {
        "gate": jax.random.normal(k1, (d_model, num_experts)) * scale,
        "w1": jax.random.normal(k2, (num_experts, d_model, d_hidden))
        * scale,
        "w2": jax.random.normal(k3, (num_experts, d_hidden, d_model))
        * (1.0 / jnp.sqrt(d_hidden)),
    }


def moe_apply(params, x, mesh=None, axis_name="ep", capacity_factor=1.5):
    """MoE FFN over tokens x (T, d). Experts shard over ``axis_name``
    when a mesh is given (expert parallelism); single-device otherwise.
    Returns (out (T, d), aux_loss)."""
    E = params["w1"].shape[0]
    T, D = x.shape
    capacity = int(max(1, (T / E) * capacity_factor))
    gate_logits = x @ params["gate"]
    dispatch, combine, aux = top1_routing(gate_logits, E, capacity)
    expert_in = jnp.einsum("td,tec->ecd", x, dispatch)      # (E, C, d)

    def run_experts(w1, w2, ein):
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", ein, w1))
        return jnp.einsum("ech,ehd->ecd", h, w2)

    if mesh is None:
        expert_out = run_experts(params["w1"], params["w2"], expert_in)
    else:
        if E % mesh.shape[axis_name]:
            raise MXNetError(
                f"experts {E} must divide mesh axis {axis_name} "
                f"({mesh.shape[axis_name]})")
        from .compat import get_shard_map
        shard_map = get_shard_map()

        expert_out = shard_map(
            run_experts, mesh=mesh,
            in_specs=(P(axis_name), P(axis_name), P(axis_name)),
            out_specs=P(axis_name),
        )(params["w1"], params["w2"], expert_in)
    out = jnp.einsum("ecd,tec->td", expert_out, combine)
    return out, aux


def shard_moe_params(params, mesh, axis_name="ep"):
    """Place expert tensors with the expert axis over ``ep``; the gate is
    replicated."""
    out = dict(params)
    out["w1"] = jax.device_put(params["w1"],
                               NamedSharding(mesh, P(axis_name)))
    out["w2"] = jax.device_put(params["w2"],
                               NamedSharding(mesh, P(axis_name)))
    out["gate"] = jax.device_put(params["gate"], NamedSharding(mesh, P()))
    return out
