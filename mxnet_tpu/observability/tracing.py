"""Low-overhead event tracer: step-scoped spans in a ring buffer.

Reference analog: MXNet's engine-integrated profiler dumping
chrome://tracing JSON (``src/profiler/profiler.cc::DumpProfile``). Here
events are plain dicts appended to a bounded ``deque`` (capacity
``MXTPU_TRACE_BUFFER``, default 65536 — old events fall off rather than
grow memory on long runs) and export two ways:

- ``dump_chrome_trace()`` — the ``{"traceEvents": [...]}`` JSON that
  chrome://tracing / Perfetto load directly,
- ``dump_jsonl()`` — one event object per line, the format
  ``tools/telemetry_report.py`` aggregates.

Timestamps are microseconds on the ``perf_counter`` clock, zeroed at
tracer construction (chrome://tracing only needs monotonicity).
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time

from ..base import getenv


def _default_capacity() -> int:
    return getenv("MXTPU_TRACE_BUFFER", 65536, dtype=int)


class Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer, name, cat, args):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer.record(self.name, cat=self.cat,
                            ts=self._t0, dur=t1 - self._t0, args=self.args)
        return False


class Tracer:
    """Ring buffer of trace events."""

    def __init__(self, capacity=None):
        self._events = collections.deque(
            maxlen=capacity or _default_capacity())
        self._epoch = time.perf_counter()
        self.step = 0  # advanced by Trainer.step via mark_step()
        # span ids: process-unique, monotonic, survive clear() — parent
        # links recorded before a clear must not collide after it
        self._span_ids = itertools.count(1)

    # -- recording -------------------------------------------------------
    def mark_step(self) -> int:
        """Advance the step counter; spans recorded afterwards carry the
        new step id in their args."""
        self.step += 1
        return self.step

    def new_span_id(self) -> int:
        """A process-unique span id (itertools.count — GIL-atomic).
        Correlated child events reference it via ``args["parent"]``."""
        return next(self._span_ids)

    def record(self, name, cat="default", ts=None, dur=0.0, args=None,
               ph="X", span_id=None):
        """Append one event. ``ts``/``dur`` are perf_counter seconds
        (``ts=None`` means now). Every event carries a unique ``id``
        (pass ``span_id`` to stamp one minted earlier, e.g. before
        handing it to children as their parent)."""
        if ts is None:
            ts = time.perf_counter()
        ev = {
            "name": name,
            "cat": cat,
            "ph": ph,
            "id": int(span_id) if span_id is not None else self.new_span_id(),
            "ts": (ts - self._epoch) * 1e6,
            "dur": dur * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFF,
            "args": dict(args or (), step=self.step),
        }
        self._events.append(ev)
        return ev

    def instant(self, name, cat="default", **args):
        return self.record(name, cat=cat, dur=0.0, args=args, ph="i")

    def span(self, name, cat="default", **args) -> Span:
        return Span(self, name, cat, args)

    # -- read side -------------------------------------------------------
    def events(self) -> list:
        return list(self._events)

    def __len__(self):
        return len(self._events)

    def clear(self):
        self._events.clear()
        self.step = 0

    # -- exporters -------------------------------------------------------
    def dump_chrome_trace(self, path=None) -> str:
        """chrome://tracing JSON; written to ``path`` when given."""
        # default=float: event args may hold asynchronous device scalars
        # (the fused step's lazy grad norm) — sync them at dump time only
        body = json.dumps({"traceEvents": self.events(),
                           "displayTimeUnit": "ms"}, default=float)
        if path:
            with open(path, "w") as f:
                f.write(body)
        return body

    def dump_jsonl(self, path=None) -> str:
        """One JSON event per line; written to ``path`` when given."""
        body = "\n".join(json.dumps(ev, default=float)
                         for ev in self._events)
        if body:
            body += "\n"
        if path:
            with open(path, "w") as f:
                f.write(body)
        return body


def load_jsonl(source) -> list:
    """Parse a JSONL trace from a path or a string body."""
    if "\n" not in source and os.path.exists(source):
        with open(source) as f:
            text = f.read()
    else:
        text = source
    return [json.loads(line) for line in text.splitlines() if line.strip()]
