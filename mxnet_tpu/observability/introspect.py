"""Performance introspection: XLA cost/memory accounting per executable
site, an MFU/roofline estimator, and step-bounded profiler windows.

PRs 3-6 collapsed training into one dispatch per step (or per K steps),
which made the host-side telemetry blind exactly where the time now
goes: inside compiled executables. This module opens that box:

- **Executable cost/memory accounting** (``MXTPU_INTROSPECT=1`` or
  ``set_enabled(True)``): every cached executable site — CachedOp
  fwd/bwd, the fused ``Trainer`` update, ``gluon.Superstep``,
  ``SPMDTrainStep``, kvstore gradient buckets — registers its
  ``lowered.compile().cost_analysis()`` / ``memory_analysis()`` once at
  build time: FLOPs, HBM bytes accessed, arithmetic intensity,
  temp/argument/output bytes, and donation verification (a donated
  buffer the compiled program did NOT alias is warned loudly — on a
  real accelerator that silently doubles peak memory). Backends lacking
  the analyses degrade to ``None`` fields, never an error.
- **MFU / roofline estimator**: per-site achieved-vs-peak from the
  device peak tables below (``mfu_estimate``), and a formatted
  ``cost_table()``; ``tools/telemetry_report.py`` renders the same
  table from a dumped trace (each registration also records one
  ``introspect.cost`` trace event carrying the full record).
- **Profiler windows**: ``MXTPU_PROFILE=<dir>[:start:stop]`` arms
  ``jax.profiler`` step-bounded trace capture — the window opens when
  the global step counter reaches ``start`` (default 1) and closes
  after ``stop`` (default ``start+9``); every covered ``Trainer.step``
  / ``Superstep.step`` is wrapped in a
  ``jax.profiler.StepTraceAnnotation``. ``profile_window(logdir)`` is
  the programmatic context-manager form.

Cost note: registration runs one extra ``lower().compile()`` per site
(JAX's AOT path does not share the jit call cache; with
``MXTPU_COMPILE_CACHE`` wired the XLA compile itself is a cache hit).
That is why introspection is opt-in and registration happens once per
site, at build time — the steady-state hot path pays one module-bool
read.
"""

from __future__ import annotations

import contextlib
import logging
import threading

from ..base import getenv

_logger = logging.getLogger("mxnet_tpu.introspect")

#: THE switch: cost/memory registration is skipped entirely when False.
#: Seeded from MXTPU_INTROSPECT (default off).
ENABLED = bool(getenv("MXTPU_INTROSPECT", False, dtype=bool))

_LOCK = threading.Lock()
_COSTS: dict = {}  # site -> cost record dict
_WARNED_DONATION: set = set()

#: mxtpu-graphcheck capture callback (tools/mxtpu_lint/graphcheck/).
#: When installed, every registration ALSO traces the site's jaxpr and
#: hands ``(site, jaxpr, compiled, rec, donated, meta)`` to the hook so
#: the compiled-artifact contract checker sees exactly what each hot
#: site lowered — no second tracing pipeline, no drift from what runs.
_GRAPH_HOOK = None


def set_graph_hook(cb):
    """Install (or clear, with ``None``) the graphcheck capture
    callback; returns the previous hook. The hook must never raise into
    training — exceptions are swallowed with a warning."""
    global _GRAPH_HOOK
    prev, _GRAPH_HOOK = _GRAPH_HOOK, cb
    return prev


def _graph_notify(site, jaxpr, compiled, rec, donated, meta):
    hook = _GRAPH_HOOK
    if hook is None:
        return
    try:
        hook(site, jaxpr, compiled, dict(rec) if rec else {},
             bool(donated), dict(meta) if meta else {})
    except Exception as e:  # the checker must never take training down
        _logger.warning("graphcheck hook failed for site %r: %s: %s",
                        site, type(e).__name__, e)


def enabled() -> bool:
    return ENABLED


def set_enabled(on: bool) -> bool:
    """Flip executable introspection at runtime; returns the previous
    state. Already-built executables register on their next dispatch."""
    global ENABLED
    prev, ENABLED = ENABLED, bool(on)
    return prev


def reset():
    """Drop every registered site record (tests)."""
    with _LOCK:
        _COSTS.clear()
        _WARNED_DONATION.clear()


# ---------------------------------------------------------------------------
# device peak tables (per chip). FLOPs: bf16 dense peak. HBM: GB/s.
# Sources: public TPU system specs; the CPU backend has no meaningful
# peak, so MFU degrades to None with a reason there.
# ---------------------------------------------------------------------------

_PEAK_TFLOPS = {
    "TPU v6 lite": 918.0,   # v6e
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v5": 459.0,
    "TPU v4": 275.0,
    "TPU v3": 123.0,
    "TPU v2": 46.0,
}

_PEAK_HBM_GBS = {
    "TPU v6 lite": 1640.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5p": 2765.0,
    "TPU v5": 2765.0,
    "TPU v4": 1228.0,
    "TPU v3": 900.0,
    "TPU v2": 700.0,
}


def device_peaks():
    """``(peak_tflops, peak_hbm_gbs, reason)`` for device 0 of the
    current backend; the peaks are None (with the reason filled) when
    the device kind has no table entry (CPU, unknown PJRT plugins)."""
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception as e:  # backend not initializable
        return None, None, f"backend unavailable: {type(e).__name__}"
    for k, v in _PEAK_TFLOPS.items():
        if kind.startswith(k):
            return v, _PEAK_HBM_GBS.get(k), None
    return None, None, f"no peak-FLOPs table for device kind {kind!r}"


# ---------------------------------------------------------------------------
# cost/memory registration
# ---------------------------------------------------------------------------

def _cost_dict(compiled):
    """Normalize ``compiled.cost_analysis()`` → dict or None (older JAX
    returns a one-element list; some PJRT plugins return None/raise)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


def _mem_stats(compiled):
    try:
        return compiled.memory_analysis()
    except Exception:
        return None


def _num(d, key):
    """A float field from a (possibly partial) cost dict, else None."""
    if not isinstance(d, dict):
        return None
    v = d.get(key)
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def analyze_compiled(site, compiled, donated=False):
    """Build one site cost record from a ``Compiled`` object. Every
    field degrades independently to ``None`` — a backend returning
    ``None`` or a partial dict from either analysis must never break
    registration (tested in tests/test_introspect.py)."""
    ca = _cost_dict(compiled)
    ma = _mem_stats(compiled)
    flops = _num(ca, "flops")
    nbytes = _num(ca, "bytes accessed")
    rec = {
        "site": site,
        "flops": flops,
        "bytes_accessed": nbytes,
        "transcendentals": _num(ca, "transcendentals"),
        "arith_intensity": (flops / nbytes)
        if flops is not None and nbytes else None,
        "argument_bytes": getattr(ma, "argument_size_in_bytes", None),
        "output_bytes": getattr(ma, "output_size_in_bytes", None),
        "temp_bytes": getattr(ma, "temp_size_in_bytes", None),
        "alias_bytes": getattr(ma, "alias_size_in_bytes", None),
        "generated_code_bytes": getattr(
            ma, "generated_code_size_in_bytes", None),
        "donated": bool(donated),
    }
    peak_tf, peak_bw, peak_reason = device_peaks()
    rec["peak_tflops"] = peak_tf
    rec["peak_hbm_gbs"] = peak_bw
    if peak_reason:
        rec["peak_reason"] = peak_reason
    return rec


def _verify_donation(rec):
    """Warn LOUDLY (once per site) when buffers were donated but the
    compiled program aliased none of them: the donation silently failed
    and peak memory holds both copies. ``alias_bytes`` None (no memory
    analysis on this backend) is indeterminate — stay quiet."""
    if not rec["donated"]:
        return
    alias = rec.get("alias_bytes")
    if alias is None or alias > 0:
        return
    site = rec["site"]
    if site in _WARNED_DONATION:
        return
    _WARNED_DONATION.add(site)
    from . import DONATION_UNALIASED_TOTAL, ENABLED as _TEL

    if _TEL:
        DONATION_UNALIASED_TOTAL.inc(1, site=site)
    _logger.warning(
        "introspect: executable %r donated its input buffers but the "
        "compiled program aliased 0 bytes — donation FAILED (expected on "
        "the CPU backend, which never aliases; on an accelerator this "
        "doubles the site's peak memory)", site)


def registered(site) -> bool:
    """Lock-free already-registered probe (a plain dict containment
    read under the GIL): hot paths call this BEFORE building the
    ``avals_of`` skeleton, so a registered site costs one dict lookup
    per dispatch instead of an O(n_params) tree_map + lock."""
    return site in _COSTS


def avals_of(args):
    """Shape/dtype skeleton of an argument pytree, captured BEFORE a
    donating call (the live buffers may be consumed by it)."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
        if hasattr(a, "shape") and hasattr(a, "dtype") else a, args)


def register_jit(site, jit_fn, args, donated=False, force=False,
                 graph_meta=None):
    """Register cost/memory analysis for ``jit_fn`` called with
    ``args`` (concrete arrays or the ``avals_of`` skeleton) under site
    name ``site``. One-shot per site unless ``force``; a no-op when
    introspection is disabled. Never raises: an un-lowerable function
    or an analysis-less backend records a stub with ``error`` set.
    ``graph_meta`` annotates the site for mxtpu-graphcheck (e.g. a
    sanctioned baked-constant exemption) and is only consulted when a
    graph hook is installed."""
    if not ENABLED:
        return None
    with _LOCK:
        if site in _COSTS and not force:
            return _COSTS[site]
    jaxpr = None
    compiled = None
    try:
        if _GRAPH_HOOK is not None and hasattr(jit_fn, "trace"):
            try:
                jaxpr = jit_fn.trace(*args).jaxpr
            except Exception:
                jaxpr = None  # un-traceable: the hook still sees memory
        compiled = jit_fn.lower(*args).compile()
        rec = analyze_compiled(site, compiled, donated=donated)
    except Exception as e:  # introspection must never take training down
        rec = {"site": site, "flops": None, "bytes_accessed": None,
               "donated": bool(donated),
               "error": f"{type(e).__name__}: {e}"[:200]}
    _publish(rec)
    _graph_notify(site, jaxpr, compiled, rec, donated, graph_meta)
    return rec


def register_compiled(site, compiled, donated=False, force=False,
                      jaxpr=None, graph_meta=None):
    """Register an already-compiled executable (AOT / SPMD paths).
    Callers that kept the traced ``jaxpr`` may pass it through for
    mxtpu-graphcheck; without it only the memory-level checks see the
    site."""
    if not ENABLED:
        return None
    with _LOCK:
        if site in _COSTS and not force:
            return _COSTS[site]
    rec = analyze_compiled(site, compiled, donated=donated)
    _publish(rec)
    _graph_notify(site, jaxpr, compiled, rec, donated, graph_meta)
    return rec


def _publish(rec):
    site = rec["site"]
    with _LOCK:
        _COSTS[site] = rec
    _verify_donation(rec)
    # gauges + one trace event carrying the whole record — this is what
    # tools/telemetry_report.py's roofline table reads from a dump
    from . import (
        ENABLED as _TEL,
        EXEC_ALIAS_BYTES,
        EXEC_ARG_BYTES,
        EXEC_ARITH_INTENSITY,
        EXEC_BYTES_ACCESSED,
        EXEC_FLOPS,
        EXEC_OUT_BYTES,
        EXEC_TEMP_BYTES,
        tracer,
    )

    if _TEL:
        for gauge, key in ((EXEC_FLOPS, "flops"),
                           (EXEC_BYTES_ACCESSED, "bytes_accessed"),
                           (EXEC_ARITH_INTENSITY, "arith_intensity"),
                           (EXEC_TEMP_BYTES, "temp_bytes"),
                           (EXEC_ARG_BYTES, "argument_bytes"),
                           (EXEC_OUT_BYTES, "output_bytes"),
                           (EXEC_ALIAS_BYTES, "alias_bytes")):
            if rec.get(key) is not None:
                gauge.set(rec[key], site=site)
    tracer().record("introspect.cost", cat="introspect", dur=0.0,
                    args=dict(rec), ph="i")


def costs() -> dict:
    """``{site: record}`` snapshot of every registered executable."""
    with _LOCK:
        return {k: dict(v) for k, v in _COSTS.items()}


def site_cost(site):
    with _LOCK:
        rec = _COSTS.get(site)
        return dict(rec) if rec else None


def flops_per_step(sites=None):
    """Sum of registered per-invocation FLOPs over ``sites`` (default:
    the one-dispatch train-step trio). Returns ``(flops, reason)`` —
    flops None with the reason filled when nothing usable registered.
    A superstep site's FLOPs cover K iterations; divide by K yourself.
    """
    if sites is None:
        snap = costs()
        sites = [s for s in snap
                 if s.startswith(("cachedop_fwd", "cachedop_bwd"))
                 or s in ("trainer_fused", "spmd_step")]
    total, seen = 0.0, 0
    for s in sites:
        rec = site_cost(s)
        if rec is None:
            continue
        if rec.get("flops") is None:
            return None, rec.get(
                "error", f"backend reports no cost analysis for {s!r}")
        total += rec["flops"]
        seen += 1
    if not seen:
        return None, "no executable sites registered " \
                     "(MXTPU_INTROSPECT off, or nothing dispatched yet)"
    return total, None


def mfu_estimate(site, step_seconds):
    """Achieved-vs-peak for one site: ``{"achieved_tflops", "mfu",
    "bound", "reason"}``. ``mfu`` is None with a reason on backends
    without a peak table or cost analysis. Gated on the runtime feature
    set — ``Features()["INTROSPECTION"]`` — so environments that stub
    it out degrade to the reason string instead of wrong numbers."""
    from ..runtime import Features

    out = {"site": site, "achieved_tflops": None, "mfu": None,
           "bound": None, "reason": None}
    try:
        if not Features().is_enabled("INTROSPECTION"):
            out["reason"] = "INTROSPECTION feature disabled"
            return out
    except Exception:
        pass
    rec = site_cost(site)
    if rec is None:
        out["reason"] = f"site {site!r} not registered"
        return out
    flops = rec.get("flops")
    if flops is None:
        out["reason"] = rec.get("error",
                                "backend reports no cost analysis")
        return out
    if not step_seconds or step_seconds <= 0:
        out["reason"] = "no step timing"
        return out
    out["achieved_tflops"] = flops / step_seconds / 1e12
    ai = rec.get("arith_intensity")
    peak_tf, peak_bw = rec.get("peak_tflops"), rec.get("peak_hbm_gbs")
    if peak_tf is None:
        out["reason"] = rec.get("peak_reason", "no peak-FLOPs table")
        return out
    out["mfu"] = out["achieved_tflops"] / peak_tf
    if ai is not None and peak_bw:
        ridge = peak_tf * 1e12 / (peak_bw * 1e9)  # flops/byte
        out["bound"] = "compute" if ai >= ridge else "memory"
    return out


def cost_table() -> str:
    """Human-readable per-site roofline table of every registered
    executable (the in-process twin of telemetry_report's section)."""
    snap = costs()
    if not snap:
        return "introspect: no executables registered " \
               "(set MXTPU_INTROSPECT=1 before building)"
    lines = ["Executable cost/memory (per invocation):",
             f"{'Site':<34}{'GFLOPs':>10}{'MiB acc':>10}{'AI':>8}"
             f"{'Temp MiB':>10}{'Alias MiB':>10}{'Donated':>9}"]
    for site in sorted(snap):
        rec = snap[site]

        def fmt(key, scale, nd=2):
            v = rec.get(key)
            return f"{v / scale:.{nd}f}" if v is not None else "-"

        lines.append(
            f"{site:<34}{fmt('flops', 1e9):>10}"
            f"{fmt('bytes_accessed', 2**20):>10}"
            f"{fmt('arith_intensity', 1.0, 1):>8}"
            f"{fmt('temp_bytes', 2**20):>10}"
            f"{fmt('alias_bytes', 2**20):>10}"
            f"{'yes' if rec.get('donated') else 'no':>9}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# profiler windows (jax.profiler)
# ---------------------------------------------------------------------------

def _parse_profile_env(value):
    """``<dir>[:start:stop]`` → (dir, start, stop). Bare dir defaults
    to steps [1, 10]; the trailing two fields must both be ints (a
    path containing ':' is otherwise kept whole)."""
    parts = value.split(":")
    if len(parts) >= 3 and parts[-1].isdigit() and parts[-2].isdigit():
        start = max(int(parts[-2]), 1)
        return ":".join(parts[:-2]), start, max(int(parts[-1]), start)
    start = 1
    return value, start, start + 9


_PROFILE = {
    "dir": None, "start": 0, "stop": 0,
    "active": False, "done": False, "step": 0, "captures": 0,
}

#: True when a MXTPU_PROFILE window is armed (or profiling was started
#: programmatically); the ONE boolean the training hot paths read.
PROFILING = False


def configure_profile(logdir, start=1, stop=None):
    """Arm a step-bounded profiler window: capture starts when the
    step counter reaches ``start`` and stops after ``stop``."""
    global PROFILING
    _PROFILE.update(dir=logdir, start=max(int(start), 1),
                    stop=int(stop) if stop is not None else int(start) + 9,
                    active=False, done=False, step=0)
    PROFILING = logdir is not None
    return dict(_PROFILE)


def _maybe_arm_from_env():
    v = getenv("MXTPU_PROFILE", None)
    if v:
        d, start, stop = _parse_profile_env(str(v))
        configure_profile(d, start, stop)


def profile_state() -> dict:
    return dict(_PROFILE)


def _start_trace():
    import jax

    try:
        jax.profiler.start_trace(_PROFILE["dir"])
        _PROFILE["active"] = True
        _PROFILE["captures"] += 1
        _logger.info("profiler window OPEN at step %d -> %s",
                     _PROFILE["step"], _PROFILE["dir"])
    except Exception as e:  # profiler plugin missing/busy: disarm loudly
        _PROFILE["done"] = True
        global PROFILING
        PROFILING = False  # steps go back to the zero-cost path
        _logger.warning("profiler window failed to open: %s: %s",
                        type(e).__name__, e)


def _stop_trace():
    import jax

    try:
        jax.profiler.stop_trace()
    except Exception as e:
        _logger.warning("profiler stop_trace failed: %s: %s",
                        type(e).__name__, e)
    _PROFILE["active"] = False
    _PROFILE["done"] = True
    global PROFILING
    PROFILING = False
    _logger.info("profiler window CLOSED after step %d", _PROFILE["step"])


@contextlib.contextmanager
def profile_step(k=1, name="train"):
    """Wrap one ``Trainer.step`` / K-step superstep dispatch: advances
    the window state machine (open at ``start``, close after ``stop``)
    and annotates the covered region with
    ``jax.profiler.StepTraceAnnotation`` so the device trace aligns
    with host step numbers. Call only when ``PROFILING`` is True."""
    import jax

    first = _PROFILE["step"] + 1
    _PROFILE["step"] += int(k)
    if (not _PROFILE["active"] and not _PROFILE["done"]
            and _PROFILE["dir"] and _PROFILE["step"] >= _PROFILE["start"]):
        _start_trace()
    if _PROFILE["active"]:
        try:
            with jax.profiler.StepTraceAnnotation(name, step_num=first):
                yield
        finally:
            if _PROFILE["step"] >= _PROFILE["stop"]:
                _stop_trace()
    else:
        yield


@contextlib.contextmanager
def profile_window(logdir):
    """Programmatic capture: everything inside the block lands in one
    ``jax.profiler`` trace under ``logdir`` (open in TensorBoard or
    Perfetto). Composes with ``annotate()`` named spans."""
    import jax

    jax.profiler.start_trace(logdir)
    _PROFILE["captures"] += 1
    was_active = _PROFILE["active"]
    _PROFILE["active"] = True  # annotate() spans inside the block record
    try:
        yield logdir
    finally:
        _PROFILE["active"] = was_active
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            _logger.warning("profile_window stop failed: %s: %s",
                            type(e).__name__, e)


def annotate(name):
    """Named profiler span (``jax.profiler.TraceAnnotation``) for hot
    regions — the fused update, bucket pack/allreduce/unpack — visible
    in the captured device trace. Returns a no-op context manager when
    no window is active, so call sites can use it unconditionally
    inside a ``PROFILING`` check."""
    if not (_PROFILE["active"] or PROFILING):
        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(name)


_maybe_arm_from_env()
