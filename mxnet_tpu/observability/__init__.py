"""``mxnet_tpu.observability`` — run-scoped runtime telemetry.

The unified instrumentation substrate for the stack (the role MXNet 1.x
gave its engine-integrated ``src/profiler/``): a metrics registry
(Counter/Gauge/Histogram with labels), a ring-buffer event tracer with
chrome://tracing + JSONL exporters, and Prometheus text exposition.

Instrumented hot paths (each behind ONE ``ENABLED`` boolean check):

- ``ops/dispatch.py`` — per-op dispatch count + wall time,
- ``gluon/block.py::_CachedGraph`` — compile count, cache hits, trace
  wall time, retrace-cause diagnosis,
- ``kvstore/local.py`` / ``kvstore/dist.py`` — push/pull counts and
  bytes, allreduce latency, barrier count,
- ``gluon/trainer.py`` — step count/latency spans, grad-norm gauge,
- ``engine.py::wait`` — sync-probe latency, relay vs native path.

Switch: ``MXTPU_TELEMETRY=1`` at process start, or
``observability.set_enabled(True)`` at runtime. Off by default: the
disabled cost at every site is a single module-attribute boolean read.

Sibling layers (docs/observability.md "Profiling & post-mortem"):

- ``observability.introspect`` — per-executable XLA cost/memory
  accounting + MFU/roofline estimation (``MXTPU_INTROSPECT``) and
  step-bounded ``jax.profiler`` windows (``MXTPU_PROFILE``),
- ``observability.flight`` — crash flight recorder
  (``MXTPU_DUMP_ON_CRASH``): excepthook + SIGTERM/SIGABRT handlers
  dumping trace ring, metrics, cost table and in-flight dispatch sites,
- ``observability.serve`` — background-thread Prometheus endpoint
  (``MXTPU_METRICS_PORT`` / ``serve_metrics(port)``).

Quickstart::

    import mxnet_tpu as mx
    mx.observability.set_enabled(True)
    ... train ...
    print(mx.observability.summary())
    print(mx.observability.dump_prometheus())
    mx.observability.tracer().dump_chrome_trace("trace.json")
"""

from __future__ import annotations

import time as _time

from ..base import getenv
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    SeriesGauge,
    DEFAULT_BUCKETS,
)
from .tracing import Span, Tracer, load_jsonl  # noqa: F401

#: THE switch. Hot paths read this module attribute and skip all
#: recording when False. Seeded from MXTPU_TELEMETRY (default off).
ENABLED = bool(getenv("MXTPU_TELEMETRY", False, dtype=bool))

_REGISTRY = MetricsRegistry()
_TRACER = Tracer()


def registry() -> MetricsRegistry:
    return _REGISTRY


def tracer() -> Tracer:
    return _TRACER


def enabled() -> bool:
    return ENABLED


def set_enabled(on: bool) -> bool:
    """Flip telemetry at runtime; returns the previous state."""
    global ENABLED
    prev, ENABLED = ENABLED, bool(on)
    return prev


def enable():
    set_enabled(True)


def disable():
    set_enabled(False)


def reset():
    """Clear every recorded metric value and all trace events."""
    _REGISTRY.reset()
    _TRACER.clear()


def span(name, cat="default", **args) -> Span:
    return _TRACER.span(name, cat=cat, **args)


# ---------------------------------------------------------------------------
# metric catalog (module-level singletons so instrumented sites pay no
# registry lookup per record) — see docs/observability.md
# ---------------------------------------------------------------------------

OP_DISPATCH_TOTAL = _REGISTRY.counter(
    "mxtpu_op_dispatch_total", "imperative op dispatches, by op name")
OP_DISPATCH_SECONDS = _REGISTRY.counter(
    "mxtpu_op_dispatch_seconds_total",
    "wall time spent in op dispatch (async: excludes device time), by op")

CACHEDOP_COMPILE_TOTAL = _REGISTRY.counter(
    "mxtpu_cachedop_compile_total",
    "CachedGraph builds (trace+compile), by block")
CACHEDOP_CACHE_HITS = _REGISTRY.counter(
    "mxtpu_cachedop_cache_hit_total",
    "CachedGraph signature-cache hits, by block")
CACHEDOP_TRACE_SECONDS = _REGISTRY.counter(
    "mxtpu_cachedop_trace_seconds_total",
    "wall time of CachedGraph build + first compiled call, by block")
CACHEDOP_RETRACE_TOTAL = _REGISTRY.counter(
    "mxtpu_cachedop_retrace_total",
    "recompiles after the first, by block and cause key-diff")

KV_PUSH_TOTAL = _REGISTRY.counter(
    "mxtpu_kvstore_push_total", "kvstore push operations (per key)")
KV_PUSH_BYTES = _REGISTRY.counter(
    "mxtpu_kvstore_push_bytes_total", "gradient bytes entering aggregation")
KV_PULL_TOTAL = _REGISTRY.counter(
    "mxtpu_kvstore_pull_total", "kvstore pull operations (per key)")
KV_PULL_BYTES = _REGISTRY.counter(
    "mxtpu_kvstore_pull_bytes_total", "bytes written into pull outputs")
KV_PUSHPULL_TOTAL = _REGISTRY.counter(
    "mxtpu_kvstore_pushpull_total", "fused pushpull aggregations (per key)")
KV_ALLREDUCE_SECONDS = _REGISTRY.histogram(
    "mxtpu_kvstore_allreduce_seconds",
    "dispatch latency of the global-mesh allreduce")
KV_ALLREDUCE_BYTES = _REGISTRY.counter(
    "mxtpu_kvstore_allreduce_bytes_total",
    "payload bytes through the global-mesh allreduce")
KV_BARRIER_TOTAL = _REGISTRY.counter(
    "mxtpu_kvstore_barrier_total", "cross-process barrier entries")

XLA_DISPATCH_TOTAL = _REGISTRY.counter(
    "mxtpu_xla_dispatch_total",
    "compiled-executable invocations, by site (op / cachedop_fwd / "
    "cachedop_bwd / kv_grouped / kv_bucket / trainer_fused / "
    "superstep / superstep_stage / serving)")

FUSED_FALLBACK_TOTAL = _REGISTRY.counter(
    "mxtpu_fused_fallback_total",
    "fused-train-step fast-path declines, by site and reason")

KV_BUCKET_BUILD_TOTAL = _REGISTRY.counter(
    "mxtpu_kvstore_bucket_build_total",
    "gradient-bucket plans built (one per pushpull signature)")
KV_BUCKET_PUSHPULL_TOTAL = _REGISTRY.counter(
    "mxtpu_kvstore_bucket_pushpull_total",
    "bucketed multi-key pushpull aggregations (per call, not per key)")

TRAINER_STEP_TOTAL = _REGISTRY.counter(
    "mxtpu_trainer_step_total", "Trainer.step calls")
TRAINER_STEP_SECONDS = _REGISTRY.histogram(
    "mxtpu_trainer_step_seconds", "Trainer.step wall time")
TRAINER_GRAD_NORM = _REGISTRY.gauge(
    "mxtpu_trainer_grad_norm",
    "global L2 norm of the (post-allreduce) gradients at the last step")

ENGINE_WAIT_TOTAL = _REGISTRY.counter(
    "mxtpu_engine_wait_total", "engine.wait sync probes, by path")
ENGINE_WAIT_SECONDS = _REGISTRY.counter(
    "mxtpu_engine_wait_seconds_total",
    "wall time blocked in engine.wait, by path")

PROFILE_COUNTER = _REGISTRY.gauge(
    "mxtpu_profile_counter",
    "user-defined profiler.ProfileCounter values, by counter name")

DATA_PREFETCH_QUEUE_DEPTH = _REGISTRY.gauge(
    "mxtpu_data_prefetch_queue_depth",
    "batches currently staged ahead in the DevicePrefetcher queue")
DATA_PREFETCH_BATCHES = _REGISTRY.counter(
    "mxtpu_data_prefetch_batches_total",
    "batches staged to device by the DevicePrefetcher")
DATA_PREFETCH_WAIT_SECONDS = _REGISTRY.counter(
    "mxtpu_data_prefetch_wait_seconds_total",
    "consumer wall time blocked waiting on the prefetch queue (the "
    "'accelerator idles on the host' signal — near-zero when overlapped)")
DATA_H2D_BYTES = _REGISTRY.counter(
    "mxtpu_data_h2d_bytes_total",
    "host->device batch payload bytes staged by the input pipeline")
DATA_H2D_SECONDS = _REGISTRY.histogram(
    "mxtpu_data_h2d_seconds",
    "host->device staging latency per batch (convert + device_put "
    "dispatch; async backends may finish the copy later)")
DATA_PREFETCH_WAIT_DELTA = _REGISTRY.gauge(
    "mxtpu_data_prefetch_wait_delta_seconds",
    "consumer prefetch-queue wait attributed to the LAST step (the "
    "per-step delta of the _total counter, set by the attribution "
    "plane) — an input-wait spike is visible here where the running "
    "total hides it; the watchdog's input_wait detector reads this")

# -- streaming data plane (gluon/data/stream.py) -------------------------
STREAM_READ_BYTES = _REGISTRY.counter(
    "mxtpu_stream_read_bytes_total",
    "raw bytes read from storage by the streaming shard reader, by "
    "shard (divide by _seconds for the per-shard read rate)")
STREAM_READ_SECONDS = _REGISTRY.counter(
    "mxtpu_stream_read_seconds_total",
    "wall time the read-ahead thread spent in storage reads, by shard "
    "(includes emulated MXTPU_STREAM_LATENCY_MS slow-storage latency)")
STREAM_RECORDS_TOTAL = _REGISTRY.counter(
    "mxtpu_stream_records_total",
    "records fetched from shards by the streaming reader, by shard")
STREAM_DECODE_SECONDS = _REGISTRY.counter(
    "mxtpu_stream_decode_seconds_total",
    "wall time the decode pool spent decoding records (busy time; "
    "utilization = busy / (busy + wait))")
STREAM_DECODE_WAIT_SECONDS = _REGISTRY.counter(
    "mxtpu_stream_decode_wait_seconds_total",
    "wall time decode-pool workers spent idle waiting on the raw-record "
    "queue — high means storage (not decode) is the bottleneck")
STREAM_CONSUMER_WAIT_SECONDS = _REGISTRY.counter(
    "mxtpu_stream_consumer_wait_seconds_total",
    "train-thread wall time blocked waiting on the streaming reader "
    "for a full batch — the 'input-bound' signal; ≈0 when the decode "
    "pool keeps up with the superstep")
STREAM_QUEUE_DEPTH = _REGISTRY.gauge(
    "mxtpu_stream_queue_depth",
    "streaming-reader staging depth, by queue (raw = undecoded "
    "records awaiting the decode pool; reorder = decoded samples "
    "awaiting in-order consumption)")
STREAM_BATCHES_TOTAL = _REGISTRY.counter(
    "mxtpu_stream_batches_total",
    "batches delivered in deterministic global order by StreamReader")
STREAM_REPARTITIONS_TOTAL = _REGISTRY.counter(
    "mxtpu_stream_repartitions_total",
    "elastic re-partitions of the streaming cursor (resize events "
    "rebasing base_batch so no sample is skipped or replayed)")

COMPILE_CACHE_HITS = _REGISTRY.counter(
    "mxtpu_compile_cache_hit_total",
    "XLA executables served from the persistent compilation cache "
    "(MXTPU_COMPILE_CACHE)")
COMPILE_CACHE_MISSES = _REGISTRY.counter(
    "mxtpu_compile_cache_miss_total",
    "XLA compiles that missed the persistent compilation cache")

SHAPE_WOBBLE_TOTAL = _REGISTRY.counter(
    "mxtpu_shape_wobble_total",
    "CachedGraph shape-signature count exceeded MXTPU_RETRACE_BUDGET, "
    "by block — pad/bucket the inputs (docs/performance.md)")

SUPERSTEP_TOTAL = _REGISTRY.counter(
    "mxtpu_superstep_total",
    "K-step on-device superstep dispatches, by k")
SUPERSTEP_ITERATIONS_TOTAL = _REGISTRY.counter(
    "mxtpu_superstep_iterations_total",
    "training iterations executed inside superstep dispatches (the "
    "denominator for dispatches-per-step amortization)")
SUPERSTEP_STEP_SECONDS = _REGISTRY.histogram(
    "mxtpu_superstep_amortized_step_seconds",
    "superstep wall time divided by its K — the amortized per-step "
    "time the host observes (gauges update once per superstep, so "
    "per-step series have K-step cadence; docs/observability.md)")

# -- step-time attribution plane (observability/attribution.py) ------------

STEP_PHASE_SECONDS = _REGISTRY.histogram(
    "mxtpu_step_phase_seconds",
    "per-step wall time by phase (input_wait / h2d / ckpt_overhead / "
    "comm_exposed / compute / host_gap) from the attribution plane's "
    "budget decomposition of each step period — phases are >= 0 and "
    "sum to the period by construction; superstep dispatches are "
    "amortized over their K (docs/observability.md, 'Reading an "
    "attribution report')")
STEP_PHASE_LAST = _REGISTRY.series_gauge(
    "mxtpu_step_phase_last_seconds",
    "the last-N per-step phase records, by phase — stored as a LAZY "
    "view over the attribution ring (materializes at read/exposition "
    "time, zero per-step list building); slot 0 is the oldest retained "
    "step")

# -- scale-out: overlapped allreduce + ZeRO sharding (parallel/) ----------

OVERLAP_BUCKETS = _REGISTRY.gauge(
    "mxtpu_overlap_buckets",
    "gradient buckets in the current bucket-ready comm plan, by site "
    "(readiness-ordered ~MXTPU_OVERLAP_BUCKET_BYTES buckets; each is "
    "one in-graph collective)")
OVERLAP_EXPOSED_COMM_SECONDS = _REGISTRY.gauge(
    "mxtpu_overlap_exposed_comm_seconds",
    "per-step wall time NOT hidden behind compute, by comm mode "
    "(step time minus the compute-only probe's; set by the overlap "
    "measurement probe — bench.py overlap / measure_overlap)")
OVERLAP_HIDDEN_FRACTION = _REGISTRY.gauge(
    "mxtpu_overlap_hidden_fraction",
    "fraction of the staged baseline's exposed comm time the "
    "bucket-ready overlapped step hides (1 - exposed_ready/"
    "exposed_staged, from the overlap measurement probe)")
ZERO_STATE_BYTES = _REGISTRY.gauge(
    "mxtpu_zero_state_bytes",
    "per-device at-rest bytes of the SPMD step's state, by kind "
    "(param / opt) — the ZeRO sharding saving vs a replicated layout "
    "is visible as this gauge dropping ~1/dp at stage 2/3")


def record_overlap_probe(exposed_by_mode, hidden_fraction):
    """Publish an overlap measurement (exposed comm seconds per mode +
    the hidden fraction) into the registry, and hand the per-mode
    exposed figures to the attribution plane as its comm hint (in-graph
    comm schedules leave no host timestamp to delta)."""
    for mode, sec in (exposed_by_mode or {}).items():
        OVERLAP_EXPOSED_COMM_SECONDS.set(float(sec), mode=str(mode))
    if hidden_fraction is not None:
        OVERLAP_HIDDEN_FRACTION.set(float(hidden_fraction))
    from . import attribution as _attr  # late: submodule binds at bottom

    _attr.set_comm_hint(exposed_by_mode)


PIPELINE_BUBBLE_FRACTION = _REGISTRY.gauge(
    "mxtpu_pipeline_bubble_fraction",
    "fraction of (rank, tick) slots with no scheduled work in the "
    "realized pipeline schedule table, by schedule (gpipe / 1f1b / "
    "interleaved) — measured from the dependency-simulated tick "
    "program, not a closed-form estimate; 1 - bubble is the "
    "pipeline-overlap criterion")
PIPELINE_STASH_SLOTS = _REGISTRY.gauge(
    "mxtpu_pipeline_stash_slots",
    "peak live forward-activation stash entries on any pipeline rank, "
    "by schedule — the 1F1B memory win over fill-drain gpipe is this "
    "gauge dropping from ~M (microbatches) to ~S (stages)")
MOE_A2A_EXPOSED_SECONDS = _REGISTRY.gauge(
    "mxtpu_moe_a2a_exposed_seconds",
    "per-step wall time of the MoE all-to-all NOT hidden behind expert "
    "compute, by dispatch mode (serial / chunked; step time minus the "
    "comm-free probe's — set by measure_moe_overlap)")
MOE_A2A_HIDDEN_FRACTION = _REGISTRY.gauge(
    "mxtpu_moe_a2a_hidden_fraction",
    "fraction of the serial baseline's exposed all-to-all time the "
    "chunked (comm/compute interleaved) MoE dispatch hides "
    "(1 - exposed_chunked/exposed_serial, from measure_moe_overlap)")


def record_pipeline_schedule(schedule, bubble_fraction, stash_slots,
                             ticks=None):
    """Publish a realized pipeline schedule's measured shape (bubble +
    stash depth gauges, by schedule) and drop a ``pipeline.schedule``
    instant on the trace so mxtpu-doctor can join it with step-phase
    attribution."""
    PIPELINE_BUBBLE_FRACTION.set(float(bubble_fraction),
                                 schedule=str(schedule))
    PIPELINE_STASH_SLOTS.set(float(stash_slots), schedule=str(schedule))
    _TRACER.instant("pipeline.schedule", cat="parallel",
                    schedule=str(schedule),
                    bubble_fraction=float(bubble_fraction),
                    stash_slots=int(stash_slots),
                    ticks=int(ticks) if ticks is not None else None)


def record_moe_probe(exposed_by_mode, hidden_fraction):
    """Publish a MoE all-to-all overlap measurement (exposed seconds
    per dispatch mode + the hidden fraction)."""
    for mode, sec in (exposed_by_mode or {}).items():
        MOE_A2A_EXPOSED_SECONDS.set(float(sec), mode=str(mode))
    if hidden_fraction is not None:
        MOE_A2A_HIDDEN_FRACTION.set(float(hidden_fraction))
    _TRACER.instant("moe.a2a_probe", cat="parallel",
                    hidden_fraction=float(hidden_fraction or 0.0))


AMP_LOSS_SCALE = _REGISTRY.gauge(
    "mxtpu_amp_loss_scale",
    "current dynamic loss scale (fp16 AMP); under the fused step this "
    "holds a LAZY device scalar that syncs only when read")
AMP_OVERFLOW_TOTAL = _REGISTRY.gauge(
    "mxtpu_amp_overflow_total",
    "gradient-overflow (skip-update + scale-backoff) events since the "
    "scaler was created — monotonic; a gauge, not a counter, so the "
    "fused step can record the in-graph total as a lazy device scalar")

# -- resilience: async checkpointing + chaos (mxnet_tpu/resilience) --------

CHECKPOINT_TOTAL = _REGISTRY.counter(
    "mxtpu_checkpoint_total",
    "committed training checkpoints, by reason "
    "(interval / manual / sigterm)")
CHECKPOINT_SECONDS = _REGISTRY.histogram(
    "mxtpu_checkpoint_seconds",
    "wall time of one checkpoint serialize+write+commit (runs on the "
    "background writer thread — NOT training-loop time)")
CHECKPOINT_TICK_SECONDS = _REGISTRY.counter(
    "mxtpu_checkpoint_tick_seconds_total",
    "training-LOOP time spent entering checkpoints (interval bookkeeping "
    "+ snapshot dispatch + writer-queue handoff) — the in-loop cost the "
    "attribution plane charges to ckpt_overhead; the background write "
    "itself stays in mxtpu_checkpoint_seconds")
CHECKPOINT_BYTES_TOTAL = _REGISTRY.counter(
    "mxtpu_checkpoint_bytes_total",
    "payload bytes committed to checkpoint storage")
CHECKPOINT_LAST_STEP = _REGISTRY.gauge(
    "mxtpu_checkpoint_last_step",
    "training step of the most recently committed checkpoint (the "
    "recovery point a preemption right now would resume from)")
CHECKPOINT_ERRORS_TOTAL = _REGISTRY.counter(
    "mxtpu_checkpoint_errors_total",
    "failed checkpoint snapshots/writes (training continues; the "
    "recovery point goes stale — alert on this)")
CHECKPOINT_DROPPED_TOTAL = _REGISTRY.counter(
    "mxtpu_checkpoint_dropped_total",
    "queued snapshots replaced by a newer one before the writer got to "
    "them (latest-wins backpressure: storage slower than the cadence)")

CHAOS_INJECTIONS_TOTAL = _REGISTRY.counter(
    "mxtpu_chaos_injections_total",
    "faults injected by the chaos harness (MXTPU_CHAOS), by kind and "
    "site — nonzero outside a test run means someone left chaos armed")

# -- live elasticity: runtime grow/shrink (resilience/elastic.py) ----------

ELASTIC_RESIZES_TOTAL = _REGISTRY.counter(
    "mxtpu_elastic_resizes_total",
    "runtime mesh resizes completed WITHOUT a process restart, by "
    "reason (chaos / notice / preempt / straggler / dead_peer / "
    "manual / signal)")
ELASTIC_RESIZE_SECONDS = _REGISTRY.histogram(
    "mxtpu_elastic_resize_seconds",
    "wall time of one in-process resize: snapshot-in-memory + mesh "
    "rebuild + pad-clipped logical re-shard + re-entry (training is "
    "paused exactly this long — the die->restore-from-disk "
    "alternative costs a full restart + recompile storm)")
ELASTIC_WORLD_SIZE = _REGISTRY.gauge(
    "mxtpu_elastic_world_size",
    "devices in the elastic trainer's current mesh (watch it shrink "
    "on eviction/preemption and grow on spot add)")
ELASTIC_STRAGGLER_EVICTIONS_TOTAL = _REGISTRY.counter(
    "mxtpu_elastic_straggler_evictions_total",
    "peers proactively resized out by the straggler policy "
    "(MXTPU_STRAGGLER_FACTOR) before the barrier watchdog timeout "
    "would have fired")
ELASTIC_PEER_LATENCY_SECONDS = _REGISTRY.histogram(
    "mxtpu_elastic_peer_latency_seconds",
    "per-rank barrier/heartbeat latency samples feeding the straggler "
    "policy, by rank (the membership monitor's barrier-latency "
    "histogram)")
KV_BARRIER_SECONDS = _REGISTRY.histogram(
    "mxtpu_kvstore_barrier_seconds",
    "wall time this process spent inside one kvstore barrier sync "
    "(the watchdog-timed wait; a rising tail here is the straggler "
    "signal the elastic monitor consumes)")

# -- executable introspection (MXTPU_INTROSPECT; observability/introspect) --

EXEC_FLOPS = _REGISTRY.gauge(
    "mxtpu_executable_flops",
    "XLA cost-analysis FLOPs per invocation of the compiled executable "
    "at each site (a superstep site's figure covers its K iterations)")
EXEC_BYTES_ACCESSED = _REGISTRY.gauge(
    "mxtpu_executable_bytes_accessed",
    "XLA cost-analysis bytes accessed (HBM traffic) per invocation, "
    "by site")
EXEC_ARITH_INTENSITY = _REGISTRY.gauge(
    "mxtpu_executable_arith_intensity",
    "flops / bytes_accessed per site — position on the roofline "
    "(compare against the device ridge point; docs/observability.md)")
EXEC_TEMP_BYTES = _REGISTRY.gauge(
    "mxtpu_executable_temp_bytes",
    "XLA memory-analysis temp allocation of the executable, by site")
EXEC_ARG_BYTES = _REGISTRY.gauge(
    "mxtpu_executable_argument_bytes",
    "XLA memory-analysis argument bytes of the executable, by site")
EXEC_OUT_BYTES = _REGISTRY.gauge(
    "mxtpu_executable_output_bytes",
    "XLA memory-analysis output bytes of the executable, by site")
EXEC_ALIAS_BYTES = _REGISTRY.gauge(
    "mxtpu_executable_alias_bytes",
    "bytes the compiled program aliased input->output (donation "
    "actually taking effect), by site")
DONATION_UNALIASED_TOTAL = _REGISTRY.counter(
    "mxtpu_donation_unaliased_total",
    "executables that donated buffers but aliased 0 bytes — the "
    "donation silently failed (also warned once per site)")

# -- inference serving SLOs (mxnet_tpu/serving) ----------------------------

SERVE_REQUESTS_TOTAL = _REGISTRY.counter(
    "mxtpu_serving_requests_total",
    "serving requests by model and terminal code (ok / shed / timeout / "
    "too_large / error / closed) — the SLO numerator/denominator pair")
SERVE_LATENCY_SECONDS = _REGISTRY.histogram(
    "mxtpu_serving_latency_seconds",
    "end-to-end request latency (submit -> result ready), by model — "
    "p50/p99 via Histogram.quantile / histogram_quantile")
SERVE_QUEUE_DEPTH = _REGISTRY.gauge(
    "mxtpu_serving_queue_depth",
    "requests waiting in the continuous-batching queue, by model "
    "(sampled at each batch dispatch; sustained depth near the bound "
    "means load-shedding is imminent)")
SERVE_BATCH_FILL = _REGISTRY.histogram(
    "mxtpu_serving_batch_fill",
    "valid-row fraction of each dispatched batch, by model (sum/count "
    "gives mean fill; low fill under load means max-wait is too short "
    "or buckets too fragmented)",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
SERVE_BATCHES_TOTAL = _REGISTRY.counter(
    "mxtpu_serving_batches_total",
    "batches dispatched to a bucket executable, by model and bucket")
SERVE_SHED_TOTAL = _REGISTRY.counter(
    "mxtpu_serving_shed_total",
    "requests rejected at submit because the bounded queue was full "
    "(backpressure / load shedding), by model")
SERVE_TIMEOUT_TOTAL = _REGISTRY.counter(
    "mxtpu_serving_timeout_total",
    "requests whose deadline expired before dispatch (typed timeout — "
    "never a stale result), by model")
SERVE_COMPILE_TOTAL = _REGISTRY.counter(
    "mxtpu_serving_compile_total",
    "AOT bucket-executable compiles at deploy time, by model — FLAT "
    "after seal(); any increase after warmup is a no-retrace-contract "
    "violation")
SERVE_LIVE_MODELS = _REGISTRY.gauge(
    "mxtpu_serving_live_models",
    "model versions currently live in the ModelRepository")
SERVE_SWAPS_TOTAL = _REGISTRY.counter(
    "mxtpu_serving_swaps_total",
    "repository version transitions, by model and outcome (committed / "
    "rolled_back / aborted — aborted = staged load failed verification "
    "and never became visible)")

# -- in-scan superstep device metrics (per-iteration, K-slot series) -------

SUPERSTEP_ITER_LOSS = _REGISTRY.series_gauge(
    "mxtpu_superstep_iter_loss",
    "per-iteration mean loss of the LAST superstep dispatch, one slot "
    "per scan iteration (lazy device array; syncs only when read) — "
    "K-step capture keeps per-step metric cadence")
SUPERSTEP_ITER_GRAD_NORM = _REGISTRY.series_gauge(
    "mxtpu_superstep_iter_grad_norm",
    "per-iteration in-graph global grad norm of the last superstep "
    "dispatch, one slot per scan iteration (lazy device array)")
SUPERSTEP_ITER_OVERFLOW = _REGISTRY.series_gauge(
    "mxtpu_superstep_iter_overflow",
    "per-iteration fp16 overflow flag (1 = that iteration skipped its "
    "update) of the last superstep dispatch (lazy device array)")

# -- cluster-scope federation (observability/federation.py) ----------------

FEDERATION_PUBLISH_TOTAL = _REGISTRY.counter(
    "mxtpu_federation_publish_total",
    "registry snapshot publishes by this rank: local heartbeat beats "
    "plus successful step-beat cross-rank exchanges")
FEDERATION_ERRORS_TOTAL = _REGISTRY.counter(
    "mxtpu_federation_errors_total",
    "failed federation exchanges (the step-beat poll degraded to a "
    "local-only publish; the cluster view goes stale, never dark)")
FEDERATION_RANKS = _REGISTRY.gauge(
    "mxtpu_federation_ranks",
    "ranks with a snapshot in the cluster table (compare against the "
    "world size: fewer means someone stopped publishing)")
FEDERATION_SNAPSHOT_AGE_SECONDS = _REGISTRY.gauge(
    "mxtpu_federation_snapshot_age_seconds",
    "age of each rank's latest federated snapshot, by rank")
FEDERATION_STALE_RANKS = _REGISTRY.gauge(
    "mxtpu_federation_stale_ranks",
    "1 when the rank's snapshot age exceeds MXTPU_FEDERATION_STALE_S "
    "(its last series stay exposed — marked, never silently dropped), "
    "by rank")
FEDERATION_LAST_STEP = _REGISTRY.gauge(
    "mxtpu_federation_last_step",
    "step-epoch id carried by each rank's latest snapshot, by rank — "
    "the cross-rank skew/straggler picture (max - min = steps of lag)")

# -- anomaly watchdog (observability/watchdog.py, MXTPU_WATCHDOG) ----------

ANOMALY_TOTAL = _REGISTRY.counter(
    "mxtpu_anomaly_total",
    "watchdog detector firings, by kind (nan / loss_spike / "
    "grad_explosion / step_time / queue_saturation / input_wait) — "
    "detection only, training numerics are never touched")

# -- serving request-phase decomposition (correlated tracing) --------------

SERVE_PHASE_SECONDS = _REGISTRY.histogram(
    "mxtpu_serving_phase_seconds",
    "per-request latency by phase (queue / batch / dispatch / slice), "
    "by model — decomposes the end-to-end p99 into where the time "
    "actually went",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5))
SERVE_SCHED_WAIT_SECONDS = _REGISTRY.counter(
    "mxtpu_serving_sched_wait_seconds_total",
    "scheduler-loop wall time blocked waiting for work on the admission "
    "queue, by model — the serving-side analogue of the prefetch-wait "
    "counter (high fraction = the batcher idles, not the device)")

# -- self-healing serving fleet (mxnet_tpu/serving/fleet.py) ---------------

FLEET_REPLICAS = _REGISTRY.gauge(
    "mxtpu_fleet_replicas",
    "replicas in the serving fleet by model and health state (live / "
    "suspect / dead / warm) — live below the autoscaler minimum means "
    "recovery is in progress")
FLEET_DISPATCH_TOTAL = _REGISTRY.counter(
    "mxtpu_fleet_dispatch_total",
    "router dispatches by model and replica index — a skewed "
    "distribution under uniform load means the depth feed sees a "
    "straggler (or the consistent-hash fallback is active)")
FLEET_RETRY_TOTAL = _REGISTRY.counter(
    "mxtpu_fleet_retry_total",
    "failover retries onto a surviving replica, by model and reason "
    "(dead / closed / pipe) — each is one request that would have hung "
    "on a dead host without the router")
FLEET_REPLICA_LOST_TOTAL = _REGISTRY.counter(
    "mxtpu_fleet_replica_lost_total",
    "requests that exhausted EVERY candidate replica and surfaced a "
    "typed ReplicaLost, by model — nonzero while any replica survives "
    "is a router bug")
FLEET_BROWNOUT = _REGISTRY.gauge(
    "mxtpu_fleet_brownout",
    "latched degraded-mode level by model: 0 normal, 1 shedding bulk, "
    "2 shedding bulk+interactive (critical always admitted) — the loud "
    "signal that the fleet is trading work for survival")
FLEET_SHED_TOTAL = _REGISTRY.counter(
    "mxtpu_fleet_shed_total",
    "requests refused by the brownout policy, by model and priority "
    "class — sheds must appear at bulk before interactive before "
    "critical (strict priority order)")
FLEET_AUTOSCALE_TOTAL = _REGISTRY.counter(
    "mxtpu_fleet_autoscale_total",
    "autoscaler actuations by model and action (grow / shrink / "
    "replace / to_zero / restore), routed through the elastic "
    "membership signal queue")
FLEET_HEDGED_TOTAL = _REGISTRY.counter(
    "mxtpu_fleet_hedged_total",
    "hedged duplicate dispatches (MXTPU_FLEET_HEDGE_MS > 0), by model "
    "— first result wins, the loser is discarded (inference is "
    "idempotent)")
FLEET_RECOVERY_SECONDS = _REGISTRY.gauge(
    "mxtpu_fleet_recovery_seconds",
    "wall time from the last detected replica death to the autoscaler's "
    "replacement replica serving again, by model — the chaos "
    "certification budget in bench.py fleet")

# -- autoregressive decode fast path (serving/generation.py, kvcache.py) ---

DECODE_TOKENS_TOTAL = _REGISTRY.counter(
    "mxtpu_decode_tokens_total",
    "tokens generated (prefill first-tokens + decode-chunk emissions), "
    "by model — with mxtpu_decode_chunks_total this is the "
    "dispatches-per-token certification pair")
DECODE_CHUNKS_TOTAL = _REGISTRY.counter(
    "mxtpu_decode_chunks_total",
    "single-dispatch decode-chunk executions (each advances EVERY "
    "active slot up to MXTPU_DECODE_CHUNK tokens in one XLA dispatch), "
    "by model")
DECODE_ITL_SECONDS = _REGISTRY.histogram(
    "mxtpu_decode_inter_token_seconds",
    "amortized inter-token latency: decode-chunk wall time / tokens the "
    "slot emitted in that chunk (tokens of one chunk arrive together), "
    "by model — p50/p99 are the bench's ITL baselines",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25))
DECODE_PREFILL_SECONDS = _REGISTRY.histogram(
    "mxtpu_decode_prefill_seconds",
    "prompt-ingestion dispatch wall time (per-bucket prefill executable "
    "+ first-token sample), by model — the join cost of token-level "
    "continuous batching")
DECODE_ACTIVE_SLOTS = _REGISTRY.gauge(
    "mxtpu_decode_active_slots",
    "decode-batch slots holding a live sequence (of MXTPU_DECODE_SLOTS), "
    "by model — sustained low fill under queue depth means prompts are "
    "stuck on cache admission (see mxtpu_kvcache_occupancy_ratio)")
KVCACHE_BLOCKS_USED = _REGISTRY.gauge(
    "mxtpu_kvcache_blocks_used",
    "paged KV cache blocks currently allocated (of the usable pool — "
    "block 0 is the reserved null sink), by model")
KVCACHE_OCCUPANCY = _REGISTRY.gauge(
    "mxtpu_kvcache_occupancy_ratio",
    "allocated fraction of the usable KV block pool, by model — near "
    "1.0 admission starts shedding (mxtpu_kvcache_oom_total) and "
    "MXTPU_KVCACHE_BLOCKS needs raising")
KVCACHE_FORKS_TOTAL = _REGISTRY.counter(
    "mxtpu_kvcache_forks_total",
    "block-table forks (shared-prefix refcount bumps; copy-on-write "
    "copies exactly one block on first divergent append), by model")
KVCACHE_OOM_TOTAL = _REGISTRY.counter(
    "mxtpu_kvcache_oom_total",
    "block allocations refused because the pool was exhausted (typed "
    "KVCacheOOM — admission backpressure or early retirement, never a "
    "partially-backed sequence), by model")


# ---------------------------------------------------------------------------
# hot-path record helpers (called only after an ENABLED check at the site)
# ---------------------------------------------------------------------------

def record_op_dispatch(name: str, dt: float):
    """Per-op dispatch accounting (ops/dispatch.py seam)."""
    key = (("op", name),)
    v = OP_DISPATCH_TOTAL._values
    v[key] = v.get(key, 0.0) + 1
    s = OP_DISPATCH_SECONDS._values
    s[key] = s.get(key, 0.0) + dt
    record_xla_dispatch("op")


def record_xla_dispatch(site: str, count: int = 1):
    """One compiled-executable invocation (jit call) at ``site`` — the
    unit the dispatch-count regression tests assert O(1) per step on."""
    key = (("site", site),)
    v = XLA_DISPATCH_TOTAL._values
    v[key] = v.get(key, 0.0) + count


def record_kv(kind: str, nbytes: int, count: int = 1):
    """kvstore traffic accounting: kind in {push, pull, pushpull}."""
    if kind == "push":
        tot, byt = KV_PUSH_TOTAL, KV_PUSH_BYTES
    elif kind == "pull":
        tot, byt = KV_PULL_TOTAL, KV_PULL_BYTES
    else:
        KV_PUSHPULL_TOTAL.inc(count)
        return
    tot.inc(count)
    byt.inc(nbytes)


def record_allreduce(dt: float, nbytes: int):
    KV_ALLREDUCE_SECONDS.observe(dt)
    KV_ALLREDUCE_BYTES.inc(nbytes)
    _TRACER.record("kvstore.allreduce", cat="comms",
                   ts=_time.perf_counter() - dt, dur=dt,
                   args={"bytes": nbytes})


def record_engine_wait(path: str, dt: float):
    key = (("path", path),)
    v = ENGINE_WAIT_TOTAL._values
    v[key] = v.get(key, 0.0) + 1
    s = ENGINE_WAIT_SECONDS._values
    s[key] = s.get(key, 0.0) + dt


def record_trainer_step(t0: float, t1: float, grad_norm=None):
    """One Trainer.step: advances the tracer step, records the span."""
    dt = t1 - t0
    TRAINER_STEP_TOTAL.inc()
    TRAINER_STEP_SECONDS.observe(dt)
    if grad_norm is not None:
        # lazy: the fused step hands a device scalar; it syncs only when
        # the gauge is read (value()/exposition), never per step
        TRAINER_GRAD_NORM.set_lazy(grad_norm)
    step = _TRACER.mark_step()
    args = {"step": step}
    if isinstance(grad_norm, float):
        # only plain floats go into the ring buffer: storing a lazy
        # device scalar per event would pin one live device buffer per
        # step for the lifetime of the 65536-event ring (the gauge above
        # keeps the latest lazy value; trace events just omit it)
        args["grad_norm"] = grad_norm
    _TRACER.record("trainer.step", cat="trainer", ts=t0, dur=dt, args=args)
    if attribution.ENABLED:
        attribution.record_step(t0, t1, site="trainer")


def record_superstep(k: int, t0: float, t1: float, grad_norm=None):
    """One K-step superstep dispatch: counts K iterations, observes the
    AMORTIZED per-step time, and advances the tracer step by K (host
    telemetry runs once per superstep — K-step cadence by design)."""
    dt = t1 - t0
    SUPERSTEP_TOTAL.inc(1, k=str(k))
    SUPERSTEP_ITERATIONS_TOTAL.inc(k)
    SUPERSTEP_STEP_SECONDS.observe(dt / max(k, 1))
    if grad_norm is not None:
        # lazy device scalar from the scan's last iteration — syncs only
        # at gauge-read time, never per superstep
        TRAINER_GRAD_NORM.set_lazy(grad_norm)
    step = None
    for _ in range(k):
        step = _TRACER.mark_step()
    _TRACER.record("trainer.superstep", cat="trainer", ts=t0, dur=dt,
                   args={"k": int(k), "step": step})
    if attribution.ENABLED:
        attribution.record_step(t0, t1, k=k, site="superstep")


def record_superstep_series(losses, gnorms=None, overflows=None):
    """Publish the per-iteration device series one superstep dispatch
    produced (scan ys: loss, in-graph grad norm, fp16 overflow flag).
    The arrays are stored WHOLE and LAZY — no slicing, no sync, zero
    added dispatches on the hot path; elements materialize only when a
    series gauge is read (summary/exposition/``superstep_series()``).
    This is what keeps K-step capture at per-step metric cadence."""
    SUPERSTEP_ITER_LOSS.set_series(losses)
    if gnorms is not None:
        SUPERSTEP_ITER_GRAD_NORM.set_series(gnorms)
    if overflows is not None:
        SUPERSTEP_ITER_OVERFLOW.set_series(overflows)


def superstep_series() -> dict:
    """The last superstep's per-iteration metrics as plain float lists
    (one device sync per series, here at read time): ``{"loss": [...],
    "grad_norm": [...], "overflow": [...]}`` — empty lists before the
    first superstep (or for series the capture did not produce)."""
    return {"loss": SUPERSTEP_ITER_LOSS.series(),
            "grad_norm": SUPERSTEP_ITER_GRAD_NORM.series(),
            "overflow": SUPERSTEP_ITER_OVERFLOW.series()}


def record_amp_scale(scale, overflow_total, overflow: bool):
    """One host-side loss-scale update (the eager AMP fallback — the
    fused step sets the gauges lazily via ``record_amp_lazy`` instead
    and emits no per-step trace event, keeping zero syncs)."""
    AMP_LOSS_SCALE.set(scale)
    AMP_OVERFLOW_TOTAL.set(float(overflow_total))
    _TRACER.record("amp.scale_update", cat="amp", ts=_time.perf_counter(),
                   dur=0.0, args={"scale": float(scale),
                                  "overflow_total": int(overflow_total),
                                  "overflow": bool(overflow)})


def record_amp_lazy(scale, overflow_total):
    """Fused-step AMP accounting: both values are device scalars stored
    WITHOUT syncing (they materialize at gauge-read time)."""
    AMP_LOSS_SCALE.set_lazy(scale)
    AMP_OVERFLOW_TOTAL.set_lazy(overflow_total)


def record_compile(block: str, dt: float, cause=None):
    """One CachedGraph build (gluon/block.py)."""
    CACHEDOP_COMPILE_TOTAL.inc(1, block=block)
    CACHEDOP_TRACE_SECONDS.inc(dt, block=block)
    if cause:
        CACHEDOP_RETRACE_TOTAL.inc(1, block=block, cause=cause)
    _TRACER.record(f"cachedop.compile[{block}]", cat="compile",
                   ts=_time.perf_counter() - dt, dur=dt,
                   args={"cause": cause or "first"})


def record_h2d(nbytes: int, dt: float, depth: int):
    """One prefetched batch staged to device (gluon/data/prefetcher.py)."""
    DATA_PREFETCH_BATCHES.inc()
    DATA_H2D_BYTES.inc(nbytes)
    DATA_H2D_SECONDS.observe(dt)
    DATA_PREFETCH_QUEUE_DEPTH.set(depth)
    _TRACER.record("data.h2d", cat="io", ts=_time.perf_counter() - dt,
                   dur=dt, args={"bytes": nbytes, "queue_depth": depth})


def record_stream_read(shard: str, nbytes: int, dt: float):
    """One storage read op by the streaming shard reader
    (gluon/data/stream.py ShardIndex.read)."""
    STREAM_READ_BYTES.inc(nbytes, shard=shard)
    STREAM_READ_SECONDS.inc(dt, shard=shard)
    STREAM_RECORDS_TOTAL.inc(1, shard=shard)


def record_stream_decode(dt: float):
    """One record decoded by the stream decode pool (busy time)."""
    STREAM_DECODE_SECONDS.inc(dt)


def record_stream_batch(wait: float, reorder_depth: int):
    """One batch delivered by StreamReader: consumer-wait accounting
    + the per-batch trace span telemetry_report joins against steps.
    Every 16th batch also emits a ``stream.stats`` instant carrying
    the cumulative per-shard read totals and decode-pool busy/wait so
    an exported trace is self-contained for the Input-pipeline
    section (registry counters don't travel with the JSONL)."""
    STREAM_BATCHES_TOTAL.inc()
    STREAM_CONSUMER_WAIT_SECONDS.inc(wait)
    STREAM_QUEUE_DEPTH.set(reorder_depth, queue="reorder")
    _TRACER.record("stream.batch", cat="io",
                   ts=_time.perf_counter() - wait, dur=wait,
                   args={"consumer_wait": wait,
                         "reorder_depth": reorder_depth})
    n = STREAM_BATCHES_TOTAL.total()
    if n % 16 == 1:
        per_shard = {}
        for labels in STREAM_READ_BYTES.labelsets():
            shard = labels.get("shard", "-")
            per_shard[shard] = {
                "bytes": STREAM_READ_BYTES.value(**labels),
                "seconds": STREAM_READ_SECONDS.value(**labels),
                "records": STREAM_RECORDS_TOTAL.value(**labels)}
        _TRACER.record(
            "stream.stats", cat="io", ph="i",
            args={"per_shard": per_shard,
                  "decode_busy": STREAM_DECODE_SECONDS.total(),
                  "decode_wait": STREAM_DECODE_WAIT_SECONDS.total(),
                  "consumer_wait": STREAM_CONSUMER_WAIT_SECONDS.total(),
                  "depth_raw": STREAM_QUEUE_DEPTH.value(queue="raw"),
                  "depth_reorder": reorder_depth,
                  "batches": n})


def record_ckpt_tick(dt: float):
    """In-LOOP checkpoint entry cost (resilience/checkpoint.py on_step:
    interval bookkeeping + snapshot dispatch + writer-queue handoff) —
    the slice the attribution plane charges to ckpt_overhead."""
    CHECKPOINT_TICK_SECONDS.inc(dt)
    _TRACER.record("checkpoint.tick", cat="resilience",
                   ts=_time.perf_counter() - dt, dur=dt)


def record_serve_batch(model: str, bucket, n_valid: int, capacity: int,
                       dt: float, depth: int, span_id=None):
    """One continuous-batching dispatch (mxnet_tpu/serving): batch-fill
    + queue-depth accounting and the per-batch trace span. ``span_id``
    (minted by the engine) parents the batch's per-request phase
    spans."""
    fill = n_valid / max(capacity, 1)
    SERVE_BATCHES_TOTAL.inc(1, model=model, bucket=str(bucket))
    SERVE_BATCH_FILL.observe(fill, model=model)
    SERVE_QUEUE_DEPTH.set(depth, model=model)
    _TRACER.record("serving.batch", cat="serving",
                   ts=_time.perf_counter() - dt, dur=dt, span_id=span_id,
                   args={"model": model, "bucket": str(bucket),
                         "n_valid": int(n_valid), "capacity": int(capacity),
                         "fill": round(fill, 4), "queue_depth": int(depth)})


def record_serve_request(model: str, code: str, latency=None):
    """Terminal accounting for one serving request. ``code`` is the
    typed outcome (ok / shed / timeout / too_large / error / closed);
    ``latency`` (submit -> result, seconds) only accompanies ok."""
    SERVE_REQUESTS_TOTAL.inc(1, model=model, code=code)
    if latency is not None:
        SERVE_LATENCY_SECONDS.observe(latency, model=model)
    if code == "shed":
        SERVE_SHED_TOTAL.inc(1, model=model)
        _TRACER.instant("serving.shed", cat="serving", model=model)
    elif code == "timeout":
        SERVE_TIMEOUT_TOTAL.inc(1, model=model)
        _TRACER.instant("serving.timeout", cat="serving", model=model)


def record_serve_swap(model: str, outcome: str, version=None,
                      prev_version=None):
    """One ModelRepository version transition (committed / rolled_back /
    aborted)."""
    SERVE_SWAPS_TOTAL.inc(1, model=model, outcome=outcome)
    _TRACER.instant("serving.swap", cat="serving", model=model,
                    outcome=outcome, version=str(version),
                    prev_version=str(prev_version))


def record_serve_submit(model: str, req_id: int):
    """Request-id birth: one instant event at ``submit`` so the id is
    traceable from ingress, before any batcher thread touches it."""
    _TRACER.instant("serving.submit", cat="serving", model=model,
                    req=int(req_id))


def record_serve_phases(model: str, req_id: int, t_submit: float,
                        phases: dict, parent=None):
    """Per-request phase decomposition (queue-wait -> batch-assembly ->
    dispatch -> slice-out): observes each phase into
    ``mxtpu_serving_phase_seconds`` and records one ``serving.request``
    child span carrying the request id + its parent batch span id —
    the correlated-trace leg that makes p99 decomposable."""
    args = {"model": model, "req": int(req_id)}
    if parent is not None:
        args["parent"] = int(parent)
    total = 0.0
    for phase, dur in phases.items():
        if dur is None:
            continue
        dur = max(float(dur), 0.0)
        total += dur
        SERVE_PHASE_SECONDS.observe(dur, model=model, phase=phase)
        args[f"{phase}_ms"] = round(dur * 1e3, 3)
    _TRACER.record("serving.request", cat="serving", ts=t_submit,
                   dur=total, args=args)


def record_fleet_states(model: str, counts: dict):
    """Publish the fleet's replica census: ``counts`` maps health state
    (live / suspect / dead / warm) -> replica count. States absent from
    ``counts`` are zeroed so a recovered fleet stops advertising dead
    rows."""
    for state in ("live", "suspect", "dead", "warm"):
        FLEET_REPLICAS.set(float(counts.get(state, 0)), model=model,
                           state=state)


def record_fleet_brownout(model: str, level: int, prev: int):
    """One brownout state-machine transition: the latched level gauge
    plus a loud trace instant (direction says entering vs draining)."""
    FLEET_BROWNOUT.set(float(level), model=model)
    _TRACER.instant("fleet.brownout", cat="serving", model=model,
                    level=int(level), prev=int(prev),
                    direction="enter" if level > prev else "exit")


def record_fleet_autoscale(model: str, action: str, n: int):
    """One autoscaler actuation (grow / shrink / replace / to_zero /
    restore) with the resulting replica target."""
    FLEET_AUTOSCALE_TOTAL.inc(1, model=model, action=action)
    _TRACER.instant("fleet.autoscale", cat="serving", model=model,
                    action=action, target=int(n))


def serve_phase_snapshot(model: str) -> dict:
    """p50/p99 per phase for ``model`` from the request-span histogram
    (empty until the engine served its first batch)."""
    out = {}
    for phase in ("queue", "batch", "dispatch", "slice"):
        n = SERVE_PHASE_SECONDS.value(model=model, phase=phase)
        if not n:
            continue
        out[phase] = {
            "p50_s": SERVE_PHASE_SECONDS.quantile(0.5, model=model,
                                                  phase=phase),
            "p99_s": SERVE_PHASE_SECONDS.quantile(0.99, model=model,
                                                  phase=phase),
            "count": n,
        }
    return out


def serve_slo_snapshot(model: str) -> dict:
    """p50/p99 latency + request/batch counters for ``model`` as plain
    floats (reads the histograms — off the hot path by construction)."""
    p50 = SERVE_LATENCY_SECONDS.quantile(0.5, model=model)
    p99 = SERVE_LATENCY_SECONDS.quantile(0.99, model=model)
    n = SERVE_BATCH_FILL.value(model=model)
    return {
        "model": model,
        "requests_ok": SERVE_REQUESTS_TOTAL.value(model=model, code="ok"),
        "latency_p50_s": p50,
        "latency_p99_s": p99,
        "latency_count": SERVE_LATENCY_SECONDS.value(model=model),
        "batches": n,
        "mean_batch_fill": (SERVE_BATCH_FILL.sum(model=model) / n) if n else None,
        "shed": SERVE_SHED_TOTAL.value(model=model),
        "timeouts": SERVE_TIMEOUT_TOTAL.value(model=model),
        "compiles": SERVE_COMPILE_TOTAL.value(model=model),
        "phases": serve_phase_snapshot(model),
    }


# ---------------------------------------------------------------------------
# exporters / summaries
# ---------------------------------------------------------------------------

def dump_prometheus() -> str:
    """Prometheus text exposition of the whole registry."""
    return _REGISTRY.dump_prometheus()


def dump_chrome_trace(path=None) -> str:
    return _TRACER.dump_chrome_trace(path)


def dump_jsonl(path=None) -> str:
    return _TRACER.dump_jsonl(path)


def summary() -> str:
    """Human-readable snapshot of the key run metrics (the per-epoch
    body logged by the estimator handler / callback hook)."""
    lines = ["telemetry summary:"]
    n_ops = OP_DISPATCH_TOTAL.total()
    if n_ops:
        top = sorted(OP_DISPATCH_SECONDS._values.items(),
                     key=lambda kv: kv[1], reverse=True)[:5]
        lines.append(f"  op dispatches: {int(n_ops)} "
                     f"({OP_DISPATCH_SECONDS.total() * 1e3:.2f} ms dispatch)")
        for key, secs in top:
            name = dict(key).get("op", "?")
            cnt = int(OP_DISPATCH_TOTAL._values.get(key, 0))
            lines.append(f"    {name:<28}{cnt:>8} calls"
                         f"{secs * 1e3:>12.3f} ms")
    compiles = CACHEDOP_COMPILE_TOTAL.total()
    if compiles or CACHEDOP_CACHE_HITS.total():
        lines.append(
            f"  cachedop: {int(compiles)} compiles, "
            f"{int(CACHEDOP_CACHE_HITS.total())} cache hits, "
            f"{CACHEDOP_TRACE_SECONDS.total() * 1e3:.1f} ms tracing, "
            f"{int(CACHEDOP_RETRACE_TOTAL.total())} retraces")
    if KV_PUSH_TOTAL.total() or KV_PULL_TOTAL.total() \
            or KV_PUSHPULL_TOTAL.total():
        lines.append(
            f"  kvstore: {int(KV_PUSH_TOTAL.total())} pushes "
            f"({int(KV_PUSH_BYTES.total())} B), "
            f"{int(KV_PULL_TOTAL.total())} pulls "
            f"({int(KV_PULL_BYTES.total())} B), "
            f"{int(KV_PUSHPULL_TOTAL.total())} pushpulls, "
            f"{int(KV_BARRIER_TOTAL.total())} barriers")
    staged = DATA_PREFETCH_BATCHES.total()
    if staged:
        lines.append(
            f"  input pipeline: {int(staged)} batches staged "
            f"({int(DATA_H2D_BYTES.total())} B h2d, "
            f"{DATA_PREFETCH_WAIT_SECONDS.total() * 1e3:.1f} ms "
            f"consumer wait)")
    cc_h, cc_m = COMPILE_CACHE_HITS.total(), COMPILE_CACHE_MISSES.total()
    if cc_h or cc_m:
        lines.append(f"  compile cache: {int(cc_h)} hits, {int(cc_m)} misses")
    ss = SUPERSTEP_TOTAL.total()
    if ss:
        iters = SUPERSTEP_ITERATIONS_TOTAL.total()
        mean_ms = (SUPERSTEP_STEP_SECONDS.sum() / max(ss, 1)) * 1e3
        lines.append(
            f"  superstep: {int(ss)} dispatches covering {int(iters)} "
            f"steps ({iters / ss:.1f} steps/dispatch, "
            f"{mean_ms:.2f} ms/step amortized)")
    steps = TRAINER_STEP_TOTAL.total()
    if steps:
        mean_ms = TRAINER_STEP_SECONDS.sum() / max(steps, 1) * 1e3
        lines.append(f"  trainer: {int(steps)} steps, "
                     f"{mean_ms:.2f} ms/step mean, "
                     f"last grad norm {TRAINER_GRAD_NORM.value():.4g}")
    if AMP_LOSS_SCALE._values or AMP_OVERFLOW_TOTAL._values:
        lines.append(
            f"  amp: loss scale {AMP_LOSS_SCALE.value():.4g}, "
            f"{int(AMP_OVERFLOW_TOTAL.value())} overflows (skipped steps)")
    waits = ENGINE_WAIT_TOTAL.total()
    if waits:
        lines.append(
            f"  engine.wait: {int(waits)} probes, "
            f"{ENGINE_WAIT_SECONDS.total() * 1e3:.1f} ms blocked")
    if len(lines) == 1:
        lines.append("  (no events recorded)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# performance introspection / crash flight recorder / scrape endpoint
# (submodules bind as attributes: observability.introspect / .flight)
# ---------------------------------------------------------------------------

from . import flight  # noqa: E402,F401
from . import introspect  # noqa: E402,F401
from .introspect import (  # noqa: E402,F401
    cost_table,
    mfu_estimate,
    profile_window,
)
from .serve import (  # noqa: E402,F401
    metrics_port,
    serve_metrics,
    stop_metrics_server,
)
from . import federation  # noqa: E402,F401
from . import watchdog  # noqa: E402,F401
from . import attribution  # noqa: E402,F401

# MXTPU_DUMP_ON_CRASH: hooks install at import (opt-in via env only —
# without the var this is a dict read and nothing else)
flight.maybe_install()


def __getattr__(name):
    # TelemetryHandler subclasses the estimator's event mixins; loading it
    # eagerly would cycle through gluon at package-import time.
    if name == "TelemetryHandler":
        from .handlers import TelemetryHandler

        return TelemetryHandler
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
