"""Cross-rank metric federation: the job-scope view of the registry.

Every observability surface below this module is per-process: rank 0's
``/metrics`` says nothing about rank 5's straggling allreduce. This
module turns the per-process registries into ONE cluster picture:

- each rank periodically serializes its ``MetricsRegistry`` into a
  plain-JSON snapshot and publishes it over the kvstore side-channel
  (``kvstore/dist.py::all_gather_bytes`` — the existing collective
  plumbing, NOT a new transport; no server processes, no sockets),
- rank 0 (any rank, really — the gather is symmetric) merges the
  snapshots and exposes them at ``GET /metrics/cluster``: every series
  re-labeled with ``rank="r"``, plus job-level aggregates under
  ``rank="all"`` (sum for counters, min/median/max for gauges,
  element-wise merged buckets for histograms),
- a rank whose snapshot age exceeds ``MXTPU_FEDERATION_STALE_S`` is
  MARKED via ``mxtpu_federation_stale_ranks{rank=...} 1`` — its last
  series stay visible; silence is a signal, never a silent drop,
- the per-rank ``step_epoch`` (the shared tracer step id stamped by
  ``Trainer.step``/``Superstep.step``) rides every snapshot, so
  ``tools/telemetry_report.py`` can line the same step up across ranks
  (the cross-rank straggler/skew picture).

Collective-ordering contract: cross-process collectives must enter
the wire in the SAME order on every rank, and each rank's publisher
timer fires on an independent clock — so the daemon thread NEVER
issues collectives. It only refreshes this rank's local row + the
meta gauges. The multi-process ``exchange()`` is driven exclusively
from ``poll()``, the step-boundary hook the trainer calls on the same
thread as the pushpull (like ``watchdog.poll``): it fires on a
step-count beat (``MXTPU_FEDERATION_BEAT_STEPS``) derived from the
shared tracer step, and synchronous data-parallel ranks execute
identical step sequences, so every rank enters the gather between the
same two training allreduces.

Hot-path contract (pinned by the dispatch-count regression test): the
training loop NEVER blocks on per-step federation work. Snapshots are
taken on the publisher daemon thread (or an HTTP handler thread);
lazy device scalars stored by ``Gauge.set_lazy`` float exactly there
— zero added dispatches, zero added syncs per step. In a multi-process
world the beat-step exchange is the one deliberate exception: two
watchdog-timed collectives every ``MXTPU_FEDERATION_BEAT_STEPS``
steps, amortized off the steady-state step cost.

Switch: ``MXTPU_FEDERATION=1`` arms the background publisher
(interval ``MXTPU_FEDERATION_INTERVAL_S``) and the step-beat poll;
``exchange()`` / ``publish_local()`` work without it for
deterministic tests.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from ..base import getenv
from .metrics import Histogram, MetricsRegistry, SeriesGauge

_logger = logging.getLogger("mxnet_tpu.observability.federation")

#: rank -> {"snap": decoded snapshot dict, "recv": monotonic receive time}
_CLUSTER = {}
_CLUSTER_LOCK = threading.Lock()

_PUBLISHER = {"thread": None, "stop": None}
_PUB_LOCK = threading.Lock()

#: step-beat state for the trainer-driven exchange: armed by start(),
#: consumed by poll() on the trainer thread. ``last_idx`` is the last
#: beat index (tracer step // MXTPU_FEDERATION_BEAT_STEPS) exchanged —
#: pure step arithmetic, identical on every rank by construction.
_BEAT = {"active": False, "last_idx": -1}

#: machine-checked lock protocol (mxtpu-lint thread-guard): the cluster
#: table is written by the publisher/HTTP threads and read by the
#: exposition path concurrently; the publisher singleton and the beat
#: state mutate only under the publisher lock so start/stop cannot
#: leak a second daemon thread or a stale beat counter
_GUARDED_BY = {"_CLUSTER": "_CLUSTER_LOCK", "_PUBLISHER": "_PUB_LOCK",
               "_BEAT": "_PUB_LOCK"}


def federation_enabled() -> bool:
    """``MXTPU_FEDERATION`` (default off): arm the background publisher
    thread at first Context creation."""
    return bool(getenv("MXTPU_FEDERATION", False, dtype=bool))


def federation_interval_s() -> float:
    """``MXTPU_FEDERATION_INTERVAL_S`` (default 10): publisher cadence."""
    return float(getenv("MXTPU_FEDERATION_INTERVAL_S", 10.0, dtype=float))


def federation_stale_s() -> float:
    """``MXTPU_FEDERATION_STALE_S`` (default 30): snapshot age beyond
    which a rank is marked stale (0 disables marking)."""
    return float(getenv("MXTPU_FEDERATION_STALE_S", 30.0, dtype=float))


def federation_beat_steps() -> int:
    """``MXTPU_FEDERATION_BEAT_STEPS`` (default 32): trainer steps
    between multi-process exchanges. A step count, not seconds — the
    beat must be derived from state every rank advances identically
    (the shared step counter), never a per-rank wall clock."""
    return max(1, int(getenv("MXTPU_FEDERATION_BEAT_STEPS", 32,
                             dtype=int)))


# ---------------------------------------------------------------------------
# snapshot / ingest
# ---------------------------------------------------------------------------

def _encode_key(key: tuple) -> str:
    """Label key tuple -> canonical JSON string (snapshots are JSON)."""
    return json.dumps([list(p) for p in key])


def _decode_key(s: str) -> tuple:
    return tuple((str(k), str(v)) for k, v in json.loads(s))


def _float(v) -> float:
    try:
        return float(v)  # mxtpu-lint: host-sync-ok
    except (TypeError, ValueError):
        return float("nan")


def _metric_kind(m) -> str:
    if isinstance(m, Histogram):
        return "histogram"
    if isinstance(m, SeriesGauge):
        return "series_gauge"
    return m.kind


def snapshot(rank=None):  # mxtpu-lint: hot-path
    """Serialize the process registry into a plain-JSON dict.

    Runs on the publisher/HTTP thread, never the training loop: this is
    exactly where lazy device scalars (``Gauge.set_lazy``, the
    superstep's series gauges) float to plain floats — the deliberate
    off-hot-path sync point.
    """
    from . import _REGISTRY, _TRACER

    if rank is None:
        rank = _process_index()
    metrics = {}
    for m in _REGISTRY.metrics():
        vals = {}
        for key in list(m._values):
            raw = m._values.get(key)
            if raw is None:
                continue
            if isinstance(m, Histogram):
                vals[_encode_key(key)] = [_float(x) for x in raw]
            elif isinstance(m, SeriesGauge):
                if hasattr(raw, "tolist"):
                    raw = raw.tolist()  # mxtpu-lint: host-sync-ok
                vals[_encode_key(key)] = [_float(x) for x in raw]
            else:
                vals[_encode_key(key)] = _float(raw)
        if not vals:
            continue
        entry = {"kind": _metric_kind(m), "help": m.help, "values": vals}
        if isinstance(m, Histogram):
            entry["buckets"] = list(m.buckets)
        metrics[m.name] = entry
    return {
        "rank": int(rank),  # mxtpu-lint: host-sync-ok
        "wall": time.time(),
        # host-side step counter, not a device value
        "step_epoch": int(_TRACER.step),  # mxtpu-lint: host-sync-ok
        "metrics": metrics,
    }


def _process_index() -> int:
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def _world_size() -> int:
    try:
        import jax

        return int(jax.process_count())  # mxtpu-lint: host-sync-ok
    except Exception:
        return 1


def ingest(snap: dict, recv_mono=None):
    """Record one rank's snapshot into the cluster table (the seam the
    exchange path, tests and bench synthetic ranks all feed)."""
    rank = int(snap.get("rank", 0))
    entry = {"snap": snap,
             "recv": time.monotonic() if recv_mono is None else recv_mono}
    with _CLUSTER_LOCK:
        _CLUSTER[rank] = entry
    return rank


def publish_local():
    """Snapshot THIS rank and ingest it locally (the single-process
    degenerate exchange; also refreshes our own row before exposition
    so the serving rank is never its own stale entry)."""
    return ingest(snapshot())


def exchange():
    """All-gather every rank's snapshot over the kvstore side-channel
    and ingest them all. Raises on collective failure (the step-beat
    ``poll()`` catches and degrades to ``publish_local``; a dist test
    lets the platform error surface so the launcher skip-contract
    applies).

    Call ONLY from a point ordered identically on every rank — the
    step-boundary ``poll()`` or a synchronous test — never from a
    free-running thread: the two side-channel collectives must
    interleave with the training allreduces in the same order on
    every process (see ``all_gather_bytes``).
    """
    snap = snapshot()
    payload = json.dumps(snap, default=float).encode("utf-8")
    from ..kvstore.dist import all_gather_bytes

    blobs = all_gather_bytes(payload)
    now = time.monotonic()
    for blob in blobs:
        if not blob:
            continue
        ingest(json.loads(blob.decode("utf-8")), recv_mono=now)
    return len(blobs)


def reset():
    """Drop every ingested snapshot (test isolation)."""
    with _CLUSTER_LOCK:
        _CLUSTER.clear()


# ---------------------------------------------------------------------------
# staleness + cluster meta gauges
# ---------------------------------------------------------------------------

def cluster_ranks() -> list:
    with _CLUSTER_LOCK:
        return sorted(_CLUSTER)


def stale_ranks(now=None) -> list:
    """Ranks whose snapshot age exceeds ``MXTPU_FEDERATION_STALE_S``."""
    limit = federation_stale_s()
    if limit <= 0:
        return []
    now = time.monotonic() if now is None else now
    with _CLUSTER_LOCK:
        ages = {r: now - e["recv"] for r, e in _CLUSTER.items()}
    return sorted(r for r, age in ages.items() if age > limit)


def cluster_values(metric, match=None, fresh_only=True, now=None):
    """Consumer API: per-rank values of one scalar metric across the
    ingested cluster table — ``{rank: float}``.

    ``match`` filters labelsets by a subset dict (e.g. ``{"model":
    "resnet"}``); multiple surviving labelsets per rank are summed.
    With ``fresh_only`` (default) stale ranks are EXCLUDED — a
    consumer that gets ``{}`` back knows the federation is cold and
    must fall back to local signals (the fleet router's
    consistent-hash fallback). Histogram/series metrics are skipped:
    this reads the scalar plane (queue depths, counters, gauges)."""
    match = match or {}
    stale = set(stale_ranks(now)) if fresh_only else ()
    out = {}
    with _CLUSTER_LOCK:
        snaps = {r: e["snap"] for r, e in _CLUSTER.items()
                 if r not in stale}
    for rank, snap in snaps.items():
        entry = (snap.get("metrics") or {}).get(metric)
        if not entry or entry.get("kind") in ("histogram", "series_gauge"):
            continue
        total, hit = 0.0, False
        for enc, value in (entry.get("values") or {}).items():
            try:
                labels = dict(_decode_key(enc))
            except Exception:
                continue
            if any(labels.get(k) != str(v) for k, v in match.items()):
                continue
            if isinstance(value, (int, float)) and value == value:
                total += float(value)
                hit = True
        if hit:
            out[rank] = total
    return out


def update_cluster_meta(now=None):
    """Refresh the federation meta gauges in the LOCAL registry (they
    ride the next snapshot like any other series): rank count, per-rank
    snapshot age, per-rank stale flag, per-rank last step_epoch."""
    from . import (
        FEDERATION_LAST_STEP,
        FEDERATION_RANKS,
        FEDERATION_SNAPSHOT_AGE_SECONDS,
        FEDERATION_STALE_RANKS,
    )

    now = time.monotonic() if now is None else now
    stale = set(stale_ranks(now))
    with _CLUSTER_LOCK:
        entries = {r: (now - e["recv"], e["snap"].get("step_epoch", 0))
                   for r, e in _CLUSTER.items()}
    FEDERATION_RANKS.set(len(entries))
    for r, (age, step) in entries.items():
        FEDERATION_SNAPSHOT_AGE_SECONDS.set(age, rank=str(r))
        FEDERATION_STALE_RANKS.set(1.0 if r in stale else 0.0, rank=str(r))
        FEDERATION_LAST_STEP.set(float(step), rank=str(r))
    return sorted(stale)


# ---------------------------------------------------------------------------
# merged exposition
# ---------------------------------------------------------------------------

def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    if not n:
        return 0.0
    mid = n // 2
    return xs[mid] if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0


def _rekey(key: tuple) -> list:
    # a base series may itself carry a rank="…" label (the federation
    # meta gauges are BY observed rank): rename it to peer="…" so the
    # publisher's own rank label stays unique in the merged exposition
    return [("peer", v) if k == "rank" else (k, v) for k, v in key]


def _with_rank(key: tuple, rank: str) -> tuple:
    return tuple(sorted(_rekey(key) + [("rank", rank)]))


def _with_agg(key: tuple, rank: str, agg: str) -> tuple:
    return tuple(sorted(_rekey(key) + [("rank", rank), ("agg", agg)]))


def cluster_registry() -> MetricsRegistry:
    """Merge every ingested snapshot into a fresh registry: per-rank
    series under ``rank="r"`` plus job aggregates under ``rank="all"``
    (counters sum; gauges min/median/max; histogram bucket lists merge
    element-wise when the rank bucket layouts agree)."""
    with _CLUSTER_LOCK:
        snaps = {r: e["snap"] for r, e in sorted(_CLUSTER.items())}

    reg = MetricsRegistry()
    # name -> {"kind", "help", "buckets", "by_key": {base key: {rank: value}}}
    merged = {}
    for rank, snap in snaps.items():
        for name, ent in (snap.get("metrics") or {}).items():
            slot = merged.setdefault(name, {
                "kind": ent.get("kind", "gauge"),
                "help": ent.get("help", ""),
                "buckets": ent.get("buckets"),
                "bucket_mismatch": False,
                "by_key": {},
            })
            if slot["kind"] == "histogram" and ent.get("buckets") is not None:
                if slot["buckets"] is None:
                    slot["buckets"] = ent["buckets"]
                elif list(slot["buckets"]) != list(ent["buckets"]):
                    slot["bucket_mismatch"] = True
            for enc_key, value in (ent.get("values") or {}).items():
                try:
                    key = _decode_key(enc_key)
                except (ValueError, TypeError):
                    continue
                slot["by_key"].setdefault(key, {})[rank] = value

    for name in sorted(merged):
        slot = merged[name]
        kind = slot["kind"]
        if kind == "counter":
            m = reg.counter(name, slot["help"])
        elif kind == "histogram":
            m = reg.histogram(name, slot["help"],
                              buckets=slot["buckets"] or None)
        elif kind == "series_gauge":
            m = reg.series_gauge(name, slot["help"])
        else:
            m = reg.gauge(name, slot["help"])
        for key, by_rank in slot["by_key"].items():
            for rank, value in by_rank.items():
                if kind == "histogram" and not (
                        isinstance(value, list)
                        and len(value) == len(m.buckets) + 3):
                    # a rank running a different bucket layout can't be
                    # rendered against this exposition's `le` edges —
                    # drop the row rather than crash the scrape (its
                    # scalar series still expose; aggregates are
                    # already suppressed via bucket_mismatch)
                    continue
                m._values[_with_rank(key, str(rank))] = (
                    list(value) if isinstance(value, list) else value)
            # job-level aggregate under rank="all"
            if kind == "counter":
                m._values[_with_rank(key, "all")] = sum(
                    v for v in by_rank.values()
                    if isinstance(v, (int, float)))
            elif kind == "gauge":
                vals = [v for v in by_rank.values()
                        if isinstance(v, (int, float)) and v == v]
                if vals:
                    m._values[_with_agg(key, "all", "min")] = min(vals)
                    m._values[_with_agg(key, "all", "median")] = _median(vals)
                    m._values[_with_agg(key, "all", "max")] = max(vals)
            elif kind == "histogram" and not slot["bucket_mismatch"]:
                recs = [v for v in by_rank.values() if isinstance(v, list)]
                width = len(m.buckets) + 3  # buckets + Inf + sum + count
                recs = [r for r in recs if len(r) == width]
                if recs:
                    total = [0.0] * width
                    for rec in recs:
                        for i, x in enumerate(rec):
                            total[i] += x
                    # counts back to ints so exposition matches a local
                    # histogram byte-for-byte (sum stays float)
                    agg = [int(x) for x in total[:-2]] + [total[-2],
                                                          int(total[-1])]
                    m._values[_with_rank(key, "all")] = agg
            # series gauges stay per-rank: per-slot series from
            # different ranks are different dispatches, not one series
    return reg


def dump_prometheus_cluster() -> str:
    """The ``/metrics/cluster`` body: refresh our own snapshot + the
    meta gauges, then expose the merged per-rank registry."""
    publish_local()
    update_cluster_meta()
    # meta gauges changed after our snapshot was taken — refresh once
    # more so the exposed row carries the current stale/age picture
    publish_local()
    return cluster_registry().dump_prometheus()


def dump_cluster_snapshot(path=None) -> str:
    """JSON post-mortem bundle for ``tools/telemetry_report.py``: every
    rank's snapshot, the stale set, and this rank's trace events (so
    the report's existing per-process sections render from the same
    file)."""
    from . import _TRACER

    publish_local()
    stale = update_cluster_meta()
    with _CLUSTER_LOCK:
        ranks = {str(r): e["snap"] for r, e in sorted(_CLUSTER.items())}
    body = json.dumps({
        "federation": 1,
        "generated_wall": time.time(),
        "stale": [int(r) for r in stale],
        "ranks": ranks,
        "events": _TRACER.events(),
    }, default=float)
    if path:
        with open(path, "w") as f:
            f.write(body)
    return body


# ---------------------------------------------------------------------------
# background publisher
# ---------------------------------------------------------------------------

def _publish_once():  # mxtpu-lint: hot-path
    """One publisher heartbeat: refresh OUR row + the meta gauges.

    LOCAL ONLY — this runs on the daemon timer thread, whose clock is
    independent per rank, so it must never issue collectives: a
    federation gather launched here can interleave differently with
    the training loop's allreduces on different ranks (mismatched
    cross-process collective order deadlocks or corrupts results).
    The multi-process exchange lives in ``poll()``."""
    from . import FEDERATION_PUBLISH_TOTAL

    publish_local()
    FEDERATION_PUBLISH_TOTAL.inc()
    update_cluster_meta()


def _exchange_once():  # mxtpu-lint: hot-path
    """One step-beat exchange: failures degrade to a local publish
    (counted, logged) so the scrape endpoint never goes dark."""
    from . import FEDERATION_ERRORS_TOTAL, FEDERATION_PUBLISH_TOTAL

    try:
        exchange()
        FEDERATION_PUBLISH_TOTAL.inc()
    except Exception as e:
        FEDERATION_ERRORS_TOTAL.inc()
        _logger.warning("federation exchange failed (%s); publishing "
                        "locally only", e)
        try:
            publish_local()
        except Exception:
            _logger.exception("federation local publish failed")
    update_cluster_meta()


def poll():  # mxtpu-lint: hot-path
    """Trainer-cadence hook (the step thread, right after pushpull):
    the ONLY place a multi-process federation exchange runs.

    Fires on a step-count beat (``MXTPU_FEDERATION_BEAT_STEPS``)
    derived from the shared tracer step: synchronous data-parallel
    ranks execute identical step sequences, so every rank reaches the
    same beat between the same two training allreduces — the
    side-channel collectives stay identically ordered across the
    world, which a per-rank interval timer cannot guarantee.
    Single-process worlds are fully covered by the daemon heartbeat;
    there poll() is a no-op (the zero-added-dispatch contract)."""
    if not _BEAT["active"]:
        return False
    if _world_size() <= 1:
        return False
    from . import _TRACER

    idx = _TRACER.step // federation_beat_steps()
    with _PUB_LOCK:
        if idx <= _BEAT["last_idx"]:
            return False
        _BEAT["last_idx"] = idx
    _exchange_once()
    return True


def _publisher_loop(stop, interval):  # mxtpu-lint: hot-path
    while not stop.wait(interval):
        _publish_once()


def start(interval=None) -> bool:
    """Start the publisher daemon thread and arm the step-beat poll
    (idempotent)."""
    if interval is None:
        interval = federation_interval_s()
    with _PUB_LOCK:
        if _PUBLISHER["thread"] is not None and \
                _PUBLISHER["thread"].is_alive():
            return False
        stop_ev = threading.Event()
        t = threading.Thread(
            target=_publisher_loop, args=(stop_ev, float(interval)),
            name="mxtpu-federation", daemon=True)
        _PUBLISHER.update(thread=t, stop=stop_ev)
        _BEAT.update(active=True, last_idx=-1)
        t.start()
    return True


def stop():
    """Stop the publisher thread and disarm the step-beat poll
    (idempotent); join outside the lock."""
    with _PUB_LOCK:
        t, ev = _PUBLISHER["thread"], _PUBLISHER["stop"]
        _PUBLISHER.update(thread=None, stop=None)
        _BEAT.update(active=False, last_idx=-1)
    if ev is not None:
        ev.set()
    if t is not None:
        t.join(timeout=5)


def maybe_start():
    """Arm from ``MXTPU_FEDERATION=1`` (first-Context wiring, same
    deferred hookup as the metrics endpoint); no-op otherwise."""
    if federation_enabled():
        start()
