"""Background-thread Prometheus scrape endpoint.

``observability.serve_metrics(port)`` starts a daemon-thread HTTP
server exposing the existing text exposition:

- ``GET /metrics``  -> ``dump_prometheus()`` (text/plain; version 0.0.4)
- ``GET /metrics/cluster`` -> the federated job-scope exposition
  (every rank's series under ``rank="r"`` + aggregates; see
  ``observability/federation.py``)
- ``GET /healthz``  -> ``ok`` (liveness — answers even mid-step, since
  the server thread never touches the device)

Anything else is 404. The env hookup is ``MXTPU_METRICS_PORT=<port>``:
the first ``Context`` creation starts the server (same deferred wiring
as ``MXTPU_COMPILE_CACHE``). ``stop_metrics_server()`` shuts it down
idempotently; starting while already serving returns the live port
(re-binding a second port would double-scrape the same process).
"""

from __future__ import annotations

import logging
import threading

from ..base import getenv

_logger = logging.getLogger("mxnet_tpu.observability")

_SERVER = {"httpd": None, "thread": None, "port": None}
_LOCK = threading.Lock()

#: machine-checked lock protocol (mxtpu-lint thread-guard): the server
#: singleton mutates only under _LOCK — concurrent serve/stop otherwise
#: leaks an orphan httpd thread bound to the port
_GUARDED_BY = {"_SERVER": "_LOCK"}


def _make_handler():
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            if self.path.split("?")[0] == "/metrics":
                from . import dump_prometheus

                try:
                    body = dump_prometheus().encode()
                except Exception as e:  # scrape must not kill the server
                    self.send_error(500, f"exposition failed: {e}")
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.split("?")[0] == "/metrics/cluster":
                from .federation import dump_prometheus_cluster

                try:
                    body = dump_prometheus_cluster().encode()
                except Exception as e:  # scrape must not kill the server
                    self.send_error(500, f"cluster exposition failed: {e}")
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.split("?")[0] == "/healthz":
                body = b"ok\n"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

        def log_message(self, fmt, *args):  # scrapes are not app logs
            _logger.debug("metrics server: " + fmt, *args)

    return Handler


def serve_metrics(port=None, host="0.0.0.0") -> int:
    """Start the scrape endpoint on ``port`` (0 = ephemeral) in a
    daemon thread; returns the bound port. Idempotent: if already
    serving, returns the live port without rebinding."""
    from http.server import ThreadingHTTPServer

    with _LOCK:
        if _SERVER["httpd"] is not None:
            return _SERVER["port"]
        if port is None:
            port = int(getenv("MXTPU_METRICS_PORT", 0, dtype=int))
        httpd = ThreadingHTTPServer((host, int(port)), _make_handler())
        httpd.daemon_threads = True
        thread = threading.Thread(target=httpd.serve_forever,
                                  name="mxtpu-metrics", daemon=True)
        thread.start()
        _SERVER.update(httpd=httpd, thread=thread,
                       port=httpd.server_address[1])
        _logger.info("metrics endpoint serving on %s:%d (/metrics, "
                     "/healthz)", host, _SERVER["port"])
        return _SERVER["port"]


def metrics_port():
    """The live scrape port, or None when not serving."""
    return _SERVER["port"]


def stop_metrics_server():
    """Shut the endpoint down. Idempotent — safe to call twice, or
    having never started."""
    with _LOCK:
        httpd, thread = _SERVER["httpd"], _SERVER["thread"]
        _SERVER.update(httpd=None, thread=None, port=None)
    if httpd is None:
        return
    httpd.shutdown()
    httpd.server_close()
    if thread is not None:
        thread.join(timeout=5)


def maybe_serve():
    """Start from ``MXTPU_METRICS_PORT`` when set (first-Context
    wiring); no-op otherwise."""
    port = getenv("MXTPU_METRICS_PORT", None)
    if port is None:
        return None
    try:
        return serve_metrics(int(port))
    except (OSError, ValueError) as e:
        # a typo'd port or an unbindable one must degrade to a warning,
        # never crash the first Context creation it is wired from
        _logger.warning("MXTPU_METRICS_PORT=%s: cannot serve (%s); "
                        "metrics endpoint disabled", port, e)
        return None
