"""Metrics registry: Counter / Gauge / Histogram with labels.

Reference analog: ``src/profiler/profiler.h`` (``ProfileCounter``,
``AggregateStats``) — generalized into a Prometheus-shaped model so the
same registry serves dispatch counters, compile-cache stats, kvstore
byte accounting and trainer gauges, and exports as text exposition.

Design constraints (the hot paths call into this per op dispatch):
- label sets are canonicalized to a sorted tuple of ``(key, value)``
  pairs; the common no-label case uses the empty tuple,
- value storage is a plain dict guarded by the GIL (single mutation per
  record — no lock),
- nothing here imports jax; the module is importable before backends.
"""

from __future__ import annotations

import threading

from ..base import MXNetError

#: default latency buckets (seconds) — spans µs-dispatch to multi-second
#: compile/allreduce times
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _label_key(labels: dict) -> tuple:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


def _fmt_value(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v) -> str:
    """Prometheus exposition label-value escaping: \\ " and newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(key: tuple, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Metric:
    """Base metric: named, labeled, registered."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._values = {}  # label key tuple -> float (or [..] for histogram)

    # -- read side -------------------------------------------------------
    def value(self, **labels) -> float:
        # float() here is what makes set_lazy work: a device scalar
        # stored by a gauge syncs at READ time, not on the hot path
        return float(self._values.get(_label_key(labels), 0.0))

    def total(self) -> float:
        """Sum across every label set (test/summary convenience)."""
        return float(sum(self._values.values()))

    def labelsets(self):
        return [dict(k) for k in self._values]

    def clear(self):
        self._values.clear()

    # -- exposition ------------------------------------------------------
    def expose(self) -> list:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        for key in sorted(self._values):
            lines.append(
                f"{self.name}{_fmt_labels(key)} {_fmt_value(self._values[key])}"
            )
        return lines


class Counter(Metric):
    """Monotonically increasing value (per label set)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise MXNetError(f"counter {self.name} cannot decrease")
        key = _label_key(labels)
        self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(Metric):
    """Value that can go up and down (per label set)."""

    kind = "gauge"

    def set(self, value: float, **labels):
        self._values[_label_key(labels)] = float(value)

    def set_lazy(self, value, **labels):
        """Store ``value`` without coercing to float: an asynchronous
        device scalar (e.g. the fused step's in-graph grad norm) stays a
        future until someone reads the gauge — recording never blocks."""
        self._values[_label_key(labels)] = value

    def inc(self, amount: float = 1.0, **labels):
        key = _label_key(labels)
        self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)


class Histogram(Metric):
    """Cumulative-bucket histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(self, name, help="", buckets=None):
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))

    def observe(self, value: float, **labels):
        key = _label_key(labels)
        rec = self._values.get(key)
        if rec is None:
            # [per-bucket counts..., +Inf count, sum, count]
            rec = self._values[key] = [0] * (len(self.buckets) + 1) + [0.0, 0]
        for i, b in enumerate(self.buckets):
            if value <= b:
                rec[i] += 1
                break
        else:
            rec[len(self.buckets)] += 1
        rec[-2] += value
        rec[-1] += 1

    def value(self, **labels) -> float:
        """Observation count for the label set."""
        rec = self._values.get(_label_key(labels))
        return rec[-1] if rec else 0

    def sum(self, **labels) -> float:
        rec = self._values.get(_label_key(labels))
        return rec[-2] if rec else 0.0

    def total(self) -> float:
        return sum(rec[-1] for rec in self._values.values())

    def quantile(self, q: float, **labels):
        """Estimated q-quantile (0..1) for the label set, interpolated
        linearly inside the containing bucket (Prometheus
        ``histogram_quantile`` semantics). ``None`` with no observations;
        observations beyond the last finite bucket clamp to it — the
        serving SLO report reads p50/p99 through this."""
        if not 0.0 <= q <= 1.0:
            raise MXNetError(f"quantile {q} outside [0, 1]")
        rec = self._values.get(_label_key(labels))
        if not rec or rec[-1] <= 0:
            return None
        rank = q * rec[-1]
        cum = 0
        for i, b in enumerate(self.buckets):
            prev_cum = cum
            cum += rec[i]
            if cum >= rank:
                lo = self.buckets[i - 1] if i else 0.0
                frac = (rank - prev_cum) / rec[i] if rec[i] else 1.0
                return lo + (b - lo) * frac
        return self.buckets[-1]

    def expose(self) -> list:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        for key in sorted(self._values):
            rec = self._values[key]
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += rec[i]
                le = 'le="%g"' % b
                lines.append(f"{self.name}_bucket{_fmt_labels(key, le)} {cum}")
            cum += rec[len(self.buckets)]
            inf = 'le="+Inf"'
            lines.append(f"{self.name}_bucket{_fmt_labels(key, inf)} {cum}")
            lines.append(f"{self.name}_sum{_fmt_labels(key)} "
                         f"{_fmt_value(rec[-2])}")
            lines.append(f"{self.name}_count{_fmt_labels(key)} {rec[-1]}")
        return lines


class SeriesGauge(Metric):
    """A gauge whose value is a short per-slot SERIES — the in-scan
    device metrics a K-step superstep publishes once per dispatch
    (per-iteration loss / grad-norm / overflow). ``set_series`` stores
    the whole device array WITHOUT slicing or syncing (one lazy array,
    zero added dispatches on the hot path); elements materialize at
    read/exposition time only, exposed per-slot as
    ``name{slot="i"}``."""

    kind = "gauge"

    def set_series(self, values, **labels):
        """Store a 1-D array/list of per-slot values (device arrays
        stay lazy — ``tolist()`` happens only when read)."""
        self._values[_label_key(labels)] = values

    def series(self, **labels) -> list:
        """The stored series as plain floats (syncs a device array)."""
        v = self._values.get(_label_key(labels))
        if v is None:
            return []
        if hasattr(v, "tolist"):
            v = v.tolist()
        return [float(x) for x in v]

    def value(self, **labels) -> float:
        """Last slot of the series (the most recent iteration)."""
        s = self.series(**labels)
        return s[-1] if s else 0.0

    def total(self) -> float:
        return float(sum(sum(self.series(**dict(k)))
                         for k in list(self._values)))

    def expose(self) -> list:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        for key in sorted(self._values):
            for i, x in enumerate(self.series(**dict(key))):
                slot = f'slot="{i}"'
                lines.append(f"{self.name}{_fmt_labels(key, slot)} "
                             f"{_fmt_value(x)}")
        return lines


class MetricsRegistry:
    """Named collection of metrics; one process-global default instance
    lives in ``mxnet_tpu.observability``."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram,
              "series_gauge": SeriesGauge}

    #: lock protocol, machine-checked by mxtpu-lint's thread-guard rule:
    #: registration mutates the name->metric map only under _lock (reads
    #: are deliberately lock-free — the GIL covers dict lookups, and the
    #: hot paths record without taking a lock).
    _GUARDED_BY = {"_metrics": "_lock"}

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise MXNetError(
                        f"metric {name} already registered as {m.kind}, "
                        f"requested {cls.kind}")
                return m
            m = cls(name, help, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name, help="") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help="") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def series_gauge(self, name, help="") -> SeriesGauge:
        return self._get_or_create(SeriesGauge, name, help)

    def get(self, name):
        return self._metrics.get(name)

    def metrics(self):
        return list(self._metrics.values())

    def reset(self):
        """Clear recorded values; metric definitions stay registered."""
        for m in self._metrics.values():
            m.clear()

    def dump_prometheus(self) -> str:
        """Prometheus text exposition format (one scrape body)."""
        lines = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].expose())
        return "\n".join(lines) + ("\n" if lines else "")
