"""Anomaly watchdog: rolling-window detectors over the live registry.

Between crashes (flight recorder) and dashboards (scrape endpoint)
nothing watches the training signal ITSELF: a NaN loss at step 40k
scrolls past, a 3x step-time regression hides in a mean. The watchdog
closes that gap with detectors that read series the hot paths already
emit — it adds NO instrumentation, NO dispatches and never mutates
training numerics (detection only):

- ``nan``          — non-finite loss (superstep per-iteration series)
                     or grad norm,
- ``loss_spike``   — loss above ``_SPIKE_FACTOR`` x the trailing-window
                     median,
- ``grad_explosion`` — grad norm above ``_GRAD_FACTOR`` x its
                     trailing-window median,
- ``step_time``    — recent mean step wall time above ``_STEP_FACTOR``
                     x the warmup baseline mean,
- ``queue_saturation`` — serving queue depth at >= 90% of the bound
                     (load shedding imminent), latched per model until
                     it drains below half,
- ``input_wait``   — the attribution plane's per-step input-wait delta
                     (``mxtpu_data_prefetch_wait_delta_seconds``) above
                     ``_INPUT_FRACTION`` of the step period: the
                     accelerator idles on the host (raise
                     MXTPU_DEVICE_PREFETCH / add loader workers).

Every firing increments ``mxtpu_anomaly_total{kind=...}``, records an
``anomaly`` trace instant, and notes itself into the crash flight
bundle via ``flight.register_pre_dump``; with
``MXTPU_WATCHDOG_CHECKPOINT=1`` and a ``CheckpointManager`` attached it
also requests a proactive async checkpoint (the recovery point moves
BEFORE the job dies of the divergence it just spotted).

Switch: ``MXTPU_WATCHDOG=1``. Cadence: the trainer hot paths call
``poll()`` (a monotonic-clock compare unless the
``MXTPU_WATCHDOG_INTERVAL_S`` window elapsed); ``start()`` runs the
same ``check_now()`` on a daemon thread for serving-only processes.
"""

from __future__ import annotations

import collections
import threading
import time

from ..base import getenv

#: THE switch (same pattern as observability.ENABLED / chaos.ENABLED):
#: hot paths read one module attribute and skip everything when False.
ENABLED = bool(getenv("MXTPU_WATCHDOG", False, dtype=bool))

#: detector constants — spike factors are deliberately loose (an alarm
#: that cries on noise gets muted); regression tests pin the contract,
#: not the exact thresholds
_SPIKE_FACTOR = 10.0     # loss vs trailing median
_GRAD_FACTOR = 25.0      # grad norm vs trailing median
_STEP_FACTOR = 3.0       # recent mean step time vs warmup baseline
_QUEUE_FRACTION = 0.9    # queue depth vs bound
_INPUT_FRACTION = 0.5    # per-step input wait vs step period
_INPUT_FLOOR_S = 0.001   # ignore sub-ms waits (tight loops are noise)
_WINDOW = 64             # trailing-window capacity
_MIN_WINDOW = 8          # observations before median detectors arm
_WARMUP_STEPS = 10       # step-time observations forming the baseline

_STATE = {
    "loss_window": collections.deque(maxlen=_WINDOW),
    "grad_window": collections.deque(maxlen=_WINDOW),
    "seen_step": 0,            # tracer step already consumed
    "warm_sum": 0.0,           # step-time warmup baseline accumulators
    "warm_count": 0,
    "prev_sum": 0.0,           # cumulative step-time at last check
    "prev_count": 0,
    "queue_latched": set(),    # models latched on queue saturation
    "input_seen_step": 0,      # attribution record already consumed
    "last_poll": 0.0,
    "ckpt_mgr": None,
    "anomalies": collections.deque(maxlen=32),
    "note_registered": False,
}
_LOCK = threading.RLock()

#: anomaly listeners (actuators): ``fn(kind, details)`` called on every
#: firing — how detection becomes ACTION (the fleet autoscaler turns
#: ``queue_saturation`` into a scale-up). Mutated under ``_LOCK``,
#: called OUTSIDE it (a slow actuator must not block detection).
_LISTENERS = []

#: machine-checked lock protocol (mxtpu-lint thread-guard): detector
#: state is shared between the trainer poll path and the daemon loop
_GUARDED_BY = {"_STATE": "_LOCK", "_LISTENERS": "_LOCK"}


def watchdog_interval_s() -> float:
    """``MXTPU_WATCHDOG_INTERVAL_S`` (default 1): minimum seconds
    between detector sweeps (poll or daemon loop)."""
    return float(getenv("MXTPU_WATCHDOG_INTERVAL_S", 1.0, dtype=float))


def _checkpoint_on_anomaly() -> bool:
    return bool(getenv("MXTPU_WATCHDOG_CHECKPOINT", False, dtype=bool))


def set_enabled(on: bool) -> bool:
    """Flip the watchdog at runtime; returns the previous state."""
    global ENABLED
    prev, ENABLED = ENABLED, bool(on)
    return prev


def reset():
    """Restore pristine detector state AND wiring (test isolation):
    a stale CheckpointManager from a previous trainer must not keep
    receiving proactive saves, and the flight-note flag re-arms so a
    fresh flight module can be registered against (re-registration of
    the same hook is idempotent in ``flight.register_pre_dump``)."""
    with _LOCK:
        _STATE["loss_window"].clear()
        _STATE["grad_window"].clear()
        _STATE["seen_step"] = 0
        _STATE["warm_sum"] = 0.0
        _STATE["warm_count"] = 0
        _STATE["prev_sum"] = 0.0
        _STATE["prev_count"] = 0
        _STATE["queue_latched"] = set()
        _STATE["input_seen_step"] = 0
        _STATE["last_poll"] = 0.0
        _STATE["anomalies"].clear()
        _STATE["ckpt_mgr"] = None
        _STATE["note_registered"] = False
        del _LISTENERS[:]


def register_listener(fn):
    """Register an anomaly actuator: ``fn(kind, details)`` runs on
    every detector firing (after the counter/trace/flight plumbing),
    outside the detector lock. Actuator exceptions are swallowed —
    a broken actuator must never break detection. Returns ``fn`` so it
    can be used as a decorator; idempotent per function object."""
    with _LOCK:
        if fn not in _LISTENERS:
            _LISTENERS.append(fn)
    return fn


def unregister_listener(fn):
    """Remove a previously registered actuator (idempotent)."""
    with _LOCK:
        try:
            _LISTENERS.remove(fn)
        except ValueError:
            pass


def attach_checkpoint_manager(mgr):
    """Give the watchdog a PR-8 ``CheckpointManager`` to request
    proactive saves through (``CheckpointManager.attach`` wires this
    automatically when the watchdog is armed)."""
    with _LOCK:
        _STATE["ckpt_mgr"] = mgr


def _flight_note():
    """flight.register_pre_dump hook: fold the recent anomaly record
    into the crash bundle's trace ring (a dying job's last bundle says
    WHAT the watchdog saw, not just that it died)."""
    from . import _TRACER

    with _LOCK:
        recent = list(_STATE["anomalies"])
    if recent:
        _TRACER.instant("anomaly", cat="watchdog", kind="summary",
                        recent=recent)


def _fire(kind: str, **details):
    """One anomaly: typed counter + trace instant + flight note +
    (opt-in) proactive checkpoint. Never touches training state."""
    from . import ANOMALY_TOTAL, _TRACER, flight

    ANOMALY_TOTAL.inc(1, kind=kind)
    _TRACER.instant("anomaly", cat="watchdog", kind=kind, **details)
    with _LOCK:
        _STATE["anomalies"].append(dict(details, kind=kind,
                                        step=_TRACER.step))
        if not _STATE["note_registered"]:
            _STATE["note_registered"] = True
            try:
                flight.register_pre_dump(_flight_note, signals_only=False)
            except Exception:
                _STATE["note_registered"] = False
        mgr = _STATE["ckpt_mgr"]
        listeners = list(_LISTENERS)
    if mgr is not None and _checkpoint_on_anomaly():
        try:
            mgr.save_async(reason="anomaly")
        except Exception:
            pass  # a failed proactive save must never break detection
    for fn in listeners:  # outside _LOCK: actuators may be slow
        try:
            fn(kind, dict(details))
        except Exception:
            pass  # a broken actuator must never break detection


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    if not n:
        return 0.0
    mid = n // 2
    return xs[mid] if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0


def _finite(x) -> bool:
    return x == x and x not in (float("inf"), float("-inf"))


def _check_training(fired):
    """Loss + grad detectors: consume the per-step series ONCE per new
    tracer step (re-checking a stale series must not re-fire — the
    'exactly one firing per seeded NaN' contract)."""
    from . import SUPERSTEP_ITER_LOSS, TRAINER_GRAD_NORM, _TRACER

    cur_step = _TRACER.step
    with _LOCK:
        if cur_step <= _STATE["seen_step"]:
            return
        _STATE["seen_step"] = cur_step

    # reading these series/gauges syncs lazy device values — that is
    # the point: the watchdog, never the training loop, pays the sync
    losses = SUPERSTEP_ITER_LOSS.series()  # mxtpu-lint: host-sync-ok
    bad = [x for x in losses if not _finite(x)]
    if bad:
        _fire("nan", source="loss", step=cur_step)
        fired.append("nan")
    with _LOCK:
        window = list(_STATE["loss_window"])
    finite = [x for x in losses if _finite(x)]
    if len(window) >= _MIN_WINDOW and finite:
        med = _median(window)
        peak = max(finite)
        if peak > _SPIKE_FACTOR * max(abs(med), 1e-12):
            _fire("loss_spike", peak=peak, median=med, step=cur_step)
            fired.append("loss_spike")
    with _LOCK:
        _STATE["loss_window"].extend(finite)

    if TRAINER_GRAD_NORM._values:
        gn = TRAINER_GRAD_NORM.value()  # mxtpu-lint: host-sync-ok
        if not _finite(gn):
            if "nan" not in fired:
                _fire("nan", source="grad_norm", step=cur_step)
                fired.append("nan")
        else:
            with _LOCK:
                gwin = list(_STATE["grad_window"])
                _STATE["grad_window"].append(gn)
            if len(gwin) >= _MIN_WINDOW:
                med = _median(gwin)
                if gn > _GRAD_FACTOR * max(abs(med), 1e-12):
                    _fire("grad_explosion", grad_norm=gn, median=med,
                          step=cur_step)
                    fired.append("grad_explosion")


def _check_step_time(fired):
    """Step-time regression vs the warmup baseline: the first
    ``_WARMUP_STEPS`` observations (eager + amortized superstep
    histograms combined) form the baseline mean; afterwards each NEW
    batch of observations fires when its mean exceeds
    ``_STEP_FACTOR`` x baseline."""
    from . import SUPERSTEP_STEP_SECONDS, TRAINER_STEP_SECONDS

    cum_sum = TRAINER_STEP_SECONDS.sum() + SUPERSTEP_STEP_SECONDS.sum()
    cum_count = TRAINER_STEP_SECONDS.value() + SUPERSTEP_STEP_SECONDS.value()
    with _LOCK:
        ds = cum_sum - _STATE["prev_sum"]
        dc = cum_count - _STATE["prev_count"]
        _STATE["prev_sum"] = cum_sum
        _STATE["prev_count"] = cum_count
        if dc <= 0:
            return
        if _STATE["warm_count"] < _WARMUP_STEPS:
            _STATE["warm_sum"] += ds
            _STATE["warm_count"] += dc
            return
        baseline = _STATE["warm_sum"] / max(_STATE["warm_count"], 1)
    recent = ds / dc
    if baseline > 0 and recent > _STEP_FACTOR * baseline:
        _fire("step_time", recent_mean_s=recent, baseline_s=baseline)
        fired.append("step_time")


def _check_serving(fired):
    """Serving queue saturation: depth at >= ``_QUEUE_FRACTION`` of the
    bound means shedding is imminent; latched per model until the
    queue drains below half."""
    from . import SERVE_QUEUE_DEPTH

    try:
        from ..serving.engine import serve_queue_cap

        cap = serve_queue_cap()
    except Exception:
        return
    if cap <= 0:
        return
    for labels in SERVE_QUEUE_DEPTH.labelsets():
        model = labels.get("model", "?")
        depth = SERVE_QUEUE_DEPTH.value(**labels)
        with _LOCK:
            latched = model in _STATE["queue_latched"]
            if depth >= _QUEUE_FRACTION * cap and not latched:
                _STATE["queue_latched"].add(model)
                do_fire = True
            else:
                do_fire = False
                if depth < 0.5 * cap and latched:
                    _STATE["queue_latched"].discard(model)
        if do_fire:
            _fire("queue_saturation", model=model, depth=depth, cap=cap)
            fired.append("queue_saturation")


def _check_input_wait(fired):
    """Input starvation: the attribution plane's LAST per-step record
    says the consumer spent >= ``_INPUT_FRACTION`` of the step period
    blocked on the prefetch queue (and at least ``_INPUT_FLOOR_S`` —
    micro-benchmark loops idle in sub-ms noise). Consumed once per new
    attribution record, so a stale record never re-fires."""
    from . import attribution

    rec = attribution.last_record()
    if rec is None:
        return
    step = int(rec.get("step") or 0)
    with _LOCK:
        if step <= _STATE["input_seen_step"]:
            return
        _STATE["input_seen_step"] = step
    per_step = rec["period_s"] / max(rec["k"], 1)
    wait = rec["input_wait"]
    if per_step > 0 and wait >= _INPUT_FLOOR_S and \
            wait >= _INPUT_FRACTION * per_step:
        _fire("input_wait", wait_s=wait, step_s=per_step,
              fraction=round(wait / per_step, 4),
              max_single_wait_s=rec.get("input_wait_max_s", 0.0),
              step=step)
        fired.append("input_wait")


def check_now() -> list:
    """Run every detector once; returns the kinds fired this sweep.
    Deterministic — the test seam (``poll()``/the daemon loop add only
    cadence)."""
    fired = []
    _check_training(fired)
    _check_step_time(fired)
    _check_serving(fired)
    _check_input_wait(fired)
    return fired


def poll():
    """Trainer-cadence hook: a monotonic-clock compare per call; the
    detectors run only when ``MXTPU_WATCHDOG_INTERVAL_S`` elapsed.
    Reading lazy gauges here syncs values the step ALREADY computed —
    zero added dispatches (pinned by the regression test)."""
    if not ENABLED:
        return []
    now = time.monotonic()
    with _LOCK:
        if now - _STATE["last_poll"] < watchdog_interval_s():
            return []
        _STATE["last_poll"] = now
    return check_now()


# ---------------------------------------------------------------------------
# daemon loop (serving-only processes have no trainer to poll from)
# ---------------------------------------------------------------------------

_WATCH = {"thread": None, "stop": None}
_WATCH_LOCK = threading.Lock()
_GUARDED_BY["_WATCH"] = "_WATCH_LOCK"


def _watchdog_loop(stop, interval):  # mxtpu-lint: hot-path
    while not stop.wait(interval):
        try:
            check_now()
        except Exception:
            pass  # the watchdog must never take the process down


def start(interval=None) -> bool:
    """Start the detector daemon thread (idempotent)."""
    if interval is None:
        interval = watchdog_interval_s()
    with _WATCH_LOCK:
        if _WATCH["thread"] is not None and _WATCH["thread"].is_alive():
            return False
        stop_ev = threading.Event()
        t = threading.Thread(
            target=_watchdog_loop, args=(stop_ev, float(interval)),
            name="mxtpu-watchdog", daemon=True)
        _WATCH.update(thread=t, stop=stop_ev)
        t.start()
    return True


def stop():
    """Stop the daemon thread (idempotent); join outside the lock."""
    with _WATCH_LOCK:
        t, ev = _WATCH["thread"], _WATCH["stop"]
        _WATCH.update(thread=None, stop=None)
    if ev is not None:
        ev.set()
    if t is not None:
        t.join(timeout=5)


def maybe_start():
    """Arm the daemon loop from ``MXTPU_WATCHDOG=1`` (first-Context
    wiring); trainer processes additionally get ``poll()`` cadence."""
    if ENABLED:
        start()
