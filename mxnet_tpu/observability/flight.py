"""Crash flight recorder: post-mortem bundles for hangs, preemptions
and crashes (``MXTPU_DUMP_ON_CRASH=<dir>``).

TPU training dies in ways host logs don't explain: a preemption SIGTERM
mid-superstep, an OOM inside a donated executable, a hung collective.
The PR-1 ring-buffer tracer already holds the last ~65k events in
memory; this module gets them OUT on the way down. With
``MXTPU_DUMP_ON_CRASH`` set (or ``flight.install(dir)`` called), an
unhandled exception, SIGTERM or SIGABRT writes ONE JSON bundle:

- the last-N trace events (``MXTPU_FLIGHT_EVENTS``, default 512),
- a live metric snapshot (every registry value, floats forced — lazy
  device gauges sync here, at dump time),
- the per-site executable cost table (``introspect.costs()``),
- the dispatch sites in flight at the moment of death (which compiled
  executable the process was inside — the "where was it stuck" answer
  for hangs),
- step counters, backend/devices, and the MXTPU_* environment.

The handlers chain: a previously-installed excepthook/signal handler
still runs after the dump. Everything is best-effort — a recorder must
never turn a crash into a different crash.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
import threading
import time

from ..base import getenv

_logger = logging.getLogger("mxnet_tpu.flight")

#: True once install() ran — the ONE boolean dispatch sites check
#: before paying the in-flight bookkeeping dict ops.
INSTALLED = False

_STATE = {
    "dir": None,
    "prev_excepthook": None,
    "prev_signal": {},  # signum -> previous handler
    "dumped": False,    # one bundle per process death, not one per hook
}

_IN_FLIGHT: dict = {}  # site -> depth (currently executing dispatches)
_IN_FLIGHT_LOCK = threading.Lock()

#: pre-dump hooks: callables run BEFORE the bundle is written when a
#: hooked signal fires (and, for hooks registered with
#: ``signals_only=False``, before an exception bundle too). This is the
#: deterministic ordering seam for the resilience layer: the final
#: checkpoint registers here, so "checkpoint first, flight bundle
#: second" holds no matter which handler was installed first (the other
#: install order reaches the same sequence through handler chaining +
#: the checkpoint's own once-per-death flag). Hooks are best-effort —
#: one raising must not cost the bundle or the re-raise.
_PRE_DUMP_HOOKS: list = []  # (fn, signals_only)


def register_pre_dump(fn, signals_only=True):
    """Run ``fn()`` before the crash bundle is written (idempotent per
    fn). ``signals_only``: skip it for plain unhandled exceptions."""
    for f, _ in _PRE_DUMP_HOOKS:
        if f is fn:
            return
    _PRE_DUMP_HOOKS.append((fn, bool(signals_only)))


def unregister_pre_dump(fn):
    _PRE_DUMP_HOOKS[:] = [(f, s) for f, s in _PRE_DUMP_HOOKS if f is not fn]


def _run_pre_dump(from_signal):
    for fn, signals_only in list(_PRE_DUMP_HOOKS):
        if signals_only and not from_signal:
            continue
        try:
            fn()
        except Exception as e:  # a hook must never mask the crash
            try:
                _logger.error("flight pre-dump hook failed: %s: %s",
                              type(e).__name__, e)
            except Exception:
                pass


def installed() -> bool:
    return INSTALLED


def dump_dir():
    return _STATE["dir"]


# ---------------------------------------------------------------------------
# in-flight dispatch tracking
# ---------------------------------------------------------------------------

class _Dispatch:
    """Context manager marking ``site`` as in flight. Near-zero cost
    and only ever constructed when the recorder is installed."""

    __slots__ = ("site",)

    def __init__(self, site):
        self.site = site

    def __enter__(self):
        with _IN_FLIGHT_LOCK:
            _IN_FLIGHT[self.site] = _IN_FLIGHT.get(self.site, 0) + 1
        return self

    def __exit__(self, *exc):
        with _IN_FLIGHT_LOCK:
            n = _IN_FLIGHT.get(self.site, 0) - 1
            if n <= 0:
                _IN_FLIGHT.pop(self.site, None)
            else:
                _IN_FLIGHT[self.site] = n
        return False


def dispatch(site) -> _Dispatch:
    """``with flight.dispatch("trainer_fused"): fn(...)`` — call sites
    guard on ``flight.INSTALLED`` first so the off path stays free."""
    return _Dispatch(site)


def in_flight() -> dict:
    with _IN_FLIGHT_LOCK:
        return dict(_IN_FLIGHT)


# ---------------------------------------------------------------------------
# bundle assembly
# ---------------------------------------------------------------------------

def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        if hasattr(v, "tolist"):
            try:  # device arrays (series gauges) sync here, at dump time
                return v.tolist()
            except Exception:
                pass
        try:
            return float(v)  # lazy device scalars sync here
        except (TypeError, ValueError):
            return str(v)


def _metric_snapshot():
    from . import registry

    snap = {}
    for m in registry().metrics():
        try:
            vals = {}
            for key, v in list(m._values.items()):
                label = ",".join(f"{k}={val}" for k, val in key) or ""
                if isinstance(v, list):
                    vals[label] = [_jsonable(x) for x in v]
                else:
                    vals[label] = _jsonable(v)
            if vals:
                snap[m.name] = {"kind": m.kind, "values": vals}
        except Exception:  # one bad metric must not sink the bundle
            snap[m.name] = {"kind": m.kind, "values": "unreadable"}
    return snap


def build_bundle(reason: str) -> dict:
    """The flight-recorder bundle as a plain dict (also the API tests
    use directly — the hooks just write this to disk)."""
    from . import summary, tracer
    from . import introspect as _introspect

    n = int(getenv("MXTPU_FLIGHT_EVENTS", 512, dtype=int))
    trc = tracer()
    events = trc.events()[-max(n, 1):]
    bundle = {
        "format": "mxtpu-flight-recorder-v1",
        "reason": reason,
        "time_unix": time.time(),
        "pid": os.getpid(),
        "step": trc.step,
        "in_flight": in_flight(),
        "executables": _introspect.costs(),
        "trace_events": [
            {k: _jsonable(v) if k != "args" else
             {ak: _jsonable(av) for ak, av in (v or {}).items()}
             for k, v in ev.items()} for ev in events],
        "metrics": _metric_snapshot(),
        "env": {k: v for k, v in os.environ.items()
                if k.startswith(("MXTPU_", "JAX_", "XLA_"))},
    }
    try:
        bundle["summary"] = summary()
    except Exception:
        pass
    try:
        # the last-N per-step phase records: a dying job's bundle says
        # WHERE its final steps spent their time, not just how long
        from . import attribution

        bundle["phase_records"] = attribution.records()[-32:]
    except Exception:
        bundle["phase_records"] = []
    try:
        import jax

        bundle["backend"] = jax.default_backend()
        bundle["devices"] = [str(d) for d in jax.devices()]
    except Exception:
        bundle["backend"] = None
    return bundle


def dump(reason="manual", path=None) -> str | None:
    """Write one bundle; returns the path (None if nowhere to write or
    the write itself failed — logged, never raised)."""
    d = _STATE["dir"]
    if path is None:
        if not d:
            return None
        path = os.path.join(
            d, f"flight_{os.getpid()}_{int(time.time())}.json")
    try:
        bundle = build_bundle(reason)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
            f.write("\n")
        _logger.error("flight recorder: wrote %s (%s)", path, reason)
        return path
    except Exception as e:  # never turn a crash into a different crash
        try:
            _logger.error("flight recorder dump failed: %s: %s",
                          type(e).__name__, e)
        except Exception:
            pass
        return None


# ---------------------------------------------------------------------------
# hooks
# ---------------------------------------------------------------------------

def _excepthook(exc_type, exc, tb):
    if not _STATE["dumped"]:
        _STATE["dumped"] = True
        _run_pre_dump(from_signal=False)
        dump(reason=f"exception: {exc_type.__name__}: {exc}"[:300])
    prev = _STATE["prev_excepthook"] or sys.__excepthook__
    prev(exc_type, exc, tb)


def _signal_handler(signum, frame):
    if not _STATE["dumped"]:
        _STATE["dumped"] = True
        # resilience ordering contract: the final checkpoint (a pre-dump
        # hook) commits BEFORE the flight bundle is written
        _run_pre_dump(from_signal=True)
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        dump(reason=f"signal: {name}")
    prev = _STATE["prev_signal"].get(signum)
    if callable(prev):
        prev(signum, frame)
        return
    # default disposition: die by the same signal so the parent sees
    # the true exit status (preemption managers key on it)
    signal.signal(signum, signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def install(dirpath) -> bool:
    """Install the excepthook + SIGTERM/SIGABRT handlers writing
    bundles into ``dirpath``. Idempotent (re-install just re-points the
    directory). Signal handlers only land on the main thread; elsewhere
    the excepthook alone is installed (logged)."""
    global INSTALLED
    _STATE["dir"] = str(dirpath)
    _STATE["dumped"] = False
    if INSTALLED:
        return True
    _STATE["prev_excepthook"] = sys.excepthook
    sys.excepthook = _excepthook
    if threading.current_thread() is threading.main_thread():
        for signum in (signal.SIGTERM, signal.SIGABRT):
            try:
                if signal.getsignal(signum) is signal.SIG_IGN:
                    # an explicitly-ignored signal stays ignored: the
                    # recorder must not turn a survive-broadcast-TERM
                    # process into one that dies on it
                    continue
                prev = signal.signal(signum, _signal_handler)
                if prev not in (signal.SIG_DFL, _signal_handler):
                    _STATE["prev_signal"][signum] = prev
            except (ValueError, OSError) as e:  # pragma: no cover
                _logger.warning("flight recorder: cannot hook %s: %s",
                                signum, e)
    else:  # pragma: no cover - install is normally at import time
        _logger.warning("flight recorder installed off the main thread: "
                        "signal hooks skipped, excepthook only")
    INSTALLED = True
    return True


def uninstall():
    """Remove the hooks (tests). Safe when not installed."""
    global INSTALLED
    if not INSTALLED:
        return
    if sys.excepthook is _excepthook:
        sys.excepthook = _STATE["prev_excepthook"] or sys.__excepthook__
    if threading.current_thread() is threading.main_thread():
        for signum in (signal.SIGTERM, signal.SIGABRT):
            try:
                if signal.getsignal(signum) is _signal_handler:
                    signal.signal(
                        signum,
                        _STATE["prev_signal"].get(signum, signal.SIG_DFL))
            except (ValueError, OSError):  # pragma: no cover
                pass
    _STATE["prev_excepthook"] = None
    _STATE["prev_signal"].clear()
    _STATE["dir"] = None
    INSTALLED = False


def maybe_install():
    """Install from ``MXTPU_DUMP_ON_CRASH`` when set (called once at
    observability import — opt-in, so plain imports stay hook-free)."""
    d = getenv("MXTPU_DUMP_ON_CRASH", None)
    if d:
        install(str(d))
    return INSTALLED
