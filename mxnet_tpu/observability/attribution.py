"""Step-time attribution plane: per-phase accounting for the train loop.

The stack emits every primitive performance signal — prefetcher
consumer-wait (`mxtpu_data_prefetch_wait_seconds_total`), `data.h2d`
staging spans, trainer/superstep dispatch spans, the overlap probe's
exposed-comm gauge, checkpoint tick time — but nothing JOINS them, so
"why is this step 80.9 ms" is a human reading five metric families side
by side. This module closes that gap (the MXNet ``src/profiler/``
operator-attribution capability, rebuilt on signals the hot paths
already record): at each step boundary it splits the step PERIOD (end
of the previous step to the end of this one) into

    {input_wait, h2d, compute, comm_exposed, ckpt_overhead, host_gap}

- ``input_wait``    — consumer wall time blocked on the prefetch queue
                      (delta of the PR-4 counter),
- ``h2d``           — host->device staging latency (delta of the
                      ``data.h2d`` histogram sum; staged concurrently by
                      the producer thread, so it is capped at the
                      period budget remaining),
- ``ckpt_overhead`` — in-loop checkpoint tick cost (snapshot dispatch +
                      enqueue; the background WRITE is never loop time),
- ``comm_exposed``  — gradient-communication time not hidden behind
                      compute: host-measured comm dispatches (kvstore
                      allreduce, the staged SPMD comm leg) when they
                      exist, else the overlap probe's per-step
                      exposed-comm figure for the running mode,
- ``compute``       — the dispatch span minus exposed comm,
- ``host_gap``      — the non-negative residual (python overhead, loss
                      construction, logging — everything unattributed).

Phases are computed with a BUDGET decomposition (each phase is capped
by the period time still unaccounted for, in the order above), which
makes two invariants hold by construction: every phase is >= 0 and the
phases sum exactly to the step period (so sum(phases) <= any outer
wall-time measurement of the same steps).

Everything here is host arithmetic over already-recorded host floats:
ZERO added device dispatches and zero device syncs per step (pinned by
the regression test). Published three ways:

- ``mxtpu_step_phase_seconds{phase=}`` histograms (per-step amortized —
  a K-step superstep divides its dispatch across its K iterations),
- ``mxtpu_step_phase_last_seconds{phase=}`` — a LAZY SeriesGauge over
  the last-N per-step records (the stored value is a live view; the
  list materializes only at read/exposition time),
- a ``step.phases`` trace span per dispatch (the timeline/doctor food),

and the whole family rides PR-15 federation automatically (federation
serializes the full registry), so the cluster view gets per-rank phase
skew for free.

Switch: ``MXTPU_ATTRIBUTION`` (default ON — the plane arms whenever
telemetry itself is on; every hook site checks ``observability.ENABLED``
first, so with telemetry off the cost is one module-bool read).
"""

from __future__ import annotations

import collections
import threading

from ..base import getenv

#: THE switch (same pattern as watchdog.ENABLED / chaos.ENABLED): hot
#: sites read one module attribute — effective only when the telemetry
#: master switch (observability.ENABLED) is also on.
ENABLED = bool(getenv("MXTPU_ATTRIBUTION", True, dtype=bool))

#: phase keys, in BUDGET order (each capped at the period time still
#: unaccounted for; host_gap is the residual and comes last)
PHASES = ("input_wait", "h2d", "ckpt_overhead", "comm_exposed",
          "compute", "host_gap")

#: per-step records kept for the series gauge / flight bundle / bench
_RECORDS = 128

_STATE = {
    "last_t1": None,        # perf_counter of the previous step boundary
    "prev_wait": 0.0,       # cumulative counters at the last boundary
    "prev_h2d": 0.0,
    "prev_ckpt": 0.0,
    "prev_comm": 0.0,
    "comm_extra": 0.0,      # host-timed comm dispatches (note_comm)
    "comm_hint": {},        # overlap-probe exposed s/step, by comm mode
    "wait_max": 0.0,        # longest single consumer wait since the
                            # last boundary (prefetcher spike evidence)
    "records": collections.deque(maxlen=_RECORDS),
}
_LOCK = threading.RLock()

#: machine-checked lock protocol (mxtpu-lint thread-guard): the state is
#: shared between the trainer thread (record_step), the prefetcher
#: consumer (note_input_wait) and probe/report readers
_GUARDED_BY = {"_STATE": "_LOCK"}


def set_enabled(on: bool) -> bool:
    """Flip the attribution plane at runtime; returns the prior state."""
    global ENABLED
    prev, ENABLED = ENABLED, bool(on)
    return prev


def reset():
    """Pristine plane state (test isolation / bench scenario boundary):
    cumulative-counter anchors re-seed at the NEXT record_step, so a
    reset mid-run never attributes another scenario's backlog."""
    from . import (CHECKPOINT_TICK_SECONDS, DATA_H2D_SECONDS,
                   DATA_PREFETCH_WAIT_SECONDS, KV_ALLREDUCE_SECONDS)

    with _LOCK:
        _STATE["last_t1"] = None
        _STATE["prev_wait"] = DATA_PREFETCH_WAIT_SECONDS.total()
        _STATE["prev_h2d"] = DATA_H2D_SECONDS.sum()
        _STATE["prev_ckpt"] = CHECKPOINT_TICK_SECONDS.total()
        _STATE["prev_comm"] = KV_ALLREDUCE_SECONDS.sum() \
            + _STATE["comm_extra"]
        _STATE["comm_hint"] = {}
        _STATE["wait_max"] = 0.0
        _STATE["records"].clear()


# ---------------------------------------------------------------------------
# feeder hooks (cheap accumulators written by OTHER hot paths)
# ---------------------------------------------------------------------------

def note_input_wait(dt: float):
    """Prefetcher consumer hook: track the longest SINGLE queue wait
    since the last step boundary (the running total already lives in
    ``mxtpu_data_prefetch_wait_seconds_total``; the max is what makes a
    one-off stall distinguishable from uniform slowness)."""
    if dt > _STATE["wait_max"]:
        with _LOCK:
            if dt > _STATE["wait_max"]:
                _STATE["wait_max"] = dt


def note_comm(dt: float):
    """A host-timed communication dispatch (e.g. the staged SPMD comm
    leg) — accumulated and attributed to ``comm_exposed`` at the next
    step boundary."""
    with _LOCK:
        _STATE["comm_extra"] += dt


def set_comm_hint(exposed_by_mode):
    """Overlap-probe wiring (``parallel.overlap.measure_overlap``): the
    per-step exposed-comm seconds by comm mode. Used for in-graph comm
    schedules (``ready``/``barrier``) where no host-side timestamp can
    see the wire time — the probe's figure is the best available
    estimate until the next probe."""
    with _LOCK:
        _STATE["comm_hint"] = dict(exposed_by_mode or {})


# ---------------------------------------------------------------------------
# the decomposition (called at step boundaries by the trainer hot paths)
# ---------------------------------------------------------------------------

class _SeriesView:
    """Lazy view for ``mxtpu_step_phase_last_seconds``: the SeriesGauge
    stores this object once; the per-phase list materializes only when
    the gauge is READ (exposition / flight dump), never per step."""

    __slots__ = ("phase",)

    def __init__(self, phase):
        self.phase = phase

    def tolist(self):
        with _LOCK:
            recs = list(_STATE["records"])
        return [r[self.phase] for r in recs]


_VIEWS = {ph: _SeriesView(ph) for ph in PHASES}


def record_step(t0: float, t1: float, k: int = 1, site: str = "trainer",
                comm_mode: str | None = None):
    """Attribute one step boundary. ``t0``/``t1`` bound the DISPATCH
    span the caller already measured; the attributed period runs from
    the previous boundary to ``t1`` (first record after reset: the
    dispatch span alone). ``k`` — training iterations the dispatch
    covered (a superstep passes its K; phases are published per-step
    amortized). ``comm_mode`` selects the overlap-probe hint when no
    host-measured comm exists. Pure host arithmetic — zero dispatches.
    """
    from . import (CHECKPOINT_TICK_SECONDS, DATA_H2D_SECONDS,
                   DATA_PREFETCH_WAIT_DELTA, DATA_PREFETCH_WAIT_SECONDS,
                   KV_ALLREDUCE_SECONDS, STEP_PHASE_LAST,
                   STEP_PHASE_SECONDS, _TRACER)

    wait_cum = DATA_PREFETCH_WAIT_SECONDS.total()
    h2d_cum = DATA_H2D_SECONDS.sum()
    ckpt_cum = CHECKPOINT_TICK_SECONDS.total()
    with _LOCK:
        comm_cum = KV_ALLREDUCE_SECONDS.sum() + _STATE["comm_extra"]
        last = _STATE["last_t1"]
        d_wait = max(wait_cum - _STATE["prev_wait"], 0.0)
        d_h2d = max(h2d_cum - _STATE["prev_h2d"], 0.0)
        d_ckpt = max(ckpt_cum - _STATE["prev_ckpt"], 0.0)
        d_comm = max(comm_cum - _STATE["prev_comm"], 0.0)
        wait_max = _STATE["wait_max"]
        hint = _STATE["comm_hint"].get(comm_mode) if comm_mode else None
        _STATE["last_t1"] = t1
        _STATE["prev_wait"] = wait_cum
        _STATE["prev_h2d"] = h2d_cum
        _STATE["prev_ckpt"] = ckpt_cum
        _STATE["prev_comm"] = comm_cum
        _STATE["wait_max"] = 0.0

    kk = max(int(k), 1)  # python int, never a device scalar  # mxtpu-lint: host-sync-ok
    dispatch = max(t1 - t0, 0.0)
    period = max(t1 - last, dispatch) if last is not None else dispatch
    if hint is not None and d_comm <= 0.0:
        # in-graph comm schedule: no host timestamp sees the wire time;
        # use the probe's per-step exposed figure (never ADDED to a
        # host-measured value — that would double-count)
        d_comm = max(float(hint), 0.0) * kk  # host float from the probe  # mxtpu-lint: host-sync-ok

    # budget decomposition: each phase caps at the unaccounted period
    # time -> every phase >= 0 and sum(phases) == period, by construction
    budget = period
    input_wait = min(d_wait, budget)
    budget -= input_wait
    h2d = min(d_h2d, budget)
    budget -= h2d
    ckpt = min(d_ckpt, budget)
    budget -= ckpt
    comm = min(d_comm, dispatch, budget)
    budget -= comm
    compute = min(max(dispatch - comm, 0.0), budget)
    budget -= compute
    host_gap = max(budget, 0.0)

    rec = {"site": site, "step": _TRACER.step, "k": kk,
           "period_s": period, "dispatch_s": dispatch,
           "input_wait": input_wait / kk, "h2d": h2d / kk,
           "ckpt_overhead": ckpt / kk, "comm_exposed": comm / kk,
           "compute": compute / kk, "host_gap": host_gap / kk,
           "input_wait_max_s": wait_max}
    for ph in PHASES:
        STEP_PHASE_SECONDS.observe(rec[ph], phase=ph)
        STEP_PHASE_LAST.set_series(_VIEWS[ph], phase=ph)
    # the promoted per-step delta series (satellite of the PR-4 counter):
    # a spike is VISIBLE here where the running total hides it — the
    # watchdog's input_wait detector reads exactly this gauge
    DATA_PREFETCH_WAIT_DELTA.set(rec["input_wait"])
    with _LOCK:
        _STATE["records"].append(rec)
    _TRACER.record(
        "step.phases", cat="attribution", ts=t1 - period, dur=period,
        args={"site": site, "k": kk,
              "period_ms": round(period * 1e3, 4),
              "dispatch_ms": round(dispatch * 1e3, 4),
              **{f"{ph}_ms": round(rec[ph] * 1e3, 4) for ph in PHASES}})
    return rec


# ---------------------------------------------------------------------------
# read side (reports / flight bundle / bench stamps — off the hot path)
# ---------------------------------------------------------------------------

def records() -> list:
    """The last-N per-step phase records (plain dicts of floats)."""
    with _LOCK:
        return [dict(r) for r in _STATE["records"]]


def last_record():
    """The most recent phase record, or None before the first step."""
    with _LOCK:
        return dict(_STATE["records"][-1]) if _STATE["records"] else None


def mean_phases(site=None, last_n=None) -> dict:
    """Mean per-step phase seconds over the recent records (optionally
    filtered by ``site`` and truncated to the last ``last_n``); adds
    ``step_wall`` (mean per-step period) and ``count``. Empty dict when
    nothing was recorded — callers degrade gracefully."""
    recs = records()
    if site is not None:
        recs = [r for r in recs if r["site"] == site]
    if last_n:
        recs = recs[-int(last_n):]
    if not recs:
        return {}
    n = len(recs)
    out = {ph: sum(r[ph] for r in recs) / n for ph in PHASES}
    out["step_wall"] = sum(r["period_s"] / r["k"] for r in recs) / n
    out["count"] = n
    return out
