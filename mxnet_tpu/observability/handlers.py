"""Training-loop integration: estimator event handler + callback hook.

``TelemetryHandler`` plugs into ``gluon.contrib.estimator.Estimator``'s
event-handler protocol (it must subclass the estimator mixins — dispatch
is isinstance-based) and logs the :func:`observability.summary` body per
epoch, tagging epoch spans into the tracer. The classic-``callback``
counterpart for Module-style loops lives in ``mxnet_tpu.callback``
(``TelemetryLogger``).
"""

from __future__ import annotations

import logging
import time

from ..gluon.contrib.estimator.event_handler import (
    BatchEnd,
    EpochBegin,
    EpochEnd,
    TrainBegin,
    TrainEnd,
)
from . import (
    OP_DISPATCH_TOTAL,
    CACHEDOP_COMPILE_TOTAL,
    KV_PUSH_BYTES,
    KV_PULL_BYTES,
)
from . import summary, tracer
from . import enabled as _enabled


class TelemetryHandler(TrainBegin, EpochBegin, BatchEnd, EpochEnd, TrainEnd):
    """Logs a per-epoch telemetry summary and emits epoch trace spans.

    Parameters
    ----------
    logger : logging.Logger, optional
        Destination (default: the ``"telemetry"`` logger, INFO level).
    auto_enable : bool
        Turn telemetry on at train_begin when it is off (default True) —
        attaching the handler is the opt-in.
    """

    def __init__(self, logger=None, auto_enable=True):
        self.logger = logger or logging.getLogger("telemetry")
        self.auto_enable = auto_enable
        self.current_epoch = 0
        self._epoch_t0 = None
        self._epoch_base = {}
        self._batches = 0

    def train_begin(self, estimator, *args, **kwargs):
        if self.auto_enable and not _enabled():
            from . import set_enabled

            set_enabled(True)
        self.current_epoch = 0

    def _snapshot(self):
        return {
            "ops": OP_DISPATCH_TOTAL.total(),
            "compiles": CACHEDOP_COMPILE_TOTAL.total(),
            "push_b": KV_PUSH_BYTES.total(),
            "pull_b": KV_PULL_BYTES.total(),
        }

    def epoch_begin(self, estimator, *args, **kwargs):
        self._epoch_t0 = time.perf_counter()
        self._epoch_base = self._snapshot()
        self._batches = 0

    def batch_end(self, estimator, *args, **kwargs):
        self._batches += 1

    def epoch_end(self, estimator, *args, **kwargs):
        dt = time.perf_counter() - (self._epoch_t0 or time.perf_counter())
        cur, base = self._snapshot(), self._epoch_base
        tracer().record(f"epoch[{self.current_epoch}]", cat="epoch",
                        ts=time.perf_counter() - dt, dur=dt,
                        args={"batches": self._batches})
        self.logger.info(
            "[Epoch %d] %d batches in %.2fs: +%d op dispatches, "
            "+%d compiles, +%d B pushed, +%d B pulled",
            self.current_epoch, self._batches, dt,
            int(cur["ops"] - base.get("ops", 0)),
            int(cur["compiles"] - base.get("compiles", 0)),
            int(cur["push_b"] - base.get("push_b", 0)),
            int(cur["pull_b"] - base.get("pull_b", 0)))
        self.logger.info(summary())
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info(summary())
