"""Global name manager for automatic block/symbol prefixes.

Reference: ``python/mxnet/name.py`` (``NameManager``).
"""

from __future__ import annotations

import threading


class NameManager(threading.local):
    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        count = self._counter.get(hint, 0)
        self._counter[hint] = count + 1
        return f"{hint}{count}"


_MANAGER = NameManager()


def next_prefix(hint: str) -> str:
    return _MANAGER.get(None, hint) + "_"


def next_name(hint: str) -> str:
    return _MANAGER.get(None, hint)


def reset():
    _MANAGER._counter.clear()
