"""Legacy data iterators.

Reference: ``python/mxnet/io/io.py`` (symbols ``DataIter``, ``NDArrayIter``,
``PrefetchingIter``) and the C++ iterators in ``src/io/`` (``ImageRecordIter``
— here a Python front over the RecordIO reader + threaded prefetch, with the
C++ decode path in ``cxx/`` wired underneath when built).
"""

from __future__ import annotations

import threading
from collections import namedtuple

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array as _array

DataDesc = namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])
DataDesc.__new__.__defaults__ = ("float32", "NCHW")


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data] if self.data else None
        label_shapes = [l.shape for l in self.label] if self.label else None
        return f"{self.__class__.__name__}: data shapes: {data_shapes} label shapes: {label_shapes}"


class DataIter:
    """Iterator protocol: next/reset/provide_data/provide_label."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


class NDArrayIter(DataIter):
    """Iterate over NDArray/numpy data (reference: ``NDArrayIter``)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = _np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        self.num_source = len(self.data)
        self.cursor = -batch_size
        self._cache_data = None
        self._cache_label = None
        self.reset()

    @property
    def provide_data(self):
        return [
            DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]), str(v.dtype))
            for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]), str(v.dtype))
            for k, v in self.label
        ]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = self.cursor - self.num_data - self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        if self.last_batch_handle == "discard" and \
                self.cursor + self.batch_size > self.num_data:
            raise StopIteration
        return DataBatch(data=self.getdata(), label=self.getlabel(),
                         pad=self.getpad(), index=None)

    def _getdata(self, data_source):
        end = min(self.cursor + self.batch_size, self.num_data)
        sel = self.idx[self.cursor:end]
        pad = self.batch_size - len(sel)
        if pad and self.last_batch_handle == "pad":
            sel = _np.concatenate([sel, self.idx[:pad]])
        out = []
        for _, arr in data_source:
            np_arr = arr[sel] if isinstance(arr, _np.ndarray) else arr.asnumpy()[sel]
            out.append(_array(np_arr, dtype=str(np_arr.dtype)
                              if np_arr.dtype != _np.float64 else "float32"))
        return out

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


def _init_data(data, allow_empty, default_name):
    if data is None:
        if not allow_empty:
            raise ValueError("data must not be None")
        return []
    if isinstance(data, (NDArray, _np.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, _np.asarray(v)))
    return out


class ResizeIter(DataIter):
    """Resize (truncate/loop) another iterator to a fixed #batches."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch (reference: ``PrefetchingIter``)."""

    #: machine-checked lock protocol (mxtpu-lint thread-guard): the
    #: started flag flips only under the close lock, so exactly ONE
    #: closer signals and joins the prefetch threads (close() racing
    #: __del__ both joined — and a late consumer could then wait on
    #: data_ready events nobody would ever set again)
    _GUARDED_BY = {"started": "_close_lock"}

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        self._close_lock = threading.Lock()
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = iters[0].batch_size
        self.n_iter = len(iters)
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None] * self.n_iter
        self.next_batch = [None] * self.n_iter
        self.error = [None] * self.n_iter

        def prefetch(i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                except BaseException as e:  # noqa: BLE001 - must never
                    # leave the consumer blocked on data_ready forever;
                    # park the exception for next() to re-raise
                    self.error[i] = e
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()
            self.data_ready[i].set()  # unblock a consumer racing close()

        self.prefetch_threads = [
            threading.Thread(target=prefetch, args=(i,), daemon=True)
            for i in range(self.n_iter)
        ]
        for t in self.prefetch_threads:
            t.start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum((i.provide_data for i in self.iters), [])
        return sum(
            ([DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
              if isinstance(d, DataDesc) else (r.get(d[0], d[0]), d[1])
              for d in i.provide_data]
             for r, i in zip(self.rename_data, self.iters)), [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum((i.provide_label for i in self.iters), [])
        return sum(
            ([DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
              if isinstance(d, DataDesc) else (r.get(d[0], d[0]), d[1])
              for d in i.provide_label]
             for r, i in zip(self.rename_label, self.iters)), [])

    def close(self):
        """Idempotent shutdown: signal the prefetch threads and JOIN
        them (the seed leaked daemon threads that were never joined).
        Exactly one closer wins the flag flip under the lock; the joins
        run outside it."""
        with self._close_lock:
            if not self.started:
                return
            self.started = False
        for e in self.data_taken:
            e.set()
        for t in self.prefetch_threads:
            if t is not threading.current_thread():
                t.join(timeout=5.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _raise_pending(self):
        for i, err in enumerate(self.error):
            if err is not None:
                self.error[i] = None
                self.close()
                raise err

    def reset(self):
        for e in self.data_ready:
            e.wait()
        self._raise_pending()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        self._raise_pending()
        if self.next_batch[0] is None:
            return False
        self.current_batch = DataBatch(
            sum((b.data for b in self.next_batch), []),
            sum((b.label for b in self.next_batch), []) if self.next_batch[0].label else None,
            self.next_batch[0].pad,
            self.next_batch[0].index)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def MXDataIter(*args, **kwargs):
    raise MXNetError("MXDataIter is C-backed in the reference; use the named "
                     "iterators (ImageRecordIter, CSVIter, NDArrayIter)")


class CSVIter(DataIter):
    """CSV iterator (reference: ``src/io/iter_csv.cc``)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32", **kwargs):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",", dtype=dtype, ndmin=2)
        self._data = data.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=dtype, ndmin=2)
            self._label = label.reshape((-1,) + tuple(label_shape))
        else:
            self._label = _np.zeros((len(self._data), 1), dtype=dtype)
        self._inner = NDArrayIter(self._data, self._label, batch_size,
                                  last_batch_handle="roll_over" if round_batch else "pad")
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def _parse_libsvm(path, dtype):
    """Parse a zero-based-index LibSVM file into CSR triplets + labels.

    Reference: ``src/io/iter_libsvm.cc`` (``LibSVMIterParam`` — indices
    are zero-based; ``#`` starts a comment; one or more leading label
    columns per row)."""
    indptr = [0]
    indices = []
    values = []
    labels = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            toks = line.split()
            lab = []
            feat_start = 0
            for tok in toks:
                if ":" in tok:
                    break
                lab.append(float(tok))
                feat_start += 1
            for tok in toks[feat_start:]:
                i, v = tok.split(":", 1)
                indices.append(int(i))
                values.append(float(v))
            indptr.append(len(indices))
            labels.append(lab if lab else [0.0])
    width = max(len(l) for l in labels) if labels else 1
    lab_arr = _np.zeros((len(labels), width), dtype)
    for r, l in enumerate(labels):
        lab_arr[r, :len(l)] = l
    return (_np.asarray(values, dtype), _np.asarray(indices, _np.int32),
            _np.asarray(indptr, _np.int64), lab_arr)


def _csr_row_slice(vals, idx, indptr, lo, hi):
    """Slice CSR triplets to rows [lo, hi) with a rebased indptr."""
    sub_indptr = (indptr[lo:hi + 1] - indptr[lo]).astype(_np.int64)
    sl = slice(indptr[lo], indptr[hi])
    return vals[sl], idx[sl], sub_indptr


class LibSVMIter(DataIter):
    """LibSVM-format iterator yielding CSR data batches (reference:
    ``src/io/iter_libsvm.cc`` registered via ``DataIteratorReg``).

    ``data_libsvm``: path to the libsvm file; ``data_shape``: feature
    dimension (int or 1-tuple). Labels come from the leading column(s)
    of the data file, or — when ``label_libsvm`` is given — from the
    feature vectors of that second libsvm file densified to
    ``label_shape`` (the reference's multi-label arrangement).
    ``round_batch=True`` wraps the last short batch to the epoch start;
    ``False`` pads it with empty rows. Either way ``batch.pad`` reports
    the non-original row count (reference ``num_batch_padd``)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, round_batch=True,
                 num_parts=1, part_index=0, dtype="float32", **kwargs):
        super().__init__(batch_size)
        from ..ndarray.sparse import CSRNDArray

        self._csr_cls = CSRNDArray
        if isinstance(data_shape, int):
            data_shape = (data_shape,)
        self._nfeat = int(data_shape[0])
        if num_parts < 1 or not 0 <= part_index < num_parts:
            raise MXNetError(
                f"part_index {part_index} out of range for "
                f"num_parts {num_parts}")
        vals, idx, indptr, file_labels = _parse_libsvm(data_libsvm, dtype)
        # validate BEFORE sharding so a bad file fails identically on
        # every worker, not just the one holding the offending row
        if idx.size and int(idx.max()) >= self._nfeat:
            raise MXNetError(
                f"LibSVMIter: feature index {int(idx.max())} out of range "
                f"for data_shape {self._nfeat} in {data_libsvm}")
        total_rows = len(indptr) - 1
        shard = None
        if num_parts > 1:
            # distributed sharded read (reference: num_parts/part_index
            # on iter_libsvm.cc): worker part_index owns one contiguous
            # row block; the blocks tile the file exactly. Note: each
            # worker still PARSES the whole file and keeps its slice —
            # fine at the scale this pure-Python reader serves (the
            # reference's byte-range splitter is the optimization to
            # reach for if startup cost ever matters).
            lo = part_index * total_rows // num_parts
            hi = (part_index + 1) * total_rows // num_parts
            shard = (lo, hi)
            vals, idx, indptr = _csr_row_slice(vals, idx, indptr, lo, hi)
            file_labels = file_labels[lo:hi]
        self._vals, self._idx, self._indptr = vals, idx, indptr
        self._nrows = len(indptr) - 1
        if label_libsvm is not None:
            lv, li, lp, _ = _parse_libsvm(label_libsvm, dtype)
            if isinstance(label_shape, int):
                label_shape = (label_shape,)
            width = int(label_shape[0]) if label_shape else \
                (int(li.max()) + 1 if li.size else 1)
            if li.size and int(li.max()) >= width:
                raise MXNetError(
                    f"LibSVMIter: label index {int(li.max())} out of range "
                    f"for label_shape {width} in {label_libsvm}")
            dense = _np.zeros((len(lp) - 1, width), dtype)
            for r in range(len(lp) - 1):
                sl = slice(lp[r], lp[r + 1])
                dense[r, li[sl]] = lv[sl]
            if len(dense) != total_rows:
                raise MXNetError(
                    f"LibSVMIter: {total_rows} data rows but "
                    f"{len(dense)} label rows in {label_libsvm}")
            if shard is not None:
                # the label file shards by the SAME row block as data
                dense = dense[shard[0]:shard[1]]
            self._labels = dense
        else:
            self._labels = file_labels
        if len(self._labels) != self._nrows:
            raise MXNetError(
                f"LibSVMIter: {self._nrows} data rows but "
                f"{len(self._labels)} label rows")
        self._round_batch = round_batch
        self._cursor = 0
        self.provide_data = [DataDesc("data", (batch_size, self._nfeat))]
        lab_shape = (batch_size,) if self._labels.shape[1] == 1 else \
            (batch_size,) + self._labels.shape[1:]
        self.provide_label = [DataDesc("softmax_label", lab_shape)]

    def _rows(self, lo, hi):
        vals, idx, indptr = _csr_row_slice(self._vals, self._idx,
                                           self._indptr, lo, hi)
        return vals, idx, indptr, self._labels[lo:hi]

    def reset(self):
        self._cursor = 0

    def next(self):
        if self._cursor >= self._nrows:
            raise StopIteration
        lo = self._cursor
        hi = min(lo + self.batch_size, self._nrows)
        vals, idx, indptr, labels = self._rows(lo, hi)
        pad = self.batch_size - (hi - lo)
        if pad and self._round_batch:
            # wrap to the epoch start, repeating the epoch as many times
            # as needed when the dataset is shorter than one batch; pad
            # still REPORTS the wrapped row count (reference
            # num_batch_padd) so consumers can exclude the duplicates
            vparts, iparts, pparts, lparts = [vals], [idx], [indptr], [labels]
            need, base = pad, indptr[-1]
            epoch = None  # full-epoch chunk, sliced once and reused
            while need > 0:
                take = min(need, self._nrows)
                if take == self._nrows:
                    if epoch is None:
                        epoch = self._rows(0, take)
                    wvals, widx, windptr, wlabels = epoch
                else:
                    wvals, widx, windptr, wlabels = self._rows(0, take)
                vparts.append(wvals)
                iparts.append(widx)
                pparts.append(windptr[1:] + base)
                lparts.append(wlabels)
                base += windptr[-1]
                need -= take
            vals = _np.concatenate(vparts)
            idx = _np.concatenate(iparts)
            indptr = _np.concatenate(pparts)
            labels = _np.concatenate(lparts)
        elif pad:
            # short tail: pad with empty rows
            indptr = _np.concatenate(
                [indptr, _np.full((pad,), indptr[-1], _np.int64)])
            labels = _np.concatenate(
                [labels, _np.zeros((pad,) + labels.shape[1:], labels.dtype)])
        self._cursor = hi
        data = self._csr_cls(vals, indptr, idx,
                             (self.batch_size, self._nfeat))
        label = _array(labels[:, 0] if labels.shape[1] == 1 else labels)
        return DataBatch(data=[data], label=[label], pad=pad)


class _NativeImageRecordIter(DataIter):
    """C++-backed RecordIO image pipeline (the reference's
    ``ImageRecordIter2`` role — decode/augment/batch off the Python thread)."""

    def __init__(self, pipeline, batch_size, data_shape, label_width):
        super().__init__(batch_size)
        self._pipe = pipeline
        self.provide_data = [DataDesc("data", (batch_size,) + tuple(data_shape))]
        self.provide_label = [DataDesc("softmax_label",
                                       (batch_size, label_width))]

    def reset(self):
        self._pipe.reset()

    def next(self):
        res = self._pipe.next_batch()
        if res is None:
            raise StopIteration
        data, label, n = res
        return DataBatch(data=[_array(data.copy())],
                         label=[_array(label.copy())],
                         pad=self.batch_size - n)


def ImageRecordIter(path_imgrec=None, data_shape=(3, 224, 224), batch_size=1,
                    label_width=1, shuffle=False, rand_crop=False,
                    rand_mirror=False, mean_r=0, mean_g=0, mean_b=0,
                    std_r=1, std_g=1, std_b=1, resize=0, preprocess_threads=4,
                    prefetch_buffer=4, seed=0, **kwargs):
    """Threaded RecordIO image pipeline (reference:
    ``src/io/iter_image_recordio_2.cc`` via factory registration).

    Uses the C++ pipeline in ``cxx/libmxtpu.so`` (decode + augment + batch
    on native threads) when available; falls back to the Python
    ``image.ImageIter`` + ``PrefetchingIter`` otherwise.
    """
    import os

    import numpy as np

    from .. import _native

    mean = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b])

    if path_imgrec and _native.available() and not kwargs.get("aug_list"):
        idx_path = kwargs.get("path_imgidx") or \
            os.path.splitext(path_imgrec)[0] + ".idx"
        if os.path.exists(idx_path):
            std = [std_r, std_g, std_b] if (std_r != 1 or std_g != 1
                                            or std_b != 1) else None
            pipe = _native.NativeImagePipeline(
                path_imgrec, idx_path, batch_size, tuple(data_shape),
                shuffle=shuffle, num_threads=preprocess_threads,
                rand_crop=rand_crop, rand_mirror=rand_mirror,
                mean=list(mean) if mean is not None else None, std=std,
                label_width=label_width, seed=seed)
            return _NativeImageRecordIter(pipe, batch_size, data_shape,
                                          label_width)

    from ..image import ImageIter

    it = ImageIter(batch_size=batch_size, data_shape=tuple(data_shape),
                   label_width=label_width, path_imgrec=path_imgrec,
                   shuffle=shuffle, rand_crop=rand_crop,
                   rand_mirror=rand_mirror, mean=mean, resize=resize,
                   **{k: v for k, v in kwargs.items()
                      if k in ("path_imglist", "path_root", "aug_list")})
    return PrefetchingIter(it)


def MNISTIter(image=None, label=None, batch_size=1, shuffle=True, flat=False,
              **kwargs):
    """MNIST idx-file iterator (reference: ``src/io/iter_mnist.cc``)."""
    import gzip
    import struct

    def opener(p):
        return gzip.open(p, "rb") if p.endswith(".gz") else open(p, "rb")

    with opener(label) as fin:
        struct.unpack(">II", fin.read(8))
        lbl = _np.frombuffer(fin.read(), dtype=_np.uint8).astype("float32")
    with opener(image) as fin:
        _, n, rows, cols = struct.unpack(">IIII", fin.read(16))
        img = _np.frombuffer(fin.read(), dtype=_np.uint8)
        img = img.reshape(n, rows, cols).astype("float32") / 255.0
    if flat:
        img = img.reshape(n, rows * cols)
    else:
        img = img.reshape(n, 1, rows, cols)
    return NDArrayIter(img, lbl, batch_size, shuffle=shuffle)
