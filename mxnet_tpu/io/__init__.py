"""``mx.io`` — legacy DataIter API (reference: ``python/mxnet/io/io.py``)."""

from .io import (  # noqa: F401
    DataDesc,
    DataBatch,
    DataIter,
    NDArrayIter,
    ResizeIter,
    PrefetchingIter,
    MXDataIter,
    CSVIter,
    LibSVMIter,
    ImageRecordIter,
    MNISTIter,
)
