"""Base utilities: error types, env helpers, shape helpers.

TPU-native re-imagining of the reference's ``python/mxnet/base.py``
(symbol: ``check_call``/``MXNetError``) — there is no C ABI to check
calls against; errors are plain Python exceptions raised eagerly or,
for async dispatch, surfaced at sync points (see ``mxnet_tpu.ndarray``).
"""

from __future__ import annotations

import os


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: ``base.py:MXNetError``)."""


def getenv(name: str, default=None, *, dtype=str):
    """Read an ``MXTPU_*`` env var (reference analog: ``dmlc::GetEnv``)."""
    v = os.environ.get(name)
    if v is None:
        return default
    if dtype is bool:
        return v not in ("0", "false", "False", "")
    return dtype(v)


_INT_TYPES = (int,)
try:  # numpy integers count as ints everywhere shapes appear
    import numpy as _np

    _INT_TYPES = (int, _np.integer)
except ImportError:  # pragma: no cover
    pass


def is_int(x) -> bool:
    return isinstance(x, _INT_TYPES) and not isinstance(x, bool)


def check_shape(shape) -> tuple:
    """Canonicalize a user-supplied shape to a tuple of ints."""
    if is_int(shape):
        return (int(shape),)
    return tuple(int(d) for d in shape)


class classproperty:  # noqa: N801 - decorator-style name
    def __init__(self, fget):
        self.fget = fget

    def __get__(self, obj, owner):
        return self.fget(owner)
