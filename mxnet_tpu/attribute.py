"""Symbol attribute scoping (reference: ``python/mxnet/attribute.py``).

``AttrScope(ctx_group=...)`` was the reference's manual model-parallel
placement hook (SURVEY.md §2.5 P8); under pjit the analog is a sharding
annotation, but the attribute plumbing is kept for symbol-graph parity.
"""

from __future__ import annotations

import threading


class AttrScope:
    _current = threading.local()

    def __init__(self, **kwargs):
        self._old_scope = None
        self._attr = kwargs

    def get(self, attr=None):
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        self._old_scope = getattr(AttrScope._current, "value", None)
        attr = {} if self._old_scope is None else dict(self._old_scope._attr)
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, *exc):
        AttrScope._current.value = self._old_scope
        return False

    @staticmethod
    def current():
        cur = getattr(AttrScope._current, "value", None)
        return cur if cur is not None else AttrScope()
