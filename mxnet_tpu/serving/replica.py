"""Serving replicas: one engine-behind-a-repository per host/process.

The unit the fleet router dispatches onto. Two implementations with
one surface:

- :class:`LocalReplica` — in-process: a private :class:`ModelRepository`
  wrapping one :class:`InferenceEngine` (same process, own queue). The
  unit-test and single-host form; ``kill()`` simulates abrupt host
  death (queued requests FAIL typed via ``ContinuousBatcher.abort`` —
  they never hang, and the router fails them over).
- :class:`ProcessReplica` — a child process running
  ``mxnet_tpu.serving.replica_worker`` with a length-prefixed pickle
  RPC over stdin/stdout: submit / ping / swap / close. Request
  completions stream back on a reader thread; a broken pipe or child
  death fails every pending future with a typed
  :class:`~.errors.ReplicaDead` IMMEDIATELY — the failure mode chaos
  certification exists to prove (``kill()`` here is a real SIGKILL).

Replica specs are plain dicts so they cross the process boundary::

    {"net": {"dense": {"classes": 4, "feat": 8, "bias": 0.5}},
     "shapes": [(8,)], "version": "v1",
     "engine": {"max_batch": 8, "max_wait_ms": 2.0}}

``net`` is a builtin-net dict, an importable ``"module:callable"``
factory path, a zero-arg factory, or a ready block (the last two for
local replicas). Every replica carries health bookkeeping (state,
heartbeat misses, last-known queue depth) owned by the
:class:`~.fleet.ReplicaSet` health loop.
"""

from __future__ import annotations

import importlib
import itertools
import os
import pickle
import struct
import subprocess
import sys
import threading
import time

import numpy as _np

from ..base import MXNetError
from ..resilience import chaos as _chaos
from .errors import (
    BrownoutShed,
    EngineClosed,
    KVCacheOOM,
    ReplicaDead,
    ReplicaLost,
    RequestCancelled,
    RequestTimeout,
    RequestTooLarge,
    RetraceForbidden,
    ServerOverloaded,
    ServingError,
    StagedLoadError,
)
from .repository import ModelRepository

#: process-unique replica uids: the router's at-most-once set is keyed
#: by uid, so a REPLACEMENT replica at a dead one's index is a fresh
#: candidate while the dead one stays burned
_UIDS = itertools.count(1)

#: typed-error wire registry: the child sends ``(etype, emsg)`` and the
#: parent re-raises the SAME class, so response-code mapping by type
#: survives the RPC hop (unknown types degrade to ServingError)
_ERROR_TYPES = {cls.__name__: cls for cls in (
    ServingError, ServerOverloaded, BrownoutShed, RequestTimeout,
    RequestTooLarge, EngineClosed, RetraceForbidden, StagedLoadError,
    RequestCancelled, ReplicaDead, ReplicaLost, KVCacheOOM, MXNetError)}
_ERROR_TYPES["TimeoutError"] = TimeoutError


def rebuild_error(etype, emsg):
    """Wire form -> typed exception (the parent half of the registry)."""
    return _ERROR_TYPES.get(str(etype), ServingError)(str(emsg))


# ---------------------------------------------------------------------------
# net specs (shared with the child worker)
# ---------------------------------------------------------------------------

def _dense_net(classes=4, feat=8, bias=0.0, scale=0.1):
    """Builtin deterministic worker net — ``y[c] = scale * sum(x) +
    bias`` for every class ``c``. Process replicas and the bench build
    it child-side without importing any test code; model VERSIONS are
    distinguishable by their bias (the swap-coherence probes rely on
    it)."""
    from .. import ndarray as nd
    from ..gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(int(classes), in_units=int(feat)))
    net.initialize()
    net[0].weight.set_data(nd.ones((int(classes), int(feat))) * float(scale))
    net[0].bias.set_data(nd.ones((int(classes),)) * float(bias))
    return net


def build_net(net_spec):
    """Materialize a replica spec's ``net`` entry into a servable block:
    a ready block passes through, a zero-arg factory is called, an
    ``"module:attr"`` path is imported (the ONLY callable form that
    crosses the process boundary), and ``{"dense": {...}}`` builds the
    builtin deterministic net."""
    if hasattr(net_spec, "aot_predict_fn") \
            or hasattr(net_spec, "decode_step_fn"):
        return net_spec
    if isinstance(net_spec, str):
        mod, _, attr = net_spec.partition(":")
        if not mod or not attr:
            raise MXNetError(
                f"replica net path {net_spec!r} must be 'module:callable'")
        return build_net(getattr(importlib.import_module(mod), attr))
    if isinstance(net_spec, dict) and "dense" in net_spec:
        return _dense_net(**dict(net_spec["dense"]))
    if isinstance(net_spec, dict) and "decoder" in net_spec:
        # generation workload: every replica rebuilds the decoder from
        # the same seeded spec, so the fleet serves identical weights
        from .decoder import TransformerDecoderLM

        return TransformerDecoderLM(**dict(net_spec["decoder"]))
    if callable(net_spec):
        return build_net(net_spec())
    raise MXNetError(
        f"cannot build a replica net from {type(net_spec).__name__} "
        "(want a block, a factory, 'module:callable', "
        "{'dense': {...}}, or {'decoder': {...}})")


def normalize_spec(spec) -> dict:
    """Validate + copy a replica spec dict."""
    spec = dict(spec)
    if "net" not in spec or "shapes" not in spec:
        raise MXNetError("replica spec needs 'net' and 'shapes' entries")
    spec.setdefault("engine", {})
    return spec


# ---------------------------------------------------------------------------
# wire framing (parent <-> child): 4-byte big-endian length + pickle
# ---------------------------------------------------------------------------

def write_msg(stream, obj):
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    stream.write(struct.pack(">I", len(blob)) + blob)
    stream.flush()


def read_msg(stream):
    head = stream.read(4)
    if head is None or len(head) < 4:
        raise EOFError("replica pipe closed")
    n = struct.unpack(">I", head)[0]
    chunks = []
    while n > 0:
        chunk = stream.read(n)
        if not chunk:
            raise EOFError("replica pipe truncated mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return pickle.loads(b"".join(chunks))


# ---------------------------------------------------------------------------
# replica base: health + depth bookkeeping shared by both kinds
# ---------------------------------------------------------------------------

class _ReplicaBase:
    kind = "?"

    def __init__(self, index, spec, name="model"):
        self.uid = next(_UIDS)
        self.index = int(index)
        self.name = str(name)
        self.spec = normalize_spec(spec)
        self.state = "starting"   # starting|live|suspect|dead|warm|closed
        self.misses = 0           # consecutive heartbeat misses
        self.death_mono = None    # monotonic stamp of death detection
        self._depth = 0
        self._depth_mono = 0.0

    def note_depth(self, depth):
        self._depth = int(depth)
        self._depth_mono = time.monotonic()

    def depth_age(self) -> float:
        """Seconds since the last depth observation (inf before the
        first one) — the router's freshness test for this signal."""
        if not self._depth_mono:
            return float("inf")
        return time.monotonic() - self._depth_mono

    def queue_depth(self) -> int:
        return self._depth

    def _chaos_point(self):
        # stall@replica<k> lands here: every dispatch onto this replica
        # stalls (serving straggler), feeding depth avoidance + hedging
        if _chaos.ENABLED:
            _chaos.step_point(f"replica{self.index}")

    def __repr__(self):
        return (f"<{type(self).__name__} {self.name}#{self.index} "
                f"uid={self.uid} {self.state}>")


class LocalReplica(_ReplicaBase):
    """In-process replica: a private ModelRepository + engine."""

    kind = "local"

    def __init__(self, index, spec, name="model"):
        super().__init__(index, spec, name)
        self._dead = False
        self._repo = ModelRepository(keep=int(self.spec.get("keep", 1)))
        self._load(self.spec)
        self.state = "live"

    def _load(self, spec):
        eng_kwargs = dict(spec.get("engine") or {})
        self._repo.load(self.name, lambda: build_net(spec["net"]),
                        spec["shapes"], version=spec.get("version"),
                        **eng_kwargs)

    def wait_ready(self, timeout=None):
        return self  # construction already compiled + verified

    def _dead_error(self):
        return ReplicaDead(
            f"replica {self.name}#{self.index} is dead (host kill) — "
            "retry on a surviving replica")

    def submit(self, x, **kwargs):
        if self._dead:
            raise self._dead_error()
        self._chaos_point()
        try:
            return self._repo.submit(self.name, x, **kwargs)
        except EngineClosed:
            if self._dead:
                raise self._dead_error() from None
            raise

    def ping(self, timeout=None) -> dict:
        if self._dead:
            raise self._dead_error()
        engine = self._repo.engine(self.name)
        depth = engine.queue_depth()
        self.note_depth(depth)
        return {"depth": depth, "version": engine.version}

    def queue_depth(self) -> int:
        if not self._dead:
            try:
                self.note_depth(self._repo.engine(self.name).queue_depth())
            except ServingError:
                pass
        return self._depth

    def depth_age(self) -> float:
        return 0.0 if not self._dead else super().depth_age()

    def live_version(self):
        return self._repo.live_version(self.name)

    def swap(self, spec, timeout=None):
        """Staged swap on THIS replica (stage -> verify -> atomic flip
        via the repository; a failed stage never becomes visible)."""
        spec = normalize_spec(spec)
        self._load(spec)
        self.spec = spec
        return self._repo.live_version(self.name)

    def stats(self) -> dict:
        return self._repo.stats(self.name)

    def pause(self):
        """Warm-pool parking (scale-to-zero): drain, keep executables
        and weights resident — ``resume()`` is instant, no recompile."""
        self._repo.engine(self.name).pause()
        self.state = "warm"

    def resume(self):
        self._repo.engine(self.name).resume()
        self.state = "live"

    def kill(self):
        """Abrupt host-death simulation: queued requests fail with
        typed ReplicaDead (never drained, never hung)."""
        self._dead = True
        self.state = "dead"
        if self.death_mono is None:
            self.death_mono = time.monotonic()
        try:
            self._repo.engine(self.name).kill()
        except ServingError:
            pass

    def close(self):
        """Graceful retirement (shrink): drain in-flight, release."""
        self._repo.close()
        if self.state != "dead":
            self.state = "closed"


# ---------------------------------------------------------------------------
# process replica (parent side)
# ---------------------------------------------------------------------------

class RemoteFuture:
    """Parent-side handle for one RPC to a child replica; same waiting
    surface as :class:`~.batcher.ServeFuture` (done/result/version)."""

    def __init__(self, replica, msg_id):
        self.replica = replica
        self.msg_id = msg_id
        self.version = None
        self._event = threading.Event()
        self._result = None
        self._error = None

    def done(self) -> bool:
        return self._event.is_set()

    def finish(self, result=None, error=None, version=None):
        self._result = result
        self._error = error
        if version is not None:
            self.version = version
        self._event.set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"replica {self.replica.name}#{self.replica.index} RPC "
                f"{self.msg_id} not ready within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


class ProcessReplica(_ReplicaBase):
    """A replica in its own OS process (the 'host' of host-kill chaos).

    RPC: pickle frames over stdin/stdout; a reader thread resolves
    pending futures; EOF/broken pipe => every pending future fails with
    typed ReplicaDead immediately (in-flight requests NEVER hang on a
    dead host). ``kill()`` is a real SIGKILL.
    """

    kind = "process"

    #: machine-checked lock protocol (mxtpu-lint thread-guard)
    _GUARDED_BY = {"_pending": "_lock", "_dead": "_lock"}

    def __init__(self, index, spec, name="model", env=None):
        super().__init__(index, spec, name)
        self._env = dict(env or {})
        self._lock = threading.Lock()
        self._wlock = threading.Lock()
        self._closing = False
        self._spawn()

    def _spawn(self):
        with self._lock:
            self._dead = False
            self._ids = itertools.count(1)
            self._pending = {}
        self._closing = False
        child_env = dict(os.environ)
        child_env.update(self._env)
        child_env.setdefault("JAX_PLATFORMS", "cpu")
        # the child must resolve the SAME mxnet_tpu the parent runs,
        # even when the parent found it via sys.path (user script)
        # rather than an installed distribution
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        prior = child_env.get("PYTHONPATH", "")
        child_env["PYTHONPATH"] = \
            pkg_root + (os.pathsep + prior if prior else "")
        # fleet faults fire in the PARENT (by replica index); the child
        # must not independently re-fire the same spec
        child_env.pop("MXTPU_CHAOS", None)
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "mxnet_tpu.serving.replica_worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=child_env)
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"mxtpu-replica{self.index}-reader")
        self._ready = self._call({"op": "init", "spec": self.spec,
                                  "name": self.name})
        self._reader.start()
        self.state = "starting"

    def wait_ready(self, timeout=180.0):
        """Block until the child compiled + verified its model (the
        persistent compile cache is what makes respawn/restore fast)."""
        self._ready.result(timeout)
        self.state = "live"
        return self

    # -- RPC plumbing ------------------------------------------------------
    def _dead_error(self, why=None):
        return ReplicaDead(
            f"replica {self.name}#{self.index} is dead"
            f"{' (' + why + ')' if why else ''} — retry on a surviving "
            "replica")

    def _call(self, msg) -> RemoteFuture:
        mid = next(self._ids)
        fut = RemoteFuture(self, mid)
        with self._lock:
            if self._dead:
                raise self._dead_error()
            self._pending[mid] = fut
        try:
            with self._wlock:
                write_msg(self._proc.stdin, dict(msg, id=mid))
        except Exception as e:
            self._mark_dead(f"pipe write failed: {type(e).__name__}")
            raise self._dead_error("pipe write failed") from None
        return fut

    def _read_loop(self):  # mxtpu-lint: hot-path
        try:
            while True:
                msg = read_msg(self._proc.stdout)
                mid = msg.get("id")
                if "depth" in msg:
                    self.note_depth(msg["depth"])
                with self._lock:
                    fut = self._pending.pop(mid, None)
                if fut is None:
                    continue
                if msg.get("ok"):
                    fut.finish(result=msg.get("result"),
                               version=msg.get("version"))
                else:
                    fut.finish(error=rebuild_error(msg.get("etype"),
                                                   msg.get("emsg")))
        except Exception:
            pass
        self._mark_dead("child pipe closed")

    def _mark_dead(self, why):
        with self._lock:
            if self._closing:
                # graceful close/pause: EOF is expected, pending is empty
                self._dead = True
                return
            already = self._dead
            self._dead = True
            pending, self._pending = self._pending, {}
        if already:
            return
        self.state = "dead"
        if self.death_mono is None:
            self.death_mono = time.monotonic()
        err = self._dead_error(why)
        for fut in pending.values():
            fut.finish(error=err)

    # -- replica surface ---------------------------------------------------
    def submit(self, x, **kwargs):
        self._chaos_point()
        arr = x.asnumpy() if hasattr(x, "asnumpy") else _np.asarray(x)
        return self._call({"op": "submit", "x": arr,
                           "kwargs": {k: v for k, v in kwargs.items()
                                      if v is not None}})

    def ping(self, timeout=2.0) -> dict:
        info = self._call({"op": "ping"}).result(timeout)
        self.note_depth(int(info.get("depth", 0)))
        return info

    def live_version(self):
        try:
            return self.ping().get("version")
        except (ServingError, TimeoutError):
            return None

    def swap(self, spec, timeout=180.0):
        """Staged swap inside the child (its repository stages,
        verifies, flips); returns the new live version."""
        spec = normalize_spec(spec)
        version = self._call({"op": "swap", "spec": spec}).result(timeout)
        self.spec = spec
        return version

    def stats(self) -> dict:
        return self.ping().get("stats") or {}

    def pause(self):
        """Warm-pool parking for a process replica: the child exits
        (graceful drain) and only the spec is kept — ``resume()``
        respawns through the persistent compile cache."""
        self._shutdown(graceful=True)
        self.state = "warm"

    def resume(self, timeout=180.0):
        self._spawn()
        return self.wait_ready(timeout)

    def kill(self):
        """Real SIGKILL — the chaos ``kill_replica`` actuation."""
        if self.death_mono is None:
            self.death_mono = time.monotonic()
        self.state = "dead"
        try:
            self._proc.kill()
        except Exception:
            pass

    def _shutdown(self, graceful=True):
        with self._lock:
            self._closing = True
        if graceful:
            try:
                self._call({"op": "close"}).result(10.0)
            except Exception:
                pass
        try:
            self._proc.wait(timeout=10.0)
        except Exception:
            try:
                self._proc.kill()
            except Exception:
                pass

    def close(self):
        self._shutdown(graceful=True)
        if self.state != "dead":
            self.state = "closed"

    def __del__(self):
        try:
            if getattr(self, "_proc", None) is not None \
                    and self._proc.poll() is None:
                self._proc.kill()
        except Exception:
            pass
