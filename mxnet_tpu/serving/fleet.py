"""Self-healing serving fleet: replica set + router + degraded mode.

:class:`ReplicaSet` owns N :class:`~.replica.LocalReplica` /
:class:`~.replica.ProcessReplica` instances behind health checking —
a heartbeat loop pings every replica; consecutive misses walk a
replica live -> suspect -> dead, and request-level failures
(:class:`~.errors.ReplicaDead` out of a dispatch) short-circuit that
walk, because a broken pipe IS the health check. Death fails the
replica's queued requests typed (never hung), burns its uid for
routing, and hands the corpse to the autoscaler for replacement.

:class:`ServingFleet` is the client object: ``submit(x, priority=...)``
routes through a :class:`~.router.ReplicaRouter` and layers the
degraded-mode overload policy on top — a LATCHED brownout state
machine driven by aggregate queue fraction:

  level 0 (clear)     all classes admitted
  level 1 (brownout)  ``bulk`` shed                     [frac >= enter]
  level 2 (blackout)  ``bulk`` + ``interactive`` shed   [frac >= enter2]
  ``critical`` is NEVER policy-shed (only hard queue-full rejects it)

Escalation is immediate; de-escalation requires the fraction to stay
below the exit threshold for a hold window (one level per window), so
a saturated fleet sheds instantly but a flapping signal cannot
oscillate admission. Transitions emit ``mxtpu_fleet_brownout`` + a
trace instant; every shed increments ``mxtpu_fleet_shed_total`` by
priority class and raises typed :class:`~.errors.BrownoutShed`.

Scale-to-zero parks every replica in the warm pool (weights + compile
cache resident); the first submit against a zero-live fleet restores
synchronously rather than failing — cold start is a latency cost, not
an error.
"""

from __future__ import annotations

import threading
import time

from .. import observability as _obs
from ..base import MXNetError, getenv
from ..resilience import chaos as _chaos
from .engine import serve_queue_cap
from .errors import BrownoutShed, ReplicaDead, ServingError
from .replica import LocalReplica, ProcessReplica, normalize_spec
from .router import ReplicaRouter

#: admission-priority classes, strongest-protection first; shedding
#: strictly walks this list from the RIGHT (bulk first, critical never)
PRIORITIES = ("critical", "interactive", "bulk")


def fleet_replicas() -> int:
    """Initial replica count, ``MXTPU_FLEET_REPLICAS``."""
    return max(1, int(getenv("MXTPU_FLEET_REPLICAS", 2, dtype=int)))


def fleet_min_replicas() -> int:
    """Autoscaler floor, ``MXTPU_FLEET_MIN_REPLICAS`` (0 permits
    scale-to-zero)."""
    return max(0, int(getenv("MXTPU_FLEET_MIN_REPLICAS", 1, dtype=int)))


def fleet_max_replicas() -> int:
    """Autoscaler ceiling, ``MXTPU_FLEET_MAX_REPLICAS``."""
    return max(1, int(getenv("MXTPU_FLEET_MAX_REPLICAS", 8, dtype=int)))


def fleet_heartbeat_s() -> float:
    """Heartbeat period, ``MXTPU_FLEET_HEARTBEAT_S``."""
    return max(0.05, float(getenv("MXTPU_FLEET_HEARTBEAT_S", 0.5,
                                  dtype=float)))


def fleet_suspect_misses() -> int:
    """Consecutive heartbeat misses before a suspect replica is
    declared dead, ``MXTPU_FLEET_SUSPECT_MISSES``."""
    return max(1, int(getenv("MXTPU_FLEET_SUSPECT_MISSES", 3, dtype=int)))


def fleet_brownout_enter() -> float:
    """Aggregate queue fraction that LATCHES brownout level 1,
    ``MXTPU_FLEET_BROWNOUT_ENTER``."""
    return float(getenv("MXTPU_FLEET_BROWNOUT_ENTER", 0.85, dtype=float))


def fleet_brownout_exit() -> float:
    """Queue fraction below which de-escalation becomes ELIGIBLE,
    ``MXTPU_FLEET_BROWNOUT_EXIT`` (hysteresis floor)."""
    return float(getenv("MXTPU_FLEET_BROWNOUT_EXIT", 0.30, dtype=float))


def fleet_brownout_hold_s() -> float:
    """How long the fraction must stay below the exit threshold before
    stepping DOWN one brownout level, ``MXTPU_FLEET_BROWNOUT_HOLD_S``."""
    return max(0.0, float(getenv("MXTPU_FLEET_BROWNOUT_HOLD_S", 1.0,
                                 dtype=float)))


class ReplicaSet:
    """N replicas of one model spec + the health plane over them."""

    #: machine-checked lock protocol (mxtpu-lint thread-guard)
    _GUARDED_BY = {"_replicas": "_lock", "_next_index": "_lock"}

    def __init__(self, spec, *, name="model", replicas=None, process=False,
                 heartbeat_s=None, suspect_misses=None, on_death=None,
                 autostart=True):
        self.name = str(name)
        self.spec = normalize_spec(spec)
        self.process = bool(process)
        self._heartbeat_s = fleet_heartbeat_s() if heartbeat_s is None \
            else float(heartbeat_s)
        self._suspect_misses = fleet_suspect_misses() \
            if suspect_misses is None else int(suspect_misses)
        self._on_death = on_death
        self._lock = threading.RLock()
        self._replicas = []
        self._next_index = 0
        self._closed = False
        self._hb_thread = None
        n = fleet_replicas() if replicas is None else int(replicas)
        self._spawn_initial(n)
        if autostart:
            self.start_heartbeat()

    # -- spawning ----------------------------------------------------------
    def _new_replica(self, spec=None):
        with self._lock:
            index = self._next_index
            self._next_index += 1
        cls = ProcessReplica if self.process else LocalReplica
        return cls(index, spec or self.spec, name=self.name)

    def _spawn_initial(self, n):
        fresh = [self._new_replica() for _ in range(max(1, n))]
        for r in fresh:
            r.wait_ready()  # process replicas compile concurrently
        with self._lock:
            self._replicas.extend(fresh)
        self.census()

    # -- views -------------------------------------------------------------
    def replicas(self):
        with self._lock:
            return list(self._replicas)

    def live(self):
        """Routable replicas (live + suspect: a suspect still serves
        until it is PROVEN dead — requests on it fail over typed)."""
        with self._lock:
            return [r for r in self._replicas
                    if r.state in ("live", "suspect")]

    def warm(self):
        with self._lock:
            return [r for r in self._replicas if r.state == "warm"]

    def n_live(self) -> int:
        return len(self.live())

    def queue_cap(self) -> int:
        return int((self.spec.get("engine") or {}).get("queue_cap")
                   or serve_queue_cap())

    def census(self):
        """Publish per-state replica counts (``mxtpu_fleet_replicas``)."""
        counts = {}
        for r in self.replicas():
            counts[r.state] = counts.get(r.state, 0) + 1
        if _obs.ENABLED:
            _obs.record_fleet_states(self.name, counts)
        return counts

    # -- health plane ------------------------------------------------------
    def start_heartbeat(self):
        with self._lock:
            if self._hb_thread is not None or self._closed:
                return
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True,
                name=f"mxtpu-fleet-{self.name}-heartbeat")
            self._hb_thread.start()

    def _hb_loop(self):  # mxtpu-lint: hot-path
        while True:
            with self._lock:
                if self._closed:
                    return
            self.heartbeat_once()
            time.sleep(self._heartbeat_s)

    def heartbeat_once(self):
        """One health sweep (the loop body, callable deterministically
        from tests): ping live/suspect replicas, walk the miss ladder."""
        for r in self.replicas():
            if r.state not in ("live", "suspect"):
                continue
            try:
                # generous timeout: a busy-but-alive replica must not be
                # declared dead (EOF/request-level detection catches real
                # deaths much faster than the miss ladder anyway)
                r.ping(timeout=max(1.0, 2.0 * self._heartbeat_s))
            except Exception:
                r.misses += 1
                if r.misses >= self._suspect_misses:
                    self.mark_dead(r, reason="heartbeat")
                elif r.state == "live":
                    r.state = "suspect"
            else:
                r.misses = 0
                if r.state == "suspect":
                    r.state = "live"
        self.census()

    def mark_dead(self, replica, reason="request"):
        """Declare a replica dead: fail its queued work typed, burn it
        for routing, notify the death listener (autoscaler)."""
        with self._lock:
            if replica.state == "dead" or replica not in self._replicas:
                dead_now = False
            else:
                replica.state = "dead"
                dead_now = True
        if not dead_now:
            return
        if replica.death_mono is None:
            replica.death_mono = time.monotonic()
        try:
            replica.kill()  # queued requests fail ReplicaDead, never hang
        except Exception:
            pass
        self.census()
        if self._on_death is not None:
            try:
                self._on_death(replica, reason)
            except Exception:
                pass

    # -- membership actuations --------------------------------------------
    def grow(self, n=1):
        """Add ``n`` fresh replicas (warm pool first, then spawn)."""
        added = []
        for _ in range(int(n)):
            warm = self.warm()
            if warm:
                r = warm[0]
                r.resume()
                added.append(r)
                continue
            r = self._new_replica()
            r.wait_ready()
            with self._lock:
                self._replicas.append(r)
            added.append(r)
        self.census()
        return added

    def shrink(self, n=1):
        """Retire ``n`` live replicas gracefully (drain, then close)."""
        victims = self.live()[-int(n):] if int(n) > 0 else []
        for r in victims:
            with self._lock:
                if r in self._replicas:
                    self._replicas.remove(r)
            r.close()
        self.census()
        return victims

    def replace(self, replica):
        """Swap a dead replica for a fresh one at a NEW uid (the dead
        uid stays burned in every in-flight request's tried set)."""
        fresh = self._new_replica(replica.spec)
        fresh.wait_ready()
        with self._lock:
            try:
                at = self._replicas.index(replica)
                self._replicas[at] = fresh
            except ValueError:
                self._replicas.append(fresh)
        try:
            replica.close()
        except Exception:
            pass
        self.census()
        return fresh

    def reap_dead(self):
        """Drop dead replicas from the set (post-replacement hygiene)."""
        with self._lock:
            dead = [r for r in self._replicas if r.state == "dead"]
            self._replicas = [r for r in self._replicas
                              if r.state != "dead"]
        for r in dead:
            try:
                r.close()
            except Exception:
                pass
        if dead:
            self.census()
        return dead

    def scale_to(self, target):
        target = max(0, int(target))
        n = self.n_live()
        if target > n:
            self.grow(target - n)
        elif target < n:
            if target == 0:
                self.scale_to_zero()
            else:
                self.shrink(n - target)
        return self.n_live()

    def scale_to_zero(self):
        """Park EVERY live replica in the warm pool: drained, weights
        and compile cache resident, zero serving capacity."""
        for r in self.live():
            try:
                r.pause()
            except Exception:
                pass
        self.census()

    def restore(self, n=None):
        """Warm-pool restore: resume parked replicas (no recompile —
        executables were kept / the compile cache is hot)."""
        warm = self.warm()
        n = len(warm) if n is None else min(int(n), len(warm))
        for r in warm[:n]:
            r.resume()
        self.census()
        return n

    # -- staged swap across the fleet --------------------------------------
    def swap(self, spec):
        """Rolling staged swap: each replica stages+verifies+flips the
        new version IN PLACE (repository semantics), one at a time, so
        capacity never drops by more than one replica and every request
        is answered by exactly one coherent version."""
        spec = normalize_spec(spec)
        versions = []
        for r in self.live():
            versions.append(r.swap(spec))
        self.spec = spec
        return versions

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            replicas, self._replicas = list(self._replicas), []
        for r in replicas:
            try:
                r.close()
            except Exception:
                pass


class ServingFleet:
    """The client-facing fleet: routed dispatch + overload policy.

    >>> fleet = ServingFleet({"net": {"dense": {}}, "shapes": [(8,)]},
    ...                      replicas=3)
    >>> fut = fleet.submit(x, priority="interactive")
    >>> y = fut.result(timeout=5.0)
    """

    def __init__(self, spec, *, name="model", replicas=None, process=False,
                 hedge_ms=None, retries=None, depth_feed=None,
                 heartbeat_s=None, suspect_misses=None,
                 brownout_enter=None, brownout_exit=None,
                 brownout_hold_s=None, autostart_heartbeat=True):
        self.name = str(name)
        self._enter = fleet_brownout_enter() if brownout_enter is None \
            else float(brownout_enter)
        self._exit = fleet_brownout_exit() if brownout_exit is None \
            else float(brownout_exit)
        self._enter2 = min(0.98, self._enter + 0.10)
        self._hold_s = fleet_brownout_hold_s() if brownout_hold_s is None \
            else float(brownout_hold_s)
        if not (self._exit < self._enter):
            raise MXNetError(
                f"brownout exit threshold ({self._exit}) must sit below "
                f"enter ({self._enter}) — hysteresis needs a gap")
        self._brownout = 0        # latched level 0|1|2
        self._drain_since = None  # when frac first dipped below exit
        self._bo_lock = threading.Lock()
        self._GUARDED_BY = {"_brownout": "_bo_lock",
                            "_drain_since": "_bo_lock"}
        self._deaths = []         # (replica, reason) pending for autoscaler
        self._death_lock = threading.Lock()
        self._last_death_mono = None
        self._last_recovery_s = None
        self._last_submit_mono = time.monotonic()
        self._set = ReplicaSet(
            spec, name=name, replicas=replicas, process=process,
            heartbeat_s=heartbeat_s, suspect_misses=suspect_misses,
            on_death=self._death_event, autostart=autostart_heartbeat)
        self._router = ReplicaRouter(
            self._set.live, model=name, retries=retries, hedge_ms=hedge_ms,
            depth_feed=depth_feed, on_death=self._router_death)

    # -- death bookkeeping -------------------------------------------------
    def _death_event(self, replica, reason):
        self._last_death_mono = replica.death_mono or time.monotonic()
        with self._death_lock:
            self._deaths.append((replica, reason))

    def _router_death(self, replica, error):
        # request-level failure IS a health signal: skip the miss ladder
        self._set.mark_dead(replica, reason="request")

    def drain_deaths(self):
        """Hand pending death events to the autoscaler (drains)."""
        with self._death_lock:
            deaths, self._deaths = self._deaths, []
        return deaths

    # -- load signals ------------------------------------------------------
    def queue_fraction(self) -> float:
        """Aggregate fleet load: sum of live queue depths over total
        live capacity (0.0 when nothing is live)."""
        live = self._set.live()
        if not live:
            return 0.0
        cap = self._set.queue_cap() * len(live)
        depth = 0
        for r in live:
            try:
                depth += r.queue_depth()
            except Exception:
                pass
        return min(1.0, depth / float(cap)) if cap else 0.0

    def p99_ms(self):
        return self._router.p99_ms()

    def idle_seconds(self) -> float:
        return time.monotonic() - self._last_submit_mono

    @property
    def last_recovery_s(self):
        """Detection->replacement latency of the most recent recovered
        replica death (the bench's ``recovery_s``)."""
        return self._last_recovery_s

    def note_recovery(self, seconds):
        self._last_recovery_s = float(seconds)
        if _obs.ENABLED:
            _obs.FLEET_RECOVERY_SECONDS.set(float(seconds),
                                            model=self.name)

    # -- degraded mode -----------------------------------------------------
    def brownout_level(self) -> int:
        with self._bo_lock:
            return self._brownout

    def _evaluate_brownout(self, frac, now):
        """The latched state machine (deterministic test seam): step UP
        immediately on threshold crossings, step DOWN one level per
        sustained-drain hold window."""
        with self._bo_lock:
            prev = self._brownout
            if frac >= self._enter2:
                self._brownout = 2
            elif frac >= self._enter:
                self._brownout = max(self._brownout, 1)
            if self._brownout > 0:
                if frac < self._exit:
                    if self._drain_since is None:
                        self._drain_since = now
                    elif now - self._drain_since >= self._hold_s:
                        self._brownout -= 1
                        self._drain_since = now if self._brownout else None
                else:
                    self._drain_since = None
            level = self._brownout
        if level != prev and _obs.ENABLED:
            _obs.record_fleet_brownout(self.name, level, prev)
        return level

    def _admit(self, priority) -> bool:
        level = self.brownout_level()
        if level >= 2:
            return priority == "critical"
        if level >= 1:
            return priority != "bulk"
        return True

    # -- client surface ----------------------------------------------------
    def submit(self, x, priority="interactive", key=None, **kwargs):
        """Dispatch one request at a priority class; raises typed
        :class:`BrownoutShed` under degraded mode, fails over replica
        death internally, and restores from the warm pool when the
        fleet was scaled to zero."""
        if priority not in PRIORITIES:
            raise MXNetError(
                f"unknown priority {priority!r}; want one of {PRIORITIES}")
        self._last_submit_mono = time.monotonic()
        # chaos: kill_replica@fleet fires HERE, mid-traffic
        if _chaos.ENABLED:
            victim = _chaos.kill_replica_due("fleet")
            if victim is not None:
                self.kill_replica(victim)
        if not self._set.live() and self._set.warm():
            self._set.restore()  # scale-from-zero on demand, not an error
            if _obs.ENABLED:
                _obs.record_fleet_autoscale(self.name, "restore",
                                            self._set.n_live())
        level = self._evaluate_brownout(self.queue_fraction(),
                                        time.monotonic())
        if not self._admit(priority):
            if _obs.ENABLED:
                _obs.FLEET_SHED_TOTAL.inc(1, model=self.name, priority=priority)
            raise BrownoutShed(
                f"fleet {self.name!r} is in brownout level {level}: "
                f"priority class {priority!r} is being shed (retry with "
                "backoff, or escalate the request's priority)")
        return self._router.submit(x, key=key, **kwargs)

    def predict(self, x, timeout=None, priority="interactive", key=None,
                **kwargs):
        return self.submit(x, priority=priority, key=key,
                           **kwargs).result(timeout)

    def kill_replica(self, index):
        """Kill the live replica at ``index`` (chaos actuation / manual
        drill). Safe when the index is gone already."""
        for r in self._set.live():
            if r.index == int(index) or int(index) < 0:
                self._set.mark_dead(r, reason="chaos")
                return r
        return None

    # -- delegation --------------------------------------------------------
    @property
    def replica_set(self) -> ReplicaSet:
        return self._set

    @property
    def router(self) -> ReplicaRouter:
        return self._router

    def n_live(self) -> int:
        return self._set.n_live()

    def swap(self, spec):
        return self._set.swap(spec)

    def stats(self) -> dict:
        return {
            "replicas": self._set.census(),
            "brownout": self.brownout_level(),
            "queue_fraction": self.queue_fraction(),
            "p99_ms": self.p99_ms(),
            "last_recovery_s": self._last_recovery_s,
        }

    def close(self):
        self._set.close()
