"""Paged KV cache: fixed-size blocks in one preallocated device pool,
per-request block tables (vLLM/PagedAttention-style).

The decode batch packs requests of wildly different lengths into one
dispatch, so per-request contiguous KV buffers would fragment device
memory and force reallocation every time a sequence grows. Instead the
cache owns ONE pool per projection, shaped

    ``(layers, num_blocks, block_size, kv_heads, head_dim)``

and every request holds a :class:`BlockTable` — the list of pool block
ids that back its tokens, in order. Growing a sequence is appending a
block id to a host-side list; no device copy, no reallocation, zero
external fragmentation (internal waste is bounded by one partial block
per sequence). Block 0 is reserved as the NULL block: in-graph writes
for inactive batch slots are routed there, so the compiled decode step
never branches on slot liveness — dead slots scatter into a sink that
nothing ever reads.

Allocation is a free-list with per-block refcounts. ``fork()`` shares
a prefix between sequences by bumping refcounts (O(blocks) host ints,
no device traffic) — copy-on-write triggers only when a writer must
append into a shared partial block, and copies exactly that one block.

The pool arrays are FUNCTIONAL values threaded through the compiled
prefill/decode executables (donated in, returned out); the cache
object carries the current arrays between dispatches plus the host
allocator state. Everything device-side (gather/scatter through the
table) lives in the pure helpers at the bottom so the decode model and
the tests target the same code.

Knobs: ``MXTPU_KVCACHE_BLOCKS`` (pool size), ``MXTPU_KVCACHE_BLOCK_SIZE``
(tokens per block). Gauges: ``mxtpu_kvcache_blocks_used`` /
``mxtpu_kvcache_occupancy_ratio``; counters ``mxtpu_kvcache_forks_total``
/ ``mxtpu_kvcache_oom_total`` (docs/observability.md).
"""

from __future__ import annotations

import threading

import numpy as np

from .. import base
from .. import observability as _obs
from .errors import KVCacheOOM


def kvcache_blocks() -> int:
    """Pool capacity in blocks (``MXTPU_KVCACHE_BLOCKS``, default 512).
    Block 0 is the reserved null sink, so usable capacity is one less.
    Sizing rule: ``blocks ~= slots * ceil(max_seq / block_size)`` plus
    headroom for forks; the allocator sheds (typed
    :class:`~.errors.KVCacheOOM`) rather than oversubscribe."""
    return max(2, base.getenv("MXTPU_KVCACHE_BLOCKS", 512, dtype=int))


def kvcache_block_size() -> int:
    """Tokens per cache block (``MXTPU_KVCACHE_BLOCK_SIZE``, default
    16). Larger blocks cut table-indirection overhead but raise
    internal waste (one partial block per sequence) and make
    copy-on-write forks copy more."""
    return max(1, base.getenv("MXTPU_KVCACHE_BLOCK_SIZE", 16, dtype=int))


class BlockTable:
    """One sequence's view into the pool: ordered block ids + how many
    tokens are written. Host-side bookkeeping only — the device sees a
    padded ``int32`` row (:meth:`device_row`) with the null block in
    unused slots."""

    __slots__ = ("blocks", "length")

    def __init__(self, blocks=None, length=0):
        self.blocks = list(blocks or [])
        self.length = int(length)

    def __repr__(self):
        return f"BlockTable(blocks={self.blocks}, length={self.length})"

    def device_row(self, max_blocks: int) -> np.ndarray:
        """Padded ``int32`` row for the decode batch's table operand —
        unused entries point at the null block (id 0)."""
        row = np.zeros((int(max_blocks),), dtype=np.int32)
        n = min(len(self.blocks), int(max_blocks))
        row[:n] = self.blocks[:n]
        return row


class PagedKVCache:
    """Device block pool + host free-list allocator (thread-safe).

    >>> cache = PagedKVCache(layers=2, kv_heads=2, head_dim=8,
    ...                      max_seq=128)
    >>> t = cache.allocate(17)          # ceil(17/16) = 2 blocks
    >>> child = cache.fork(t)           # refcount bump, no copy
    >>> cache.ensure(child, 18)         # COW copies ONE shared block
    >>> cache.release(t); cache.release(child)
    """

    # machine-checked lock protocol (mxtpu-lint thread-guard rule)
    _GUARDED_BY = {
        "_free": "_lock",
        "_ref": "_lock",
    }

    def __init__(self, layers, kv_heads, head_dim, *, max_seq=None,
                 num_blocks=None, block_size=None, dtype="float32",
                 name="model"):
        import jax.numpy as jnp

        self.layers = int(layers)
        self.kv_heads = int(kv_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size or kvcache_block_size())
        self.num_blocks = int(num_blocks or kvcache_blocks())
        if self.num_blocks < 2:
            raise ValueError("PagedKVCache needs >= 2 blocks "
                             "(block 0 is the reserved null sink)")
        self.name = str(name)
        self._dtype = np.dtype(dtype)
        self.max_blocks_per_seq = (
            -(-int(max_seq) // self.block_size) if max_seq
            else self.num_blocks - 1)
        shape = (self.layers, self.num_blocks, self.block_size,
                 self.kv_heads, self.head_dim)
        self.k_pool = jnp.zeros(shape, dtype=self._dtype)
        self.v_pool = jnp.zeros(shape, dtype=self._dtype)
        self._lock = threading.Lock()
        self._free = list(range(self.num_blocks - 1, 0, -1))  # pop() -> 1
        self._ref = np.zeros((self.num_blocks,), dtype=np.int64)
        self._ref[0] = 1  # the null block is permanently resident
        self.forks = 0
        self.cow_copies = 0

    # -- pool threading ----------------------------------------------------
    def pools(self):
        """Current ``(k_pool, v_pool)`` device arrays — the operands to
        hand the next prefill/decode dispatch (which donates them)."""
        return self.k_pool, self.v_pool

    def update_pools(self, k_pool, v_pool):
        """Adopt the pool arrays a dispatch returned (the donated
        inputs are dead after the call — this is the hand-over)."""
        self.k_pool, self.v_pool = k_pool, v_pool

    # -- allocator ---------------------------------------------------------
    def _blocks_for(self, num_tokens: int) -> int:
        return -(-max(0, int(num_tokens)) // self.block_size)

    def _take(self, n: int):
        """Pop ``n`` free blocks (caller holds ``_lock``); raises typed
        OOM without mutating anything when the pool can't supply them."""
        if n > len(self._free):
            if _obs.ENABLED:
                _obs.KVCACHE_OOM_TOTAL.inc(1, model=self.name)
            raise KVCacheOOM(
                f"KV cache pool exhausted: need {n} block(s), "
                f"{len(self._free)} free of {self.num_blocks - 1} usable "
                f"(MXTPU_KVCACHE_BLOCKS={self.num_blocks}, "
                f"block_size={self.block_size})")
        return [self._free.pop() for _ in range(n)]

    def allocate(self, num_tokens: int) -> BlockTable:
        """Blocks for a fresh sequence of ``num_tokens`` tokens."""
        n = self._blocks_for(num_tokens)
        with self._lock:
            blocks = self._take(n)
            for b in blocks:
                self._ref[b] = 1
        self._gauges()
        return BlockTable(blocks, 0)

    def ensure(self, table: BlockTable, num_tokens: int):
        """Grow ``table`` to cover ``num_tokens`` tokens, triggering
        copy-on-write first if new tokens would land in a shared
        partial block. Returns the table."""
        need = self._blocks_for(num_tokens) - len(table.blocks)
        will_append = num_tokens > table.length
        copy = None
        with self._lock:
            if (will_append and table.blocks
                    and table.length % self.block_size != 0
                    and self._ref[table.blocks[-1]] > 1):
                # COW: the writer gets a private copy of the one shared
                # partial block; readers keep the original.
                (dst,) = self._take(1)
                self._ref[dst] = 1
                src = table.blocks[-1]
                self._ref[src] -= 1
                table.blocks[-1] = dst
                copy = (src, dst)
            if need > 0:
                grown = self._take(need)
                for b in grown:
                    self._ref[b] = 1
                table.blocks.extend(grown)
        if copy is not None:
            self._copy_block(*copy)
            self.cow_copies += 1
        self._gauges()
        return table

    def fork(self, table: BlockTable) -> BlockTable:
        """Share ``table``'s prefix with a new sequence: refcount bump
        only — no device traffic until a writer appends into the shared
        partial block (then exactly that block is copied)."""
        with self._lock:
            for b in table.blocks:
                self._ref[b] += 1
        self.forks += 1
        if _obs.ENABLED:
            _obs.KVCACHE_FORKS_TOTAL.inc(1, model=self.name)
        return BlockTable(list(table.blocks), table.length)

    def release(self, table: BlockTable):
        """Return the table's blocks (refcounted — a block frees only
        when its last holder releases). Idempotent per table."""
        blocks, table.blocks, table.length = table.blocks, [], 0
        with self._lock:
            for b in blocks:
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    self._free.append(b)
        self._gauges()

    def _copy_block(self, src: int, dst: int):
        """Device-copy one block (all layers, K and V) — the COW path.
        One fused dispatch pair per copy; copies are rare (only shared
        partial blocks on first divergence)."""
        self.k_pool = self.k_pool.at[:, dst].set(self.k_pool[:, src])
        self.v_pool = self.v_pool.at[:, dst].set(self.v_pool[:, src])

    # -- accounting --------------------------------------------------------
    def blocks_used(self) -> int:
        with self._lock:
            return self.num_blocks - 1 - len(self._free)

    def blocks_free(self) -> int:
        with self._lock:
            return len(self._free)

    def occupancy(self) -> float:
        usable = max(1, self.num_blocks - 1)
        return self.blocks_used() / usable

    def can_allocate(self, num_tokens: int) -> bool:
        """Admission check: could a fresh sequence of this length be
        backed right now? (Advisory — allocate() stays the authority.)"""
        with self._lock:
            return self._blocks_for(num_tokens) <= len(self._free)

    def _gauges(self):
        if _obs.ENABLED:
            used = self.blocks_used()
            _obs.KVCACHE_BLOCKS_USED.set(used, model=self.name)
            _obs.KVCACHE_OCCUPANCY.set(
                used / max(1, self.num_blocks - 1), model=self.name)

    def stats(self) -> dict:
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "blocks_used": self.blocks_used(),
            "occupancy": self.occupancy(),
            "forks": self.forks,
            "cow_copies": self.cow_copies,
        }


# ---------------------------------------------------------------------------
# pure in-graph helpers (used under jit by the decode model AND the tests —
# one implementation of the table indirection, exercised from both sides)
# ---------------------------------------------------------------------------

def slot_coords(tables, pos, block_size, active=None):
    """``(block_id, offset)`` pool coordinates for writing each batch
    slot's token at position ``pos``. ``tables`` is ``(B, max_blocks)``
    int32, ``pos`` is ``(B,)`` int32. Inactive slots are routed to the
    null block (id 0) so the compiled step is branch-free in liveness.
    """
    import jax.numpy as jnp

    idx = jnp.clip(pos // block_size, 0, tables.shape[1] - 1)
    blk = jnp.take_along_axis(tables, idx[:, None], axis=1)[:, 0]
    off = pos % block_size
    if active is not None:
        blk = jnp.where(active, blk, 0)
    return blk.astype(jnp.int32), off.astype(jnp.int32)


def paged_write(pool_layer, blk, off, values):
    """Scatter one token's K (or V) per batch slot into a single
    layer's pool slice ``(num_blocks, block_size, kv_heads, head_dim)``.
    ``values`` is ``(B, kv_heads, head_dim)``."""
    return pool_layer.at[blk, off].set(values)


def paged_prefill_write(pool_layer, table_row, length, values):
    """Scatter a whole prompt's K (or V) into one layer's pool slice.
    ``table_row`` ``(max_blocks,)`` int32, ``values`` ``(T, kv_heads,
    head_dim)``; positions ``>= length`` (bucket padding) go to the
    null block."""
    import jax.numpy as jnp

    t = values.shape[0]
    pos = jnp.arange(t, dtype=jnp.int32)
    block_size = pool_layer.shape[1]
    idx = jnp.clip(pos // block_size, 0, table_row.shape[0] - 1)
    blk = jnp.where(pos < length, table_row[idx], 0)
    off = pos % block_size
    return pool_layer.at[blk, off].set(values)


def paged_gather(pool_layer, tables):
    """Gather each slot's K (or V) context from one layer's pool slice
    through its block table: ``(B, max_blocks * block_size, kv_heads,
    head_dim)``. Padding rows gather the null block — callers mask by
    context length."""
    b, mb = tables.shape
    g = pool_layer[tables]  # (B, max_blocks, block_size, KVH, D)
    return g.reshape(b, mb * pool_layer.shape[1],
                     pool_layer.shape[2], pool_layer.shape[3])
