"""ModelRepository: multiple named+versioned models on one device,
live swap/rollback with the PR-8 commit protocol applied in-memory.

``resilience.checkpoint.atomic_replace`` commits a checkpoint as
write-to-tmp -> verify -> atomic rename; a model swap is the same
shape with the filesystem swapped for a pointer:

  stage   build the new engine OFF to the side (AOT compile + warmup +
          canary verification) while the live version keeps serving;
  flip    one pointer assignment under the repository lock — the
          indivisible "rename". Requests that already captured the old
          engine finish on it; new submits land on the new one;
  drain   the old engine stops accepting work and completes its
          in-flight requests (``pause()``), then parks as a standby
          (weights resident) inside the keep window — ``rollback()``
          is a pointer flip back + ``resume()``, not a recompile;
  release standbys beyond the keep window close fully (executables and
          weight references dropped).

A corrupt/failed staged load NEVER becomes visible: any exception
during build/warmup/verify discards the stage and raises
:class:`StagedLoadError` while the previous version keeps answering —
the serving analog of "a torn checkpoint never gets the rename".
"""

from __future__ import annotations

import threading

import numpy as _np

from .. import observability as _obs
from .engine import InferenceEngine
from .errors import EngineClosed, ServingError, StagedLoadError


def _default_verify(engine):
    """Canary: one zero-filled row through every bucket, results must
    be finite. Catches NaN/garbage weights before the flip. Engines
    exposing their own ``canary()`` (GenerationEngine: a short greedy
    generation must stay in-vocabulary) delegate to it."""
    if hasattr(engine, "canary"):
        engine.canary()
        return
    for bucket in engine.buckets:
        out = engine.predict(_np.zeros(tuple(bucket), engine._dtype),
                             timeout=30.0)
        for leaf in (out if isinstance(out, tuple) else (out,)):
            if not _np.all(_np.isfinite(leaf)):
                raise ServingError(
                    f"canary produced non-finite outputs on bucket "
                    f"{bucket} — refusing to serve this version")


class ModelRepository:
    """Host many models; swap versions live; roll back instantly.

    >>> repo = ModelRepository()
    >>> repo.load("clf", net_v1, shapes=[(16,)], version="v1")
    >>> repo.predict("clf", x)
    >>> repo.load("clf", net_v2_int8, shapes=[(16,)], version="v2")
    >>> repo.rollback("clf")          # v1 again, no recompile

    ``keep``: standby versions retained per model for rollback
    (default 1 — the previous version).
    """

    def __init__(self, keep=1):
        self._keep = max(0, int(keep))
        self._lock = threading.Lock()
        self._models = {}  # name -> {"live": engine, "standby": [engines]}

    # -- staged load + atomic flip ----------------------------------------
    def load(self, name, net_or_factory, shapes, *, version=None,
             verify=None, **engine_kwargs):
        """Stage -> verify -> flip. Returns the new live engine.

        ``net_or_factory``: a block (HybridBlock / QuantizedNet), a
        decode-capable net (``decode_step_fn`` — served by a
        :class:`~.generation.GenerationEngine` instead), or a zero-arg
        callable building one (the factory runs inside the stage, so a
        crash there also never touches the live version).
        ``verify``: optional callable(engine) raising to veto; the
        default canary checks finite outputs on every bucket (greedy
        in-vocabulary generation for generation engines)."""
        with self._lock:
            prev = (self._models.get(name) or {}).get("live")
        if version is None:
            version = f"v{self._version_seq(name) + 1}"
        engine = None
        try:
            net = net_or_factory() if callable(net_or_factory) \
                and not hasattr(net_or_factory, "aot_predict_fn") \
                and not hasattr(net_or_factory, "decode_step_fn") \
                else net_or_factory
            if hasattr(net, "decode_step_fn"):
                from .generation import GenerationEngine as _cls
            else:
                _cls = InferenceEngine
            engine = _cls(net, shapes, name=name,
                          version=version, **engine_kwargs)
            (verify or _default_verify)(engine)
        except BaseException as e:
            if engine is not None:
                engine.close()
            if _obs.ENABLED:
                _obs.record_serve_swap(
                    name, "aborted", version=version,
                    prev_version=prev.version if prev else None)
            raise StagedLoadError(
                f"staged load of {name}:{version} failed and was "
                f"discarded ({type(e).__name__}: {e}); "
                f"{'version ' + prev.version + ' keeps serving' if prev else 'no version is live'}"
            ) from e
        # the atomic "rename": one pointer flip under the lock
        with self._lock:
            entry = self._models.setdefault(name,
                                            {"live": None, "standby": []})
            prev = entry["live"]
            entry["live"] = engine
            if prev is not None:
                entry["standby"].append(prev)
            trim = entry["standby"][:-self._keep] if self._keep \
                else list(entry["standby"])
            entry["standby"] = entry["standby"][len(trim):]
        # outside the lock: drain the old version, release beyond keep
        if prev is not None:
            prev.pause()  # drain in-flight, weights stay for rollback
        for old in trim:
            old.close()  # released: executables + weights dropped
        if _obs.ENABLED:
            _obs.record_serve_swap(
                name, "committed", version=version,
                prev_version=prev.version if prev else None)
            _obs.SERVE_LIVE_MODELS.set(self._live_count())
        return engine

    def _version_seq(self, name) -> int:
        with self._lock:
            entry = self._models.get(name)
            if not entry:
                return 0
            return len(entry["standby"]) + (1 if entry["live"] else 0)

    def _live_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._models.values() if e["live"])

    # -- rollback ----------------------------------------------------------
    def rollback(self, name):
        """Flip back to the most recent standby version (drains the
        version being demoted; it becomes the standby, so rolling
        forward again is another ``rollback``)."""
        with self._lock:
            entry = self._models.get(name)
            if not entry or not entry["standby"]:
                raise ServingError(
                    f"no standby version of {name!r} to roll back to")
            demoted = entry["live"]
            restored = entry["standby"].pop()
            restored.resume()
            entry["live"] = restored
            if demoted is not None:
                entry["standby"].append(demoted)
        if demoted is not None:
            demoted.pause()
        if _obs.ENABLED:
            _obs.record_serve_swap(
                name, "rolled_back", version=restored.version,
                prev_version=demoted.version if demoted else None)
        return restored

    # -- request routing ---------------------------------------------------
    def engine(self, name) -> InferenceEngine:
        with self._lock:
            entry = self._models.get(name)
            live = entry["live"] if entry else None
        if live is None:
            raise ServingError(f"no live version of model {name!r}")
        return live

    def live_version(self, name):
        """Version string of the live engine (None when nothing is
        live) — the fleet's zero-stale-version assertions read this."""
        with self._lock:
            entry = self._models.get(name)
            live = entry["live"] if entry else None
        return live.version if live is not None else None

    def submit(self, name, x, **kwargs):
        """Submit to the CURRENT live version. A swap between the
        pointer read and the submit is retried onto the new version, so
        continuous traffic across a swap never fails spuriously — each
        request is answered by exactly one coherent version."""
        for _ in range(8):
            engine = self.engine(name)
            try:
                return engine.submit(x, **kwargs)
            except EngineClosed:
                with self._lock:
                    entry = self._models.get(name)
                    still_live = entry and entry["live"] is engine
                if still_live:
                    raise  # genuinely closed, not a swap race
        raise ServingError(
            f"model {name!r} kept swapping during submit; giving up")

    def predict(self, name, x, timeout=None, **kwargs):
        return self.submit(name, x, **kwargs).result(timeout)

    # -- inventory ---------------------------------------------------------
    def models(self) -> dict:
        """{name: {"live": version|None, "standby": [versions...]}}"""
        with self._lock:
            return {
                name: {
                    "live": e["live"].version if e["live"] else None,
                    "standby": [s.version for s in e["standby"]],
                }
                for name, e in self._models.items()
            }

    def stats(self, name) -> dict:
        return self.engine(name).stats()

    def unload(self, name):
        """Drain and fully release every version of ``name``."""
        with self._lock:
            entry = self._models.pop(name, None)
        if entry is None:
            return
        for eng in [entry["live"]] + entry["standby"]:
            if eng is not None:
                eng.close()
        if _obs.ENABLED:
            _obs.SERVE_LIVE_MODELS.set(self._live_count())

    def close(self):
        """Unload everything (idempotent)."""
        with self._lock:
            names = list(self._models)
        for name in names:
            self.unload(name)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
