"""``mxnet_tpu.serving`` — production inference serving.

The inference half of the north star (the role MXNet 1.x's C predict
API + model-server heritage played), built on the training stack's own
primitives:

- :class:`InferenceEngine` — AOT shape-bucket executables
  (``jax.jit(...).lower().compile()`` at deploy time, warmed through
  ``MXTPU_COMPILE_CACHE``), sealed with a hard no-retrace contract, fed
  by a continuous-batching scheduler (``SequenceBucketer`` selection +
  ``pad_batch`` fill, per-request deadlines, bounded-queue load shed);
- :class:`ModelRepository` — many named+versioned models on one
  device; staged load -> warmup -> atomic pointer flip (the PR-8
  checkpoint commit protocol in-memory), drain, instant rollback;
- serving SLOs on the observability registry (p50/p99 latency,
  batch-fill, queue depth, shed/timeout counters — scrapeable via
  ``observability.serve_metrics``; ``tools/telemetry_report.py`` has a
  Serving section).

Knobs: ``MXTPU_SERVE_MAX_BATCH`` / ``MXTPU_SERVE_MAX_WAIT_MS`` /
``MXTPU_SERVE_QUEUE`` (docs/env_vars.md); recipe: docs/serving.md.
"""

from __future__ import annotations

from .batcher import ContinuousBatcher, ServeFuture  # noqa: F401
from .engine import (  # noqa: F401
    InferenceEngine,
    serve_max_batch,
    serve_max_wait_ms,
    serve_queue_cap,
)
from .errors import (  # noqa: F401
    EngineClosed,
    RequestTimeout,
    RequestTooLarge,
    RetraceForbidden,
    ServerOverloaded,
    ServingError,
    StagedLoadError,
)
from .repository import ModelRepository  # noqa: F401
