"""``mxnet_tpu.serving`` — production inference serving.

The inference half of the north star (the role MXNet 1.x's C predict
API + model-server heritage played), built on the training stack's own
primitives:

- :class:`InferenceEngine` — AOT shape-bucket executables
  (``jax.jit(...).lower().compile()`` at deploy time, warmed through
  ``MXTPU_COMPILE_CACHE``), sealed with a hard no-retrace contract, fed
  by a continuous-batching scheduler (``SequenceBucketer`` selection +
  ``pad_batch`` fill, per-request deadlines, bounded-queue load shed);
- :class:`ModelRepository` — many named+versioned models on one
  device; staged load -> warmup -> atomic pointer flip (the PR-8
  checkpoint commit protocol in-memory), drain, instant rollback;
- serving SLOs on the observability registry (p50/p99 latency,
  batch-fill, queue depth, shed/timeout counters — scrapeable via
  ``observability.serve_metrics``; ``tools/telemetry_report.py`` has a
  Serving section);
- the autoregressive decode fast path — :class:`GenerationEngine`
  (token-level continuous batching: one sealed chunk-of-T decode
  executable with on-device sampling; requests join/leave between
  chunks) over :class:`PagedKVCache` (block-table paged K/V pool with
  free-list allocation and copy-on-fork shared prefixes), with
  :class:`TransformerDecoderLM` as the reference decode-capable net;
- the self-healing fleet layer — :class:`ServingFleet` /
  :class:`ReplicaSet` (replicas across processes/hosts behind one
  :class:`ReplicaRouter` with least-queue-depth dispatch, typed
  failover and optional hedging), :class:`SLOAutoscaler` (watchdog +
  SLO signals actuated through the PR-11 membership bus: grow, shrink,
  scale-to-zero with warm-pool restore, cooldown-exempt replacement of
  dead replicas), and the latched brownout degraded mode (``bulk``
  sheds before ``interactive`` before ``critical``).

Knobs: ``MXTPU_SERVE_MAX_BATCH`` / ``MXTPU_SERVE_MAX_WAIT_MS`` /
``MXTPU_SERVE_QUEUE`` + the ``MXTPU_FLEET_*`` family
(docs/env_vars.md); recipes: docs/serving.md, docs/robustness.md.
"""

from __future__ import annotations

from .batcher import ContinuousBatcher, ServeFuture  # noqa: F401
from .engine import (  # noqa: F401
    InferenceEngine,
    serve_max_batch,
    serve_max_wait_ms,
    serve_queue_cap,
)
from .errors import (  # noqa: F401
    BrownoutShed,
    EngineClosed,
    KVCacheOOM,
    ReplicaDead,
    ReplicaLost,
    RequestCancelled,
    RequestTimeout,
    RequestTooLarge,
    RetraceForbidden,
    ServerOverloaded,
    ServingError,
    StagedLoadError,
)
from .kvcache import (  # noqa: F401
    BlockTable,
    PagedKVCache,
    kvcache_block_size,
    kvcache_blocks,
)
from .decoder import TransformerDecoderLM  # noqa: F401
from .generation import (  # noqa: F401
    GenerateFuture,
    GenerationEngine,
    decode_chunk,
    decode_max_new,
    decode_slots,
    sample_tokens,
)
from .repository import ModelRepository  # noqa: F401
from .replica import LocalReplica, ProcessReplica  # noqa: F401
from .router import (  # noqa: F401
    FleetFuture,
    ReplicaRouter,
    federation_depth_feed,
    fleet_hedge_ms,
    fleet_retries,
)
from .fleet import (  # noqa: F401
    PRIORITIES,
    ReplicaSet,
    ServingFleet,
    fleet_brownout_enter,
    fleet_brownout_exit,
    fleet_brownout_hold_s,
    fleet_heartbeat_s,
    fleet_max_replicas,
    fleet_min_replicas,
    fleet_replicas,
    fleet_suspect_misses,
)
from .autoscaler import SLOAutoscaler, fleet_cooldown_s, fleet_slo_p99_ms  # noqa: F401
