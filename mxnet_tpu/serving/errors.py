"""Typed serving errors — every way a request can fail has its own
class, so front-ends map outcomes to response codes by type (load shed
-> 503, deadline -> 504, refused shape -> 400) instead of parsing
message strings. All subclass :class:`~mxnet_tpu.base.MXNetError`.
"""

from __future__ import annotations

from ..base import MXNetError


class ServingError(MXNetError):
    """Base class for every serving-layer failure."""


class ServerOverloaded(ServingError):
    """Load shed: the bounded request queue was full at submit time
    (backpressure — the client should retry with backoff or reroute).
    The request was REJECTED, never partially processed."""


class RequestTimeout(ServingError):
    """The request's deadline expired before its batch dispatched.
    Typed — a deadline miss is never answered with a stale result."""


class RequestTooLarge(ServingError):
    """A single request carries more rows than ``max_batch`` — it can
    never fit in one dispatch. Split it client-side (the engine never
    splits implicitly: partial results are not a thing)."""


class EngineClosed(ServingError):
    """Submit after ``close()`` (or to a paused standby version).
    In-flight requests at close time still complete — only NEW work is
    refused."""


class RetraceForbidden(ServingError):
    """The sealed engine refused an input signature with no AOT
    executable (retrace budget is 0 after warmup). The message names
    the cause (shape/dtype/arity — ``gluon.block.signature_causes``)
    and the known buckets; fix the client or add a bucket and
    redeploy."""


class StagedLoadError(ServingError):
    """A staged model load failed build/warmup/verification. The stage
    was discarded — the previous live version never stopped serving."""


class RequestCancelled(ServingError):
    """The client cancelled a still-queued request (``ServeFuture.
    cancel()``). The request was never dispatched — its queue slot is
    reclaimed at the next drain and no compute was spent on it. A
    request that already entered batch assembly can NOT be cancelled
    (cancel() returns False); exactly one of {dispatch, cancel} wins."""


class ReplicaDead(ServingError):
    """ONE replica died with this request on it (host kill, broken
    pipe, heartbeat death). An internal routing signal: the fleet
    router catches it and retries the request on a surviving replica —
    fleet callers only ever see :class:`ReplicaLost`, and only when
    every candidate failed."""


class ReplicaLost(ServingError):
    """Fleet-level terminal failure: EVERY candidate replica was tried
    (at most once each) and all failed with a replica-death class error.
    Raised only after the router's retry-with-backoff is exhausted —
    a single host kill never surfaces this while a survivor exists."""


class KVCacheOOM(ServerOverloaded):
    """The paged KV cache's block pool could not supply the blocks a
    generation request needs (admission reservation or mid-decode
    growth). Subclasses :class:`ServerOverloaded` — the request was
    refused (or retired early with the tokens produced so far), never
    left holding a partially-backed cache; the client should retry
    after other sequences complete or the pool is resized
    (``MXTPU_KVCACHE_BLOCKS``)."""


class BrownoutShed(ServerOverloaded):
    """Degraded-mode load shed: the fleet's latched brownout state
    machine refused this request's priority class (``bulk`` sheds
    before ``interactive`` before ``critical``). Subclasses
    :class:`ServerOverloaded` so existing 503 mappings apply, but typed
    so clients can tell policy shedding from a full queue."""
