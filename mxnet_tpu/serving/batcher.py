"""Continuous batching: a background scheduler thread drains an async
request queue into shape-stable batches.

The serving mirror of ``gluon/data/prefetcher.py`` — same thread +
bounded-``queue.Queue`` shape, same error/close contract (exceptions
propagate to the waiter, ``close()`` is idempotent, drains, and joins;
``__del__`` is safe) — but demand-driven: requests arrive one at a
time from many client threads, and the scheduler groups them by shape
bucket, dispatching a group when it FILLS (``max_batch`` rows) or when
its oldest request has waited ``max_wait`` (tail-latency bound),
whichever comes first. Per-request deadlines are enforced HERE, before
dispatch: an expired request gets a typed :class:`RequestTimeout`, its
slot goes to the next request — never a stale result.

Backpressure is the bounded submit queue: when it is full, ``submit``
raises :class:`ServerOverloaded` immediately (load shed) instead of
queueing unbounded work the deadline would kill anyway.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time

from .errors import EngineClosed, ServerOverloaded
from .. import observability as _obs

#: queue sentinel: close() enqueues it BEHIND already-accepted requests,
#: so the drain processes everything admitted before the close.
_CLOSE = object()

#: process-unique request ids, minted at construction (itertools.count
#: is GIL-atomic) — the correlation key the trace spans thread through
#: queue-wait -> batch-assembly -> dispatch -> slice-out
_REQ_IDS = itertools.count(1)


class _Request:
    """One in-flight request: host payload rows (already padded onto
    their bucket's row shape), terminal result/error, and the wait
    event its :class:`ServeFuture` blocks on."""

    __slots__ = ("payload", "rows", "bucket", "t_submit", "deadline",
                 "event", "result", "error", "version", "req_id",
                 "t_assembly", "claimed", "cancelled", "_state_lock")

    def __init__(self, payload, rows, bucket, deadline=None):
        self.payload = payload
        self.rows = rows
        self.bucket = bucket
        self.t_submit = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter time, or None
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.version = None
        self.req_id = next(_REQ_IDS)
        self.t_assembly = None  # stamped when batch assembly picks it up
        # claim/cancel CAS: exactly one of {batch assembly, client
        # cancel} wins a queued request; the loser sees False
        self.claimed = False
        self.cancelled = False
        self._state_lock = threading.Lock()

    def finish(self, result=None, error=None):
        self.result = result
        self.error = error
        self.event.set()

    def claim(self) -> bool:
        """Batch assembly takes ownership: False iff the client already
        cancelled (or the request is otherwise terminal) — the entry is
        skipped at drain time, its slot going to the next request."""
        with self._state_lock:
            if self.cancelled or self.event.is_set():
                return False
            self.claimed = True
            return True

    def cancel(self) -> bool:
        """Client-side withdrawal: wins only while still queued (never
        claimed by assembly, not yet terminal). On success the request
        finishes with a typed :class:`RequestCancelled`."""
        from .errors import RequestCancelled

        with self._state_lock:
            if self.claimed or self.event.is_set():
                return False
            self.cancelled = True
        self.finish(error=RequestCancelled(
            "request cancelled by the client while queued (never "
            "dispatched; the queue slot is reclaimed at the next drain)"))
        return True


class ServeFuture:
    """Client-side handle for a submitted request."""

    def __init__(self, req: _Request):
        self._req = req

    def done(self) -> bool:
        return self._req.event.is_set()

    @property
    def version(self):
        """The model version that answered (set with the result) —
        exactly one coherent version per request, even mid-swap."""
        return self._req.version

    @property
    def req_id(self) -> int:
        """The request's correlation id — the key its trace spans
        (``serving.submit`` / ``serving.request``) carry."""
        return self._req.req_id

    def cancel(self) -> bool:
        """Withdraw a still-queued request: True iff the cancel won the
        race against batch assembly. On True the request is NEVER
        dispatched, its queue slot is reclaimed at the next drain, and
        ``result()`` raises :class:`RequestCancelled`. On False the
        request already entered a batch (or finished) — its original
        outcome stands. A caller abandoning ``result(timeout=)`` should
        cancel() so its slot stops occupying the bounded queue."""
        return self._req.cancel()

    def cancelled(self) -> bool:
        return self._req.cancelled

    def result(self, timeout=None):
        """Block for the outcome; raises the request's typed error
        (RequestTimeout / EngineClosed / ...) if it failed. ``timeout``
        here is the CLIENT's patience — hitting it raises TimeoutError
        without cancelling the request (call :meth:`cancel` to also
        withdraw it)."""
        if not self._req.event.wait(timeout):
            raise TimeoutError(
                f"serving result not ready within {timeout}s (the request "
                "is still in flight; its own deadline governs shedding — "
                "cancel() withdraws it if it is still queued)")
        if self._req.error is not None:
            raise self._req.error
        return self._req.result


class ContinuousBatcher:
    """Scheduler thread turning single requests into bucket batches.

    ``dispatch(bucket, requests)`` is the engine's execute hook: it runs
    the batch and calls ``finish`` on every request (the batcher
    backstops it — an exception from dispatch fails the whole group).
    ``on_expire(request)`` is invoked for deadline-expired requests
    (metrics), after the typed error is set.
    """

    #: machine-checked lock protocol (mxtpu-lint thread-guard):
    #: lifecycle state flips only under the close lock — submit/close
    #: racing on `_closed`, or two closers both joining `_thread`, was
    #: exactly the shutdown flake class PR-8 retired for checkpoints
    _GUARDED_BY = {"_closed": "_close_lock", "_thread": "_close_lock",
                   "_abort": "_close_lock"}

    def __init__(self, dispatch, *, max_batch, max_wait, queue_cap,
                 on_expire=None, autostart=True, name="default"):
        self._dispatch = dispatch
        self._max_batch = int(max_batch)
        self._max_wait = float(max_wait)
        self._on_expire = on_expire
        self._name = str(name)  # metric label: the model this serves
        self._queue = queue.Queue(maxsize=int(queue_cap))
        self._closed = False
        self._abort = None  # error factory set by abort(); see _GUARDED_BY
        self._close_lock = threading.Lock()
        self._thread = None
        if autostart:
            self.start()

    def start(self):
        with self._close_lock:
            if self._thread is None and not self._closed:
                self._thread = threading.Thread(
                    target=self._run, name="mxtpu-serving-batcher",
                    daemon=True)
                self._thread.start()
        return self

    def qsize(self) -> int:
        return self._queue.qsize()

    # -- client side -------------------------------------------------------
    def submit(self, req: _Request):
        if self._closed:
            raise EngineClosed("serving engine is closed/paused; submit "
                               "refused (in-flight work was drained)")
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            raise ServerOverloaded(
                f"serving queue full ({self._queue.maxsize} requests, "
                "MXTPU_SERVE_QUEUE) — load shed; retry with backoff") \
                from None
        return req

    # -- scheduler thread --------------------------------------------------
    def _next_wake(self, pending):
        """Earliest future event: a group's max-wait flush or a request
        deadline (None = nothing pending, sleep until work arrives)."""
        wake = None
        for group in pending.values():
            if not group:
                continue
            t = group[0].t_submit + self._max_wait
            wake = t if wake is None else min(wake, t)
            for r in group:
                if r.deadline is not None:
                    wake = r.deadline if wake is None else min(wake, r.deadline)
        return wake

    def _admit(self, pending, req):
        pending.setdefault(req.bucket, []).append(req)

    def _expire(self, pending, now):
        from .errors import RequestTimeout

        for bucket, group in pending.items():
            kept = []
            for r in group:
                if r.event.is_set():
                    continue  # cancelled while pending: drop the entry
                if r.deadline is not None and now >= r.deadline:
                    r.finish(error=RequestTimeout(
                        f"deadline expired after "
                        f"{(now - r.t_submit) * 1e3:.1f} ms waiting for a "
                        f"bucket {r.bucket} batch slot"))
                    if self._on_expire is not None:
                        self._on_expire(r)
                else:
                    kept.append(r)
            pending[bucket] = kept

    def _flush(self, pending, bucket, force=False):
        """Dispatch FIFO prefixes of ``bucket``'s group while it fills a
        batch (or unconditionally under ``force`` — close-time drain)."""
        group = pending.get(bucket) or []
        while group:
            take, rows = [], 0
            while group and rows + group[0].rows <= self._max_batch:
                r = group.pop(0)
                if not r.claim():
                    continue  # cancelled entry: skipped at drain time
                take.append(r)
                rows += r.rows
            if not take:  # head alone exceeds max_batch: cannot happen
                break     # (submit validates rows <= max_batch)
            try:
                self._dispatch(bucket, take)
            except BaseException as e:  # propagate to every waiter
                for r in take:
                    if not r.event.is_set():
                        r.finish(error=e)
            if rows < self._max_batch and not force:
                break  # partial batch only flushes when due/forced
        pending[bucket] = group

    def _sweep(self, pending, force=False):
        now = time.perf_counter()
        self._expire(pending, now)
        for bucket in list(pending):
            group = pending[bucket]
            if not group:
                continue
            rows = 0
            for r in group:
                rows += r.rows
            due = group[0].t_submit + self._max_wait <= now
            if force or due or rows >= self._max_batch:
                self._flush(pending, bucket, force=force or due)

    def _run(self):  # mxtpu-lint: hot-path
        pending = {}
        while True:
            wake = self._next_wake(pending)
            timeout = None if wake is None else \
                max(0.0, wake - time.perf_counter())
            t0 = time.perf_counter()
            try:
                item = self._queue.get(timeout=timeout)
            except queue.Empty:
                item = None
            if _obs.ENABLED:
                # idle-vs-busy split for the scheduler thread: blocked-
                # on-admission wall time (the serving analogue of the
                # prefetch consumer-wait counter; a counter inc, no sync)
                _obs.SERVE_SCHED_WAIT_SECONDS.inc(
                    time.perf_counter() - t0, model=self._name)
            closing = item is _CLOSE
            if item is not None and not closing:
                self._admit(pending, item)
            # greedy drain: admit the WHOLE backlog before scheduling,
            # so a burst coalesces into full batches instead of being
            # dispatched one newly-due request at a time
            while True:
                try:
                    extra = self._queue.get_nowait()
                except queue.Empty:
                    break
                if extra is _CLOSE:
                    closing = True
                else:
                    self._admit(pending, extra)
            if closing:
                with self._close_lock:
                    abort = self._abort
                if abort is not None:
                    # abrupt death: FAIL everything pending instead of
                    # dispatching it (waiters unblock typed, never hang)
                    for group in pending.values():
                        for r in group:
                            if not r.event.is_set():
                                r.finish(error=abort())
                    return
                # close-time drain: everything admitted before the
                # close dispatches (partial batches go out padded)
                self._sweep(pending, force=True)
                return
            self._sweep(pending)

    # -- shutdown ----------------------------------------------------------
    def abort(self, error_factory=None):
        """Abrupt-death hook (fleet replica kill / host-death
        simulation): refuse new submits and FAIL every queued request
        with ``error_factory()`` instead of dispatching it — the
        opposite of ``close()``'s graceful drain. In-flight waiters
        unblock immediately with a typed error, never hang."""
        def _default():
            return EngineClosed("engine killed (abrupt replica death); "
                                "queued work was failed, not drained")

        make = error_factory or _default
        with self._close_lock:
            self._closed = True
            self._abort = make
            thread = self._thread
        if thread is not None:
            while True:  # a full queue drains continuously under _run
                try:
                    self._queue.put_nowait(_CLOSE)
                    break
                except queue.Full:
                    time.sleep(0.001)
            thread.join(timeout=10.0)
        # whether or not a scheduler thread ever ran, nothing may stay
        # queued: fail the stragglers here (idempotent with _run's own
        # abort drain — finished requests are skipped)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not _CLOSE and not req.event.is_set():
                req.finish(error=make())
        with self._close_lock:
            self._thread = None

    def close(self):
        """Idempotent: refuse new submits, drain accepted requests
        (partial batches dispatch), join the scheduler thread."""
        with self._close_lock:
            first = not self._closed
            self._closed = True
            thread = self._thread
        if not first:
            # a concurrent/second closer still waits for the drain, but
            # the JOIN happens outside the lock: holding it across a
            # 10 s wait would convoy submit()/start() (lock-order rule)
            if thread is not None:
                thread.join(timeout=10.0)
                with self._close_lock:
                    self._thread = None
            return
        if thread is None:
            # never started (autostart=False): fail queued requests —
            # nothing will ever dispatch them
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    return
                if req is not _CLOSE and not req.event.is_set():
                    req.finish(error=EngineClosed(
                        "engine closed before its scheduler started"))
        self._queue.put(_CLOSE)
        thread.join(timeout=10.0)
        with self._close_lock:
            self._thread = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
