"""Child-process main for :class:`~.replica.ProcessReplica`.

``python -m mxnet_tpu.serving.replica_worker`` speaks the
length-prefixed pickle RPC over stdin/stdout: ``init`` builds a private
:class:`~.repository.ModelRepository` and loads the replica spec's
model (staged + verified, through the persistent compile cache);
``submit`` runs a request to completion on a small thread pool and
streams the answer back with the model VERSION that produced it (the
fleet's zero-stale-version proof reads this) plus the current queue
depth (the router's load signal piggybacks on every response);
``ping`` reports health inline; ``swap`` stages a new version in the
background; ``close`` drains and exits.

Anything the model or framework prints must not corrupt the frame
stream, so stdout is rebound to stderr at startup and only the worker
itself writes frames to the real stdout (under a lock — pool threads
complete out of order).
"""

from __future__ import annotations

import sys
import threading
from concurrent.futures import ThreadPoolExecutor


def main():
    out = sys.stdout.buffer
    sys.stdout = sys.stderr  # stray prints must never hit the frame stream
    inp = sys.stdin.buffer

    # heavy imports AFTER the stream swap so import-time chatter is safe
    from .replica import build_net, read_msg, write_msg
    from .repository import ModelRepository

    wlock = threading.Lock()

    def reply(mid, **fields):
        with wlock:
            write_msg(out, dict(fields, id=mid))

    repo = ModelRepository(keep=1)
    name = "model"
    pool = ThreadPoolExecutor(max_workers=16,
                              thread_name_prefix="mxtpu-replica-worker")

    def depth():
        try:
            return repo.engine(name).queue_depth()
        except Exception:
            return 0

    def fail(mid, e):
        reply(mid, ok=False, etype=type(e).__name__, emsg=str(e),
              depth=depth())

    def do_init(mid, msg):
        nonlocal name
        try:
            spec = msg["spec"]
            name = str(msg.get("name") or "model")
            engine = repo.load(name, lambda: build_net(spec["net"]),
                               spec["shapes"],
                               version=spec.get("version"),
                               **dict(spec.get("engine") or {}))
            reply(mid, ok=True, result="ready", version=engine.version,
                  depth=0)
        except Exception as e:  # noqa: BLE001 - everything crosses the wire
            fail(mid, e)

    def do_submit(mid, msg):
        try:
            fut = repo.submit(name, msg["x"], **dict(msg.get("kwargs") or {}))
            result = fut.result(timeout=60.0)
            reply(mid, ok=True, result=result,
                  version=getattr(fut, "version", None), depth=depth())
        except Exception as e:  # noqa: BLE001
            fail(mid, e)

    def do_ping(mid, msg):
        try:
            try:
                stats = repo.stats(name)
            except Exception:
                stats = {}
            d = depth()
            info = {"depth": d, "version": repo.live_version(name),
                    "stats": stats}
            reply(mid, ok=True, result=info, depth=d,
                  version=info["version"])
        except Exception as e:  # noqa: BLE001
            fail(mid, e)

    def do_swap(mid, msg):
        try:
            spec = msg["spec"]
            engine = repo.load(name, lambda: build_net(spec["net"]),
                               spec["shapes"],
                               version=spec.get("version"),
                               **dict(spec.get("engine") or {}))
            reply(mid, ok=True, result=engine.version,
                  version=engine.version, depth=depth())
        except Exception as e:  # noqa: BLE001
            fail(mid, e)

    while True:
        try:
            msg = read_msg(inp)
        except (EOFError, OSError):
            break
        op, mid = msg.get("op"), msg.get("id")
        if op == "init":
            do_init(mid, msg)          # inline: nothing else until ready
        elif op == "submit":
            pool.submit(do_submit, mid, msg)
        elif op == "ping":
            do_ping(mid, msg)          # inline: health must not queue
        elif op == "swap":
            pool.submit(do_swap, mid, msg)
        elif op == "close":
            reply(mid, ok=True, result="closing", depth=depth())
            break
        else:
            reply(mid, ok=False, etype="ServingError",
                  emsg=f"unknown op {op!r}")

    pool.shutdown(wait=True)
    repo.close()


if __name__ == "__main__":
    main()
