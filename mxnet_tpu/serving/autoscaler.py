"""SLO-driven autoscaler: signals in, membership actuations out.

Three signal sources converge on one auditable bus — the PR-11
:class:`~mxnet_tpu.resilience.elastic.MembershipMonitor` signal queue —
and ONE deterministic ``tick()`` drains it into fleet actuations:

- **watchdog anomalies**: a ``queue_saturation`` firing from the PR-15
  anomaly watchdog (registered listener) becomes a grow request;
- **SLO pressure**: router p99 above ``MXTPU_FLEET_SLO_P99_MS`` or
  aggregate queue fraction at the brownout enter threshold becomes a
  grow request; sustained headroom (p99 under half the SLO, fraction
  under the brownout exit) becomes a shrink request; a fully idle
  fleet (``idle_to_zero_s``) requests scale-to-zero;
- **replica deaths**: each death drained off the fleet becomes a
  ``dead_peer`` signal, actuated as an immediate REPLACEMENT — never
  cooldown-gated, because restoring redundancy is what the cooldown
  exists to protect.

Growth/shrink are cooldown-gated (``MXTPU_FLEET_COOLDOWN_S``) and
clamped to [min_replicas, max_replicas]. Replacement measures
detection->ready recovery latency into ``mxtpu_fleet_recovery_seconds``
— the number the chaos certification gates on.

The monitor here is a PRIVATE instance (policy disabled:
``straggler_factor=0.0``, ``notice_path=""``) used purely as the signal
bus; it is never ``attach()``-ed, so global elastic wiring is
untouched.
"""

from __future__ import annotations

import threading
import time

from .. import observability as _obs
from ..base import getenv
from ..observability import watchdog as _watchdog
from ..resilience.elastic import MembershipMonitor


def fleet_slo_p99_ms() -> float:
    """Serving latency SLO (p99, ms), ``MXTPU_FLEET_SLO_P99_MS``."""
    return float(getenv("MXTPU_FLEET_SLO_P99_MS", 100.0, dtype=float))


def fleet_cooldown_s() -> float:
    """Minimum spacing between capacity changes (replacement is
    exempt), ``MXTPU_FLEET_COOLDOWN_S``."""
    return max(0.0, float(getenv("MXTPU_FLEET_COOLDOWN_S", 5.0,
                                 dtype=float)))


class SLOAutoscaler:
    """Drive a :class:`~.fleet.ServingFleet` toward its SLO."""

    def __init__(self, fleet, *, min_replicas=None, max_replicas=None,
                 slo_p99_ms=None, cooldown_s=None, interval_s=0.5,
                 idle_to_zero_s=0.0, monitor=None, use_watchdog=True):
        from .fleet import fleet_min_replicas, fleet_max_replicas
        self.fleet = fleet
        self.min_replicas = fleet_min_replicas() if min_replicas is None \
            else max(0, int(min_replicas))
        self.max_replicas = fleet_max_replicas() if max_replicas is None \
            else max(1, int(max_replicas))
        self.slo_p99_ms = fleet_slo_p99_ms() if slo_p99_ms is None \
            else float(slo_p99_ms)
        self.cooldown_s = fleet_cooldown_s() if cooldown_s is None \
            else float(cooldown_s)
        self.interval_s = float(interval_s)
        self.idle_to_zero_s = float(idle_to_zero_s)
        # signal bus only: straggler policy + notice file-poll disabled,
        # and NEVER .attach()-ed (that would hijack global wiring)
        self.monitor = monitor or MembershipMonitor(
            straggler_factor=0.0, notice_path="")
        self._last_change_mono = 0.0
        self._reported_uids = set()
        self._replaced = 0
        self._thread = None
        self._stop = threading.Event()
        self._use_watchdog = bool(use_watchdog)
        if self._use_watchdog:
            _watchdog.register_listener(self._on_anomaly)

    # -- signal ingestion --------------------------------------------------
    def _on_anomaly(self, kind, details):
        """Watchdog actuator hook: saturation anomalies request growth
        through the same auditable bus as everything else."""
        if kind == "queue_saturation":
            self.monitor.request_resize(
                self.fleet.n_live() + 1, reason="queue_saturation")

    def _ingest_deaths(self):
        for replica, reason in self.fleet.drain_deaths():
            if replica.uid in self._reported_uids:
                continue
            self._reported_uids.add(replica.uid)
            self.monitor.report_dead_peer(
                replica.index,
                detail=f"replica uid={replica.uid} ({reason})")

    def _slo_policy(self, now):
        """Translate SLO pressure/headroom into resize requests."""
        n = self.fleet.n_live()
        p99 = self.fleet.p99_ms()
        frac = self.fleet.queue_fraction()
        in_cooldown = now - self._last_change_mono < self.cooldown_s
        if n > 0 and not in_cooldown and n < self.max_replicas and (
                (p99 is not None and p99 > self.slo_p99_ms)
                or frac >= self.fleet._enter):
            self.monitor.request_resize(n + 1, reason="slo")
            return
        if (self.idle_to_zero_s > 0 and n > 0 and self.min_replicas == 0
                and self.fleet.idle_seconds() >= self.idle_to_zero_s):
            self.monitor.request_resize(0, reason="idle")
            return
        if (n > self.min_replicas and n > 1 and not in_cooldown
                and frac <= self.fleet._exit
                and (p99 is None or p99 < 0.5 * self.slo_p99_ms)
                and self.fleet.router.latency_count() >= 5):
            self.monitor.request_resize(n - 1, reason="drain")

    # -- actuation ---------------------------------------------------------
    def _replace_dead(self, now):
        """Replace every dead replica NOW (cooldown-exempt) and record
        detection->ready recovery latency."""
        rs = self.fleet.replica_set
        for replica in [r for r in rs.replicas() if r.state == "dead"]:
            t_death = replica.death_mono or now
            rs.replace(replica)
            recovery = time.monotonic() - t_death
            self.fleet.note_recovery(recovery)
            self._replaced += 1
            if _obs.ENABLED:
                _obs.record_fleet_autoscale(self.fleet.name, "replace",
                                            self.fleet.n_live())

    def _actuate_resize(self, target, reason, now):
        n = self.fleet.n_live()
        target = max(self.min_replicas, min(self.max_replicas, int(target)))
        if target == 0 and n > 0:
            self.fleet.replica_set.scale_to_zero()
            action = "to_zero"
        elif target > n:
            if self.fleet.replica_set.warm():
                action = "restore"
            else:
                action = "grow"
            self.fleet.replica_set.scale_to(target)
        elif target < n:
            self.fleet.replica_set.scale_to(target)
            action = "shrink"
        else:
            return
        self._last_change_mono = now
        if _obs.ENABLED:
            _obs.record_fleet_autoscale(self.fleet.name, action,
                                        self.fleet.n_live())

    def tick(self, now=None):
        """One deterministic control-loop pass: ingest signals, run the
        SLO policy, drain the bus, actuate. Returns the drained signal
        list (auditable)."""
        now = time.monotonic() if now is None else now
        self._ingest_deaths()
        self._slo_policy(now)
        signals = self.monitor.drain(kinds=("dead_peer", "resize"))
        for sig in signals:
            if sig["kind"] == "dead_peer":
                self._replace_dead(now)
            elif sig["kind"] == "resize":
                self._actuate_resize(sig.get("target"),
                                     sig.get("reason"), now)
        # deaths can also be observed directly (chaos kill between
        # ticks): replace even without a routed dead_peer signal
        if any(r.state == "dead"
               for r in self.fleet.replica_set.replicas()):
            self._replace_dead(now)
        return signals

    @property
    def replaced(self) -> int:
        return self._replaced

    # -- background loop ---------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"mxtpu-fleet-{self.fleet.name}-autoscaler")
        self._thread.start()

    def _loop(self):  # mxtpu-lint: hot-path
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                pass  # the control loop must outlive any single actuation

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        if self._use_watchdog:
            _watchdog.unregister_listener(self._on_anomaly)
