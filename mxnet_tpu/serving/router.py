"""Fleet router: least-queue-depth dispatch with typed failover.

One entry point (:meth:`ReplicaRouter.submit`) in front of N replicas:

- **placement** — candidates are ordered by queue depth when a FRESH
  depth signal exists: the cluster federation feed first
  (``mxtpu_serving_queue_depth`` per rank via
  :func:`~mxnet_tpu.observability.federation.cluster_values`), local
  piggybacked depth observations second. Replicas with no fresh signal
  (federation cold, no recent response) fall back to a CONSISTENT-HASH
  ring on the request key, so placement stays deterministic and
  cache-friendly instead of degrading to random under signal loss.
- **failover** — a dispatch or wait that dies with a replica-death
  class (:class:`ReplicaDead` / :class:`EngineClosed` pipe variants)
  is retried with decorrelated-jitter backoff on the next candidate.
  AT-MOST-ONCE per replica: a request's ``tried`` set burns each uid
  permanently, so a flapping replica can never see the same request
  twice. Only when EVERY candidate failed does the caller see a typed
  :class:`ReplicaLost` — a single host kill is invisible to clients
  while a survivor exists.
- **hedging** (off by default, ``MXTPU_FLEET_HEDGE_MS``) — a request
  stuck past the hedge budget dispatches a duplicate onto the next
  candidate; first completion wins, the loser is dropped. Bounds tail
  latency from a stalling replica at the cost of duplicate compute.
"""

from __future__ import annotations

import hashlib
import random
import threading
import time
from collections import deque

from .. import observability as _obs
from ..base import getenv
from ..runtime import backoff_delays
from .errors import EngineClosed, ReplicaDead, ReplicaLost

#: error classes that mean "this REPLICA is gone", triggering failover
#: (anything else — timeout, shape refusal, cancel — is the request's
#: own outcome and must surface unchanged)
DEATH_ERRORS = (ReplicaDead, EngineClosed)


def fleet_retries(default=0) -> int:
    """``MXTPU_FLEET_RETRIES``: max failover dispatches per request
    beyond the first (0 = every surviving candidate, the default)."""
    return int(getenv("MXTPU_FLEET_RETRIES", default))


def fleet_hedge_ms(default=0.0) -> float:
    """``MXTPU_FLEET_HEDGE_MS``: hedge a request onto a second replica
    after this many ms without a result (0 = hedging off, default)."""
    return float(getenv("MXTPU_FLEET_HEDGE_MS", default))


def federation_depth_feed(rank_of):
    """Build a ``depth_feed`` reading per-rank queue depth from the
    PR-15 federation plane. ``rank_of(replica) -> rank`` maps fleet
    replicas onto federation ranks. Returns None (-> hash fallback) for
    replicas whose rank is stale or unreported."""
    from ..observability import federation as _fed

    def feed(replica):
        values = _fed.cluster_values("mxtpu_serving_queue_depth")
        rank = rank_of(replica)
        return values.get(rank)

    return feed


class ReplicaRouter:
    """Dispatch requests across live replicas; see module docstring."""

    #: vnodes per replica on the hash ring — enough that one death
    #: reshuffles ~1/n of keyspace, not half of it
    _VNODES = 32

    def __init__(self, candidates_fn, model="model", *, retries=None,
                 hedge_ms=None, depth_feed=None, on_death=None,
                 fresh_depth_s=5.0):
        self._candidates = candidates_fn  # () -> ordered live replicas
        self._model = str(model)
        self._retries = fleet_retries() if retries is None else int(retries)
        self._hedge_ms = fleet_hedge_ms() if hedge_ms is None \
            else float(hedge_ms)
        self._depth_feed = depth_feed
        self._on_death = on_death
        self._fresh_depth_s = float(fresh_depth_s)
        self._rng = random.Random()  # placement tie-break only, not crypto
        self._lat_lock = threading.Lock()
        self._latencies = deque(maxlen=512)  # seconds, completed requests
        self._GUARDED_BY = {"_latencies": "_lat_lock"}

    # -- candidate ordering ------------------------------------------------
    def _depth_of(self, replica):
        """Freshest known queue depth, or None when no fresh signal."""
        if self._depth_feed is not None:
            try:
                d = self._depth_feed(replica)
            except Exception:
                d = None
            if d is not None:
                return float(d)
        if replica.depth_age() <= self._fresh_depth_s:
            return float(replica.queue_depth())
        return None

    def _hash_order(self, replicas, key):
        """Consistent-hash ring walk from the key's point; ``key=None``
        degrades to a uniform shuffle (stateless spread)."""
        if key is None:
            order = list(replicas)
            self._rng.shuffle(order)
            return order
        ring = []
        for r in replicas:
            for v in range(self._VNODES):
                h = hashlib.md5(f"{r.uid}:{v}".encode()).digest()
                ring.append((h, r))
        ring.sort(key=lambda t: t[0])
        point = hashlib.md5(str(key).encode()).digest()
        order, seen = [], set()
        start = 0
        while start < len(ring) and ring[start][0] < point:
            start += 1
        for i in range(len(ring)):
            r = ring[(start + i) % len(ring)][1]
            if r.uid not in seen:
                seen.add(r.uid)
                order.append(r)
        return order

    def _order(self, key, tried):
        """Candidates for the next dispatch: fresh-depth replicas first
        (ascending depth), signal-less ones after in ring order."""
        live = [r for r in self._candidates() if r.uid not in tried]
        scored, unknown = [], []
        for r in live:
            d = self._depth_of(r)
            (unknown if d is None else scored).append((d, r))
        scored.sort(key=lambda t: (t[0], t[1].uid))
        ordered = [r for _, r in scored]
        ordered += self._hash_order([r for _, r in unknown], key)
        return ordered

    # -- dispatch ----------------------------------------------------------
    def _note_death(self, replica, error):
        reason = "dead" if isinstance(error, ReplicaDead) else "closed"
        if _obs.ENABLED:
            _obs.FLEET_RETRY_TOTAL.inc(1, model=self._model, reason=reason)
        if self._on_death is not None:
            try:
                self._on_death(replica, error)
            except Exception:
                pass

    def _dispatch_once(self, x, kwargs, key, tried):
        """One placement round: try candidates in order until ONE
        accepts the request (at most one dispatch per call). Raises
        ReplicaLost when no candidate accepts."""
        budget = None if self._retries <= 0 else self._retries + 1
        for replica in self._order(key, tried):
            if budget is not None and len(tried) >= budget:
                break
            tried.add(replica.uid)
            try:
                inner = replica.submit(x, **kwargs)
            except DEATH_ERRORS as e:
                self._note_death(replica, e)
                continue
            if _obs.ENABLED:
                _obs.FLEET_DISPATCH_TOTAL.inc(
                    1, model=self._model, replica=str(replica.index))
            return replica, inner
        if _obs.ENABLED:
            _obs.FLEET_REPLICA_LOST_TOTAL.inc(1, model=self._model)
        raise ReplicaLost(
            f"model {self._model!r}: all {len(tried)} candidate "
            "replica(s) failed with replica-death errors — no survivor "
            "accepted the request")

    def submit(self, x, key=None, **kwargs):
        """Dispatch one request; returns a :class:`FleetFuture` whose
        ``result()`` transparently fails over on replica death."""
        tried = set()
        replica, inner = self._dispatch_once(x, kwargs, key, tried)
        return FleetFuture(self, replica, inner, x, kwargs, key, tried)

    # -- latency window ----------------------------------------------------
    def record_latency(self, seconds):
        with self._lat_lock:
            self._latencies.append(float(seconds))

    def p99_ms(self):
        """p99 over the sliding completed-request window (None until
        enough samples) — the autoscaler's SLO signal."""
        with self._lat_lock:
            lat = sorted(self._latencies)
        if len(lat) < 5:
            return None
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1000.0

    def latency_count(self) -> int:
        with self._lat_lock:
            return len(self._latencies)


class FleetFuture:
    """A request's fleet-level handle: waits on the current replica's
    future and re-dispatches (at-most-once per replica) when the
    replica dies underneath it. ``result()`` therefore raises
    :class:`ReplicaLost` only when every candidate has failed — and
    the request's OWN typed outcomes (timeout, cancel, shed) pass
    through unchanged."""

    _POLL_S = 0.002  # hedge-mode completion poll slice

    def __init__(self, router, replica, inner, x, kwargs, key, tried):
        self._router = router
        self._replica = replica
        self._inner = inner
        self._x = x
        self._kwargs = kwargs
        self._key = key
        self._tried = tried
        self._hedge = None       # (replica, inner) once hedged
        self._hedged = False
        self._t0 = time.monotonic()

    @property
    def replica(self):
        return self._replica

    @property
    def version(self):
        return getattr(self._inner, "version", None)

    def done(self) -> bool:
        if self._inner.done():
            return True
        return self._hedge is not None and self._hedge[1].done()

    def tried_count(self) -> int:
        return len(self._tried)

    def _reap(self, fut, deadline):
        """Resolve one inner future within the deadline; DEATH_ERRORS
        propagate for failover, other outcomes are final."""
        remaining = None if deadline is None \
            else max(0.0, deadline - time.monotonic())
        return fut.result(remaining)

    def _hedge_wait(self, deadline):
        """Wait with a duplicate dispatch after the hedge budget; first
        terminal future wins, dead branches fail over."""
        hedge_s = self._router._hedge_ms / 1000.0
        if self._hedge is None:
            hedge_at = self._t0 + hedge_s
            while time.monotonic() < hedge_at:
                if self._inner.done():
                    return self._reap(self._inner, deadline)
                if deadline is not None and time.monotonic() >= deadline:
                    return self._reap(self._inner, deadline)  # raises
                time.sleep(self._POLL_S)
            try:
                self._hedge = self._router._dispatch_once(
                    self._x, self._kwargs, self._key, self._tried)
                self._hedged = True
                if _obs.ENABLED:
                    _obs.FLEET_HEDGED_TOTAL.inc(1, model=self._router._model)
            except ReplicaLost:
                self._hedge = None  # nobody left to hedge onto: primary only
                return self._reap(self._inner, deadline)
        # poll both branches; first terminal result wins
        while True:
            for fut in (self._inner, self._hedge[1]):
                if fut.done():
                    try:
                        return fut.result(0)
                    except DEATH_ERRORS:
                        if fut is self._inner:
                            # primary died: promote the hedge
                            self._router._note_death(
                                self._replica, ReplicaDead("hedge primary"))
                            self._replica, self._inner = self._hedge
                            self._hedge = None
                            return self._reap(self._inner, deadline)
                        self._hedge = None  # hedge died: primary only
                        return self._reap(self._inner, deadline)
            if deadline is not None and time.monotonic() >= deadline:
                return self._reap(self._inner, deadline)  # raises Timeout
            time.sleep(self._POLL_S)

    def _await_once(self, deadline):
        if self._router._hedge_ms > 0:
            return self._hedge_wait(deadline)
        return self._reap(self._inner, deadline)

    def result(self, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        attempt = 0
        while True:
            try:
                out = self._await_once(deadline)
            except DEATH_ERRORS as e:
                self._router._note_death(self._replica, e)
                attempt += 1
                delay = backoff_delays(2, 0.001, max_delay=0.05)[0]
                time.sleep(delay)
                # re-dispatch onto the next candidate (at-most-once set
                # carries over, so dead replicas stay burned)
                self._replica, self._inner = self._router._dispatch_once(
                    self._x, self._kwargs, self._key, self._tried)
                continue
            self._router.record_latency(time.monotonic() - self._t0)
            return out

    def was_hedged(self) -> bool:
        return self._hedged
