"""A small functional transformer decoder LM — the generation stack's
reference model (and the fleet demo/test workload).

This is deliberately NOT a Gluon block: the decode fast path needs
pure ``(params, state) -> (logits, state)`` functions it can close
into AOT-compiled prefill/decode executables, with the KV pools
threaded through as donated operands. The class carries the
hyperparameters and the (deterministically seeded) weights; everything
the device runs comes out of :meth:`prefill_fn` / :meth:`decode_step_fn`
/ :meth:`forward_fn` as pure closures over nothing but shapes.

The SAME math is exposed three ways, which is what the correctness
tests pin against each other:

- :meth:`forward_fn` — dense full-context causal forward (the oracle);
- :meth:`prefill_fn` — dense over the prompt, but scattering each
  layer's K/V into the paged pool through the request's block table;
- :meth:`decode_step_fn` — one token per sequence, K/V appended to the
  pool and attention read back through
  :func:`~mxnet_tpu.ops.flash_attention.paged_decode_attention`.

Architecture: learned positional embeddings, pre-LN, grouped-query
attention (``kv_heads | num_heads``), GELU MLP, weight-tied-free head.
Process replicas rebuild it from the ``{"decoder": {...}}`` spec with
the same seed, so every replica serves identical weights.
"""

from __future__ import annotations

import numpy as np

_EPS = 1e-5


def _ln(x, g, b):
    import jax.numpy as jnp

    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + _EPS) * g + b


class TransformerDecoderLM:
    """Tiny decoder-only LM with paged-cache-aware prefill/decode.

    >>> net = TransformerDecoderLM(vocab_size=64, num_layers=2,
    ...                            d_model=32, num_heads=4, kv_heads=2)
    >>> dims = net.decode_dims()   # cache geometry for PagedKVCache
    """

    def __init__(self, vocab_size=64, num_layers=2, d_model=32,
                 num_heads=4, kv_heads=None, d_ff=None, max_seq=128,
                 seed=0, dtype="float32"):
        self.vocab_size = int(vocab_size)
        self.num_layers = int(num_layers)
        self.d_model = int(d_model)
        self.num_heads = int(num_heads)
        self.kv_heads = int(kv_heads or num_heads)
        self.d_ff = int(d_ff or 2 * d_model)
        self.max_seq = int(max_seq)
        self.seed = int(seed)
        self.dtype = str(dtype)
        if self.num_heads % self.kv_heads != 0:
            raise ValueError("num_heads must be a multiple of kv_heads; "
                             f"got {self.num_heads} vs {self.kv_heads}")
        if self.d_model % self.num_heads != 0:
            raise ValueError("d_model must divide into num_heads")
        self.head_dim = self.d_model // self.num_heads
        self._params = self._init_params()

    # -- weights -----------------------------------------------------------
    def _init_params(self):
        import jax.numpy as jnp

        rng = np.random.RandomState(self.seed)
        s = 0.02

        def w(*shape):
            return jnp.asarray(rng.normal(0.0, s, shape), dtype=self.dtype)

        def zeros(*shape):
            return jnp.zeros(shape, dtype=self.dtype)

        def ones(*shape):
            return jnp.ones(shape, dtype=self.dtype)

        d, h, kvh, hd, ff = (self.d_model, self.num_heads, self.kv_heads,
                             self.head_dim, self.d_ff)
        layers = []
        for _ in range(self.num_layers):
            layers.append({
                "ln1_g": ones(d), "ln1_b": zeros(d),
                "wq": w(d, h * hd), "wk": w(d, kvh * hd),
                "wv": w(d, kvh * hd), "wo": w(h * hd, d),
                "ln2_g": ones(d), "ln2_b": zeros(d),
                "w1": w(d, ff), "b1": zeros(ff),
                "w2": w(ff, d), "b2": zeros(d),
            })
        return {
            "embed": w(self.vocab_size, d),
            "pos": w(self.max_seq, d),
            "layers": layers,
            "lnf_g": ones(d), "lnf_b": zeros(d),
            "head": w(d, self.vocab_size),
        }

    def params(self):
        """The weight pytree (a plain dict — device-resident arrays)."""
        return self._params

    def decode_dims(self) -> dict:
        """Cache geometry the engine hands to :class:`PagedKVCache`."""
        return {
            "layers": self.num_layers,
            "kv_heads": self.kv_heads,
            "head_dim": self.head_dim,
            "max_seq": self.max_seq,
            "vocab_size": self.vocab_size,
            "d_model": self.d_model,
        }

    def spec(self) -> dict:
        """The ``{"decoder": ...}`` replica spec that rebuilds this net
        (same seed -> identical weights in every process replica)."""
        return {"decoder": {
            "vocab_size": self.vocab_size, "num_layers": self.num_layers,
            "d_model": self.d_model, "num_heads": self.num_heads,
            "kv_heads": self.kv_heads, "d_ff": self.d_ff,
            "max_seq": self.max_seq, "seed": self.seed,
            "dtype": self.dtype,
        }}

    # -- shared layer math -------------------------------------------------
    def _qkv(self, lyr, h):
        """Project one layer's hidden states ``(..., d)`` to q/k/v with
        head axes split out."""
        lead = h.shape[:-1]
        q = (h @ lyr["wq"]).reshape(*lead, self.num_heads, self.head_dim)
        k = (h @ lyr["wk"]).reshape(*lead, self.kv_heads, self.head_dim)
        v = (h @ lyr["wv"]).reshape(*lead, self.kv_heads, self.head_dim)
        return q, k, v

    def _mlp(self, lyr, x):
        import jax

        return jax.nn.gelu(x @ lyr["w1"] + lyr["b1"]) @ lyr["w2"] + lyr["b2"]

    def _dense_attend(self, q, k, v, causal_mask):
        """Dense causal attention over full context (oracle + prefill).
        q: (B, T, H, hd); k/v: (B, S, KVH, hd)."""
        import jax.numpy as jnp

        group = self.num_heads // self.kv_heads
        if group > 1:
            k = jnp.repeat(k, group, axis=2)
            v = jnp.repeat(v, group, axis=2)
        scale = 1.0 / (self.head_dim ** 0.5)
        s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        s = jnp.where(causal_mask, s, -1e30)
        p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
        p = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        o = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
        return o.astype(q.dtype)

    def _trunk_dense(self, params, tokens, write_kv=None):
        """Dense causal trunk over ``tokens`` (B, T). ``write_kv`` is an
        optional callback ``(layer_idx, k, v)`` the prefill path uses to
        scatter each layer's K/V into the paged pool."""
        import jax.numpy as jnp

        b, t = tokens.shape
        x = params["embed"][tokens] + params["pos"][:t][None]
        mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
        for li, lyr in enumerate(params["layers"]):
            h = _ln(x, lyr["ln1_g"], lyr["ln1_b"])
            q, k, v = self._qkv(lyr, h)
            if write_kv is not None:
                write_kv(li, k, v)
            o = self._dense_attend(q, k, v, mask)
            x = x + o.reshape(b, t, -1) @ lyr["wo"]
            x = x + self._mlp(lyr, _ln(x, lyr["ln2_g"], lyr["ln2_b"]))
        return _ln(x, params["lnf_g"], params["lnf_b"])

    # -- the three pure faces ---------------------------------------------
    def forward_fn(self):
        """Dense full-context oracle: ``(params, tokens[B, T]) ->
        logits[B, T, V]`` — what every decode step must reproduce."""

        def forward(params, tokens):
            h = self._trunk_dense(params, tokens)
            return h @ params["head"]

        return forward

    def prefill_fn(self):
        """Prompt ingestion: dense causal forward over ONE padded
        prompt, scattering every layer's K/V into the paged pool
        through the request's block table. ``(params, tokens[1, Tb],
        k_pool, v_pool, table[1, mb], length[1]) -> (logits[1, V],
        k_pool, v_pool)`` — logits are at the LAST REAL position
        (``length - 1``); pad positions write to the null block."""
        from .kvcache import paged_prefill_write

        def prefill(params, tokens, k_pool, v_pool, table, length):
            import jax.numpy as jnp

            writes = []

            def write_kv(li, k, v):
                writes.append((li, k[0], v[0]))  # (Tb, KVH, hd)

            h = self._trunk_dense(params, tokens, write_kv=write_kv)
            for li, k, v in writes:
                k_pool = k_pool.at[li].set(
                    paged_prefill_write(k_pool[li], table[0], length[0], k))
                v_pool = v_pool.at[li].set(
                    paged_prefill_write(v_pool[li], table[0], length[0], v))
            last = jnp.clip(length - 1, 0, tokens.shape[1] - 1)
            h_last = jnp.take_along_axis(
                h, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]
            return h_last @ params["head"], k_pool, v_pool

        return prefill

    def decode_step_fn(self):
        """One decode step for the whole slot batch: append each active
        slot's token K/V to the pool, attend through the block table,
        return next-token logits. ``(params, token[B], pos[B], k_pool,
        v_pool, tables[B, mb], active[B]) -> (logits[B, V], k_pool,
        v_pool)``. Inactive slots write to the null block and read an
        empty context — the step is branch-free in slot liveness."""
        from ..ops.flash_attention import paged_decode_attention
        from .kvcache import slot_coords

        def step(params, token, pos, k_pool, v_pool, tables, active):
            import jax.numpy as jnp

            block_size = k_pool.shape[2]
            pos_c = jnp.clip(pos, 0, self.max_seq - 1)
            x = params["embed"][token] + params["pos"][pos_c]
            blk, off = slot_coords(tables, pos_c, block_size, active)
            # context includes the token being written THIS step
            ctx = jnp.where(active, pos_c + 1, 0).astype(jnp.int32)
            scale = 1.0 / (self.head_dim ** 0.5)
            for li, lyr in enumerate(params["layers"]):
                h = _ln(x, lyr["ln1_g"], lyr["ln1_b"])
                q, k, v = self._qkv(lyr, h)       # (B, H/KVH, hd)
                k_pool = k_pool.at[li, blk, off].set(k)
                v_pool = v_pool.at[li, blk, off].set(v)
                o = paged_decode_attention(q, k_pool[li], v_pool[li],
                                           tables, ctx, scale=scale)
                x = x + o.reshape(x.shape[0], -1) @ lyr["wo"]
                x = x + self._mlp(lyr, _ln(x, lyr["ln2_g"], lyr["ln2_b"]))
            h = _ln(x, params["lnf_g"], params["lnf_b"])
            return h @ params["head"], k_pool, v_pool

        return step
