"""Autoregressive decode fast path: single-dispatch chunked decode,
on-device sampling, and token-level continuous batching.

The one-shot :class:`~.engine.InferenceEngine` answers a request with
one dispatch; a generative request is HUNDREDS of sequential steps, so
the host round trip per token — not the math — dominates. This engine
removes it at three levels:

- **one executable for the whole decode batch**: a ``lax.scan`` over
  ``MXTPU_DECODE_CHUNK`` steps (model step + sampling + EOS/budget
  bookkeeping all in-graph) is AOT-compiled ONCE at deploy for a fixed
  slot count, so the host touches the loop once per chunk —
  amortized XLA dispatches per generated token are ``<= 1/chunk``
  (the bench certifies this with a PR-6-style dispatch-count assert);
- **on-device sampling** (:func:`sample_tokens`): greedy / temperature
  / top-k / top-p per SLOT (every request carries its own knobs as
  operands, so mixed sampling policies share one executable), PRNG
  keys folded and threaded device-side — no sync to pick a token;
- **token-level continuous batching** (Orca-style iteration-level
  scheduling): the decode batch is ``MXTPU_DECODE_SLOTS`` slots;
  requests JOIN an idle slot between chunks (prefill is its own
  per-prompt-bucket executable) and LEAVE the moment EOS or their
  token budget retires them — a late submit never waits for the
  running batch to drain, and a finished sequence never pads it.

K/V state lives in the :class:`~.kvcache.PagedKVCache` block pool;
the pools are DONATED through every prefill/decode dispatch, so cache
memory is constant and aliased in place. Slot liveness is an operand
(never a shape): ragged traffic — joins, retirements, wildly different
lengths — reuses the same sealed executables with ZERO retraces after
warmup (``RetraceForbidden`` otherwise, the PR-13 contract).

Sampling reproducibility: a request's first token is drawn from its
own ``seed`` (folded in-graph), so prefill is per-request
deterministic; subsequent tokens draw from the engine's device-side
key stream, which advances per CHUNK — deterministic for a fixed
admission order. ``greedy=True`` (the default) is always bit-stable.

Served through :class:`~.repository.ModelRepository` and the PR-17
fleet unchanged: ``submit()/predict()/stats()/queue_depth()`` plus the
pause/resume/kill/close lifecycle mirror ``InferenceEngine``, and
``repo.load`` picks this engine automatically for nets exposing
``decode_step_fn`` (e.g. :class:`~.decoder.TransformerDecoderLM`).

Knobs: ``MXTPU_DECODE_SLOTS`` / ``MXTPU_DECODE_CHUNK`` /
``MXTPU_DECODE_MAX_NEW`` (docs/env_vars.md); metrics:
``mxtpu_decode_*`` + ``mxtpu_kvcache_*`` (docs/observability.md);
recipe: docs/serving.md "Generation".
"""

from __future__ import annotations

import collections
import threading
import time

import numpy as _np

from .. import base
from .. import observability as _obs
from ..base import MXNetError
from .engine import serve_queue_cap
from .errors import (
    EngineClosed,
    KVCacheOOM,
    ReplicaDead,
    RequestCancelled,
    RequestTimeout,
    RetraceForbidden,
    ServerOverloaded,
    ServingError,
)
from .kvcache import PagedKVCache

_SLOTS_DEFAULT = 8
_CHUNK_DEFAULT = 8
_MAX_NEW_DEFAULT = 32


def decode_slots() -> int:
    """Decode-batch width in slots (``MXTPU_DECODE_SLOTS``, default 8).
    ONE decode executable is compiled for exactly this many slots;
    requests join/leave between chunks. More slots = more concurrent
    sequences per dispatch (throughput) at more pool pressure."""
    return max(1, base.getenv("MXTPU_DECODE_SLOTS", _SLOTS_DEFAULT,
                              dtype=int))


def decode_chunk() -> int:
    """Decode steps fused per dispatch (``MXTPU_DECODE_CHUNK``, default
    8) — the ``lax.scan`` length. Raising it amortizes the host round
    trip over more tokens (dispatches/token = 1/chunk) but delays
    join/retire scheduling to chunk boundaries; the serving analog of
    ``MXTPU_SUPERSTEP_K``."""
    return max(1, base.getenv("MXTPU_DECODE_CHUNK", _CHUNK_DEFAULT,
                              dtype=int))


def decode_max_new() -> int:
    """Default per-request new-token budget when ``submit`` doesn't
    pass ``max_new_tokens`` (``MXTPU_DECODE_MAX_NEW``, default 32)."""
    return max(1, base.getenv("MXTPU_DECODE_MAX_NEW", _MAX_NEW_DEFAULT,
                              dtype=int))


# ---------------------------------------------------------------------------
# on-device sampling
# ---------------------------------------------------------------------------

def sample_tokens(logits, key, temperature, top_k, top_p, greedy):
    """Sample one token per row, entirely in-graph. ``logits`` is
    ``(B, V)``; every knob is a ``(B,)`` vector so each batch slot
    applies ITS OWN policy inside the shared executable:

    - ``greedy`` (bool): argmax of the raw logits (ignores the rest);
    - ``temperature`` (f32): logit scale before filtering;
    - ``top_k`` (i32): keep the k highest-scoring tokens (0 = off);
    - ``top_p`` (f32): nucleus — keep the smallest prefix of the
      sorted distribution with cumulative probability >= p (1.0 = off;
      the argmax always survives, so filtering can never empty a row).

    Filters compose (top-k first, then top-p) by masking to ``-inf``
    and drawing with ``jax.random.categorical``."""
    import jax
    import jax.numpy as jnp

    v = logits.shape[-1]
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kk = jnp.where(top_k > 0, jnp.clip(top_k, 1, v), v)
    kth = jnp.take_along_axis(sorted_desc, (kk - 1)[:, None], axis=-1)
    limited = jnp.where(scaled < kth, -jnp.inf, scaled)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    keep = mass_before < top_p[:, None]
    thresh = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    limited = jnp.where(scaled < thresh, -jnp.inf, limited)
    drawn = jax.random.categorical(key, limited, axis=-1)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1),
                     drawn).astype(jnp.int32)


# ---------------------------------------------------------------------------
# request/future plumbing (mirrors batcher._Request / ServeFuture)
# ---------------------------------------------------------------------------

class _GenRequest:
    __slots__ = ("prompt", "max_new", "temperature", "top_k", "top_p",
                 "greedy", "seed", "eos", "deadline", "t_submit",
                 "tokens", "t_first", "t_last", "event", "result",
                 "error", "version", "claimed", "cancelled",
                 "_state_lock")

    def __init__(self, prompt, max_new, temperature, top_k, top_p,
                 greedy, seed, eos, deadline):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.greedy = bool(greedy)
        self.seed = int(seed)
        self.eos = int(eos)
        self.deadline = deadline  # absolute perf_counter time, or None
        self.t_submit = time.perf_counter()
        self.tokens = []
        self.t_first = None
        self.t_last = None
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.version = None
        self.claimed = False     # admission won the CAS
        self.cancelled = False
        self._state_lock = threading.Lock()

    def claim(self) -> bool:
        """Admission-side CAS: exactly one of {admit, cancel} wins."""
        with self._state_lock:
            if self.cancelled:
                return False
            self.claimed = True
            return True

    def cancel(self) -> bool:
        with self._state_lock:
            if self.claimed or self.event.is_set():
                return False
            self.cancelled = True
        self.error = RequestCancelled(
            "generation request cancelled while queued — never admitted")
        self.event.set()
        return True

    def finish(self, result=None, error=None, version=None):
        if self.event.is_set():
            return
        self.result = result
        self.error = error
        self.version = version
        self.event.set()


class GenerateFuture:
    """Client handle for a generation request. ``result()`` returns the
    generated token ids as ``np.int32`` (prompt NOT included; the EOS
    token, when hit, IS the last element)."""

    def __init__(self, req: _GenRequest):
        self._req = req

    def done(self) -> bool:
        return self._req.event.is_set()

    @property
    def version(self):
        return self._req.version

    def cancel(self) -> bool:
        """Withdraw a still-queued request (True iff it was never
        admitted to a slot — after admission the generation runs to
        completion and the original outcome stands)."""
        return self._req.cancel()

    def cancelled(self) -> bool:
        return self._req.cancelled

    def result(self, timeout=None):
        if not self._req.event.wait(timeout):
            raise TimeoutError(
                f"generation result not ready within {timeout}s (the "
                "request itself is still running; cancel() to withdraw "
                "a queued one)")
        if self._req.error is not None:
            raise self._req.error
        return self._req.result

    def token_times(self):
        """(t_first_token, t_last_token) perf_counter stamps — the
        bench's ITL source (None until the request finishes)."""
        return self._req.t_first, self._req.t_last


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class GenerationEngine:
    """Continuous-batching generation server over a paged KV cache.

    ``shapes`` are PROMPT-LENGTH buckets (ints, or 1-tuples): each gets
    its own sealed prefill executable; the decode loop is ONE sealed
    executable for ``slots`` concurrent sequences regardless of length.

    >>> net = TransformerDecoderLM(vocab_size=64)
    >>> eng = GenerationEngine(net, [8, 16], slots=4, chunk=4)
    >>> toks = eng.predict(np.array([5, 3, 9]), max_new_tokens=12)

    Drop-in for the repository/fleet: same submit/predict/stats/
    lifecycle surface as :class:`InferenceEngine`."""

    # machine-checked lock protocol (mxtpu-lint thread-guard rule)
    _GUARDED_BY = {
        "_queue": "_lock",
        "_closing": "_lock",
        "_killed": "_lock",
        "_paused": "_lock",
    }

    def __init__(self, net, shapes, *, slots=None, chunk=None,
                 queue_cap=None, cache_blocks=None, cache_block_size=None,
                 max_new_default=None, seed=0, name="model", version="v1",
                 autostart=True, ctx=None, dtype=None):
        for attr in ("decode_step_fn", "prefill_fn", "params",
                     "decode_dims"):
            if not hasattr(net, attr):
                raise MXNetError(
                    f"{type(net).__name__} has no {attr} — generation "
                    "needs a decode-capable net (e.g. "
                    "serving.TransformerDecoderLM)")
        self._name = str(name)
        self._version = str(version)
        self._net = net
        dims = net.decode_dims()
        self.max_seq = int(dims["max_seq"])
        self.vocab_size = int(dims["vocab_size"])
        self._slots = int(slots) if slots is not None else decode_slots()
        self._chunk = int(chunk) if chunk is not None else decode_chunk()
        self._max_new_default = (int(max_new_default) if max_new_default
                                 is not None else decode_max_new())
        self._queue_cap = (int(queue_cap) if queue_cap is not None
                           else serve_queue_cap())
        self._buckets = self._normalize_buckets(shapes)
        self.cache = PagedKVCache(
            dims["layers"], dims["kv_heads"], dims["head_dim"],
            max_seq=self.max_seq, num_blocks=cache_blocks,
            block_size=cache_block_size, name=self._name)
        self._mb = self.cache.max_blocks_per_seq
        self._lock = threading.Lock()
        self._queue = collections.deque()
        self._closing = False
        self._closed = False
        self._killed = False
        self._paused = False
        self._work = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        # engine-local SLO state (real numbers with telemetry off)
        self._itl = collections.deque(maxlen=8192)
        self._tokens = 0
        self._chunks = 0
        self._prefills = 0
        self._requests_ok = 0
        self._refused = 0
        self._shed = 0
        self._timeouts = 0
        self._failed = 0
        self._compiles = 0
        self._decode_wall = 0.0
        self._sealed = False
        # slot state (scheduler-thread-private after start)
        n = self._slots
        self._slot_req = [None] * n
        self._slot_tables = [None] * n
        self._lens = _np.zeros(n, _np.int32)
        self._token = _np.zeros(n, _np.int32)
        self._active = _np.zeros(n, bool)
        self._remaining = _np.zeros(n, _np.int32)
        self._temp = _np.ones(n, _np.float32)
        self._topk = _np.zeros(n, _np.int32)
        self._topp = _np.ones(n, _np.float32)
        self._greedy = _np.ones(n, bool)
        self._eos = _np.full(n, -1, _np.int32)
        self._deploy(seed)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"mxtpu-genserve-{self._name}")
        if autostart:
            self._thread.start()

    @staticmethod
    def _normalize_buckets(shapes):
        if base.is_int(shapes):
            shapes = [shapes]
        out = set()
        for s in shapes:
            if isinstance(s, (tuple, list)):
                if len(s) != 1:
                    raise MXNetError(
                        "generation buckets are PROMPT LENGTHS (ints or "
                        f"1-tuples); got {s!r}")
                s = s[0]
            out.add(int(s))
        buckets = sorted(out)
        if not buckets or buckets[0] <= 0:
            raise MXNetError(f"invalid prompt buckets {shapes!r}")
        return buckets

    # -- deploy: build + AOT-compile + warm + seal -------------------------
    def _deploy(self, seed):
        import jax
        import jax.numpy as jnp

        step = self._net.decode_step_fn()
        prefill = self._net.prefill_fn()
        params = self._net.params()
        chunk_t = self._chunk

        def chunk_fn(params, k_pool, v_pool, tables, lens, token, active,
                     remaining, rng, temp, top_k, top_p, greedy, eos):
            def body(carry, _):
                k_pool, v_pool, lens, token, active, remaining, rng = carry
                logits, k_pool, v_pool = step(params, token, lens, k_pool,
                                              v_pool, tables, active)
                rng, sub = jax.random.split(rng)
                nxt = sample_tokens(logits, sub, temp, top_k, top_p,
                                    greedy)
                emitted = active
                nxt = jnp.where(emitted, nxt, 0)
                lens = lens + active.astype(lens.dtype)
                remaining = remaining - active.astype(remaining.dtype)
                hit_eos = (nxt == eos) & (eos >= 0)
                active = active & ~hit_eos & (remaining > 0)
                return ((k_pool, v_pool, lens, nxt, active, remaining,
                         rng), (nxt, emitted))

            carry = (k_pool, v_pool, lens, token, active, remaining, rng)
            carry, (toks, flags) = jax.lax.scan(body, carry, None,
                                                length=chunk_t)
            k_pool, v_pool, lens, token, active, remaining, rng = carry
            return (k_pool, v_pool, lens, token, active, remaining, rng,
                    toks, flags)

        def prefill_fn(params, tokens, k_pool, v_pool, table, length,
                       seed_v, temp, top_k, top_p, greedy):
            logits, k_pool, v_pool = prefill(params, tokens, k_pool,
                                             v_pool, table, length)
            key = jax.random.fold_in(jax.random.PRNGKey(0), seed_v[0])
            tok = sample_tokens(logits, key, temp, top_k, top_p, greedy)
            return tok, k_pool, v_pool

        n, mb = self._slots, self._mb
        self._params = params
        self._rng = jax.random.PRNGKey(int(seed))
        k_shape = self.cache.k_pool
        chunk_args = (params, k_shape, self.cache.v_pool,
                      jnp.zeros((n, mb), jnp.int32),
                      jnp.zeros(n, jnp.int32), jnp.zeros(n, jnp.int32),
                      jnp.zeros(n, bool), jnp.zeros(n, jnp.int32),
                      self._rng, jnp.ones(n, jnp.float32),
                      jnp.zeros(n, jnp.int32), jnp.ones(n, jnp.float32),
                      jnp.ones(n, bool), jnp.full(n, -1, jnp.int32))
        jfn = jax.jit(chunk_fn, donate_argnums=(1, 2))
        t0 = time.perf_counter()
        self._chunk_exe = jfn.lower(*chunk_args).compile()
        self._record_compile("decode_chunk", t0)
        if _obs.introspect.ENABLED \
                and not _obs.introspect.registered("decode_chunk"):
            _obs.introspect.register_jit(
                "decode_chunk", jfn,
                _obs.introspect.avals_of(chunk_args), donated=True)
        # warm run: all slots inactive -> writes land in the null block,
        # lens unchanged, rng advances; adopts the returned pools
        out = self._chunk_exe(*chunk_args)
        jax.block_until_ready(out[0])
        self.cache.update_pools(out[0], out[1])
        self._rng = out[6]

        self._prefill_exes = {}
        jpf = jax.jit(prefill_fn, donate_argnums=(2, 3))
        for tb in self._buckets:
            if tb > self.max_seq:
                raise MXNetError(
                    f"prompt bucket {tb} exceeds the net's max_seq "
                    f"{self.max_seq}")
            args = (params, jnp.zeros((1, tb), jnp.int32),
                    self.cache.k_pool, self.cache.v_pool,
                    jnp.zeros((1, mb), jnp.int32),
                    jnp.zeros(1, jnp.int32), jnp.zeros(1, jnp.int32),
                    jnp.ones(1, jnp.float32), jnp.zeros(1, jnp.int32),
                    jnp.ones(1, jnp.float32), jnp.ones(1, bool))
            t0 = time.perf_counter()
            exe = jpf.lower(*args).compile()
            self._prefill_exes[tb] = exe
            self._record_compile(f"decode_prefill[{tb}]", t0)
            site = f"decode_prefill[{self._name}:{tb}]"
            if _obs.introspect.ENABLED \
                    and not _obs.introspect.registered(site):
                _obs.introspect.register_jit(
                    site, jpf, _obs.introspect.avals_of(args),
                    donated=True)
            # warm run: length 0 -> every write goes to the null block
            tok, kp, vp = exe(*args)
            jax.block_until_ready(tok)
            self.cache.update_pools(kp, vp)
        self._sealed = True

    def _record_compile(self, what, t0):
        self._compiles += 1
        if _obs.ENABLED:
            _obs.SERVE_COMPILE_TOTAL.inc(1, model=self._name)
            _obs.tracer().record(
                "serving.compile", cat="serving", ts=t0,
                dur=time.perf_counter() - t0,
                args={"model": self._name, "version": self._version,
                      "bucket": str(what)})

    # -- submit path -------------------------------------------------------
    def _bucket_for(self, plen):
        for tb in self._buckets:
            if plen <= tb:
                return tb
        return None

    def submit(self, x, max_new_tokens=None, temperature=1.0, top_k=0,
               top_p=1.0, greedy=True, seed=None, eos=None,
               deadline_ms=None, **_ignored) -> GenerateFuture:
        """Queue one prompt (1-D int token array; a leading singleton
        batch axis is squeezed). Typed refusals mirror the one-shot
        engine: :class:`EngineClosed`, :class:`ServerOverloaded` (queue
        full), :class:`RetraceForbidden` (no prompt bucket fits —
        sealed, never compiles). ``max_new_tokens`` is clipped so
        ``prompt + generated <= max_seq``."""
        prompt = _np.asarray(x)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1 or prompt.size == 0:
            raise ServingError(
                "generation takes ONE 1-D prompt of token ids per "
                f"submit; got shape {prompt.shape}")
        prompt = prompt.astype(_np.int32)
        plen = int(prompt.size)
        bucket = self._bucket_for(plen)
        if bucket is None or plen >= self.max_seq:
            self._refused += 1
            if _obs.ENABLED:
                _obs.record_serve_request(self._name, "error")
            raise RetraceForbidden(
                f"sealed generation engine {self._name}:{self._version} "
                f"has no prefill bucket for prompt length {plen} "
                f"(cause: shape; retrace budget is 0 after warmup). "
                f"Known buckets: {self._buckets}, max_seq {self.max_seq}. "
                "Truncate the prompt, or add a bucket and redeploy.")
        max_new = int(max_new_tokens) if max_new_tokens else \
            self._max_new_default
        max_new = max(1, min(max_new, self.max_seq - plen))
        deadline = (time.perf_counter() + float(deadline_ms) / 1e3
                    if deadline_ms else None)
        req = _GenRequest(
            prompt, max_new, temperature, top_k, top_p, greedy,
            seed if seed is not None else _np.random.randint(1 << 30),
            eos if eos is not None else -1, deadline)
        with self._lock:
            if self._closing or self._killed or self._paused:
                if _obs.ENABLED:
                    _obs.record_serve_request(self._name, "closed")
                raise EngineClosed(
                    f"generation engine {self._name}:{self._version} is "
                    "not accepting requests "
                    f"({'paused' if self._paused else 'closed'})")
            if len(self._queue) >= self._queue_cap:
                self._shed += 1
                if _obs.ENABLED:
                    _obs.record_serve_request(self._name, "shed")
                raise ServerOverloaded(
                    f"generation queue full ({self._queue_cap}) on "
                    f"{self._name}:{self._version} — retry with backoff")
            self._queue.append(req)
            self._idle.clear()
        self._work.set()
        if _obs.ENABLED:
            _obs.SERVE_QUEUE_DEPTH.set(self.queue_depth(),
                                       model=self._name)
        return GenerateFuture(req)

    def predict(self, x, timeout=None, **kwargs):
        """Synchronous generation: submit + wait; returns np.int32
        generated token ids."""
        return self.submit(x, **kwargs).result(timeout)

    # -- scheduler loop ----------------------------------------------------
    def _loop(self):
        while True:
            with self._lock:
                killed = self._killed
            if killed:
                self._abort_all(ReplicaDead(
                    f"generation engine {self._name}:{self._version} was "
                    "killed (host-death simulation) — request failed over "
                    "by the fleet router"))
                return
            self._admit()
            if self._active.any():
                self._step_chunk()
                continue
            with self._lock:
                drained = not self._queue
                closing = self._closing
            if drained:
                self._idle.set()
                if closing:
                    return
            self._work.wait(0.02)
            self._work.clear()

    def _fail(self, req, err, code):
        self._failed += 1
        if _obs.ENABLED:
            _obs.record_serve_request(self._name, code)
        req.finish(error=err, version=self._version)

    def _admit(self):
        """Join queued requests to idle slots (iteration-level
        scheduling): sweep deadlines, then prefill into free slots
        while the cache can back the prompt."""
        now = time.perf_counter()
        with self._lock:
            q = list(self._queue)
        for req in q:
            if req.deadline is not None and now > req.deadline \
                    and not req.claimed:
                with self._lock:
                    try:
                        self._queue.remove(req)
                    except ValueError:
                        continue
                self._timeouts += 1
                self._fail(req, RequestTimeout(
                    "generation deadline expired before a slot opened"),
                    "timeout")
        while True:
            free = [s for s in range(self._slots) if not self._active[s]
                    and self._slot_req[s] is None]
            if not free:
                return
            with self._lock:
                req = self._queue.popleft() if self._queue else None
            if req is None:
                return
            if not req.claim():  # lost to cancel()
                continue
            try:
                table = self.cache.allocate(len(req.prompt))
            except KVCacheOOM as e:
                if self._active.any():
                    # blocks free as running sequences retire: put the
                    # request back and retry after the next chunk
                    with req._state_lock:
                        req.claimed = False
                    with self._lock:
                        self._queue.appendleft(req)
                    return
                self._fail(req, e, "shed")
                continue
            try:
                self._prefill(req, table, free[0])
            except BaseException as e:  # noqa: BLE001 - typed to waiter
                self.cache.release(table)
                self._fail(req, e if isinstance(e, ServingError) else
                           ServingError(f"prefill failed: {e}"), "error")

    def _prefill(self, req, table, slot):
        import jax.numpy as jnp

        plen = len(req.prompt)
        tb = self._bucket_for(plen)
        padded = _np.zeros((1, tb), _np.int32)
        padded[0, :plen] = req.prompt
        k, v = self.cache.pools()
        t0 = time.perf_counter()
        tok, k, v = self._prefill_exes[tb](
            self._params, jnp.asarray(padded), k, v,
            table.device_row(self._mb)[None, :],
            _np.array([plen], _np.int32),  # mxtpu-lint: host-sync-ok
            _np.array([req.seed], _np.int32),  # mxtpu-lint: host-sync-ok
            _np.array([max(req.temperature, 1e-6)], _np.float32),  # mxtpu-lint: host-sync-ok
            _np.array([req.top_k], _np.int32),  # mxtpu-lint: host-sync-ok
            _np.array([req.top_p], _np.float32),  # mxtpu-lint: host-sync-ok
            _np.array([req.greedy], bool))  # host operand staging  # mxtpu-lint: host-sync-ok
        self.cache.update_pools(k, v)
        # the ONE deliberate per-request sync: the first token decides
        # retire-or-seat before the next chunk can include this slot
        first = int(_np.asarray(tok)[0])  # mxtpu-lint: host-sync-ok
        dt = time.perf_counter() - t0
        table.length = plen
        self._prefills += 1
        now = time.perf_counter()
        req.tokens.append(first)
        req.t_first = req.t_last = now
        self._tokens += 1
        if _obs.ENABLED:
            _obs.record_xla_dispatch("decode_prefill")
            _obs.DECODE_PREFILL_SECONDS.observe(dt, model=self._name)
            _obs.DECODE_TOKENS_TOTAL.inc(1, model=self._name)
        done = (req.max_new <= 1
                or (req.eos >= 0 and first == req.eos))
        if done:
            self._retire(req, table)
            return
        self._slot_req[slot] = req
        self._slot_tables[slot] = table
        self._lens[slot] = plen  # next decode step writes position plen
        self._token[slot] = first
        self._active[slot] = True
        self._remaining[slot] = req.max_new - 1
        self._temp[slot] = max(req.temperature, 1e-6)
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p
        self._greedy[slot] = req.greedy
        self._eos[slot] = req.eos
        if _obs.ENABLED:
            _obs.DECODE_ACTIVE_SLOTS.set(
                int(self._active.sum()),  # host numpy mirror  # mxtpu-lint: host-sync-ok
                model=self._name)

    def _step_chunk(self):
        """One decode dispatch: every active slot advances up to
        ``chunk`` tokens; retirements free their slots and cache blocks
        at the boundary (where the NEXT _admit can seat a newcomer)."""
        import jax.numpy as jnp

        # back the chunk's cache growth per slot; a pool too full to
        # grow a sequence retires that request early (typed OOM)
        for s in range(self._slots):
            if not self._active[s]:
                continue
            need = int(self._lens[s]) + min(  # mxtpu-lint: host-sync-ok
                self._chunk,
                int(self._remaining[s]))  # host numpy mirror  # mxtpu-lint: host-sync-ok
            try:
                self.cache.ensure(self._slot_tables[s],
                                  min(need, self.max_seq))
            except KVCacheOOM as e:
                req = self._slot_req[s]
                self.cache.release(self._slot_tables[s])
                self._clear_slot(s)
                self._fail(req, e, "shed")
        if not self._active.any():
            return
        tables = _np.zeros((self._slots, self._mb), _np.int32)
        for s in range(self._slots):
            if self._slot_tables[s] is not None:
                tables[s] = self._slot_tables[s].device_row(self._mb)
        k, v = self.cache.pools()
        t0 = time.perf_counter()
        (k, v, lens, token, active, remaining, rng, toks, flags) = \
            self._chunk_exe(
                self._params, k, v, jnp.asarray(tables),
                jnp.asarray(self._lens), jnp.asarray(self._token),
                jnp.asarray(self._active), jnp.asarray(self._remaining),
                self._rng, jnp.asarray(self._temp),
                jnp.asarray(self._topk), jnp.asarray(self._topp),
                jnp.asarray(self._greedy), jnp.asarray(self._eos))
        self.cache.update_pools(k, v)
        self._rng = rng
        # ONE host sync per chunk: everything the scheduler needs
        # (np.array copies — jax device views are read-only and the
        # slot mirrors are mutated at admission)
        toks = _np.asarray(toks)  # (chunk, slots)  # mxtpu-lint: host-sync-ok
        flags = _np.asarray(flags)  # mxtpu-lint: host-sync-ok
        self._lens = _np.array(lens)  # mxtpu-lint: host-sync-ok
        self._token = _np.array(token)  # mxtpu-lint: host-sync-ok
        self._active = _np.array(active)  # mxtpu-lint: host-sync-ok
        self._remaining = _np.array(remaining)  # mxtpu-lint: host-sync-ok
        dt = time.perf_counter() - t0
        self._decode_wall += dt
        self._chunks += 1
        now = time.perf_counter()
        emitted_total = 0
        for s in range(self._slots):
            req = self._slot_req[s]
            if req is None:
                continue
            mask = flags[:, s]
            n = int(mask.sum())  # host numpy  # mxtpu-lint: host-sync-ok
            if n:
                req.tokens.extend(
                    int(t) for t in toks[mask, s])  # mxtpu-lint: host-sync-ok
                # tokens of one chunk arrive together: the honest
                # inter-token latency is the amortized chunk wall time
                per_tok = dt / n
                if req.t_first is None:
                    req.t_first = now
                req.t_last = now
                for _ in range(n):
                    self._itl.append(per_tok)
                if _obs.ENABLED:
                    _obs.DECODE_ITL_SECONDS.observe(per_tok,
                                                    model=self._name)
                emitted_total += n
            if not self._active[s]:
                table = self._slot_tables[s]
                self._clear_slot(s)
                self._retire(req, table)
        self._tokens += emitted_total
        if _obs.ENABLED:
            _obs.record_xla_dispatch("decode_chunk")
            _obs.DECODE_CHUNKS_TOTAL.inc(1, model=self._name)
            if emitted_total:
                _obs.DECODE_TOKENS_TOTAL.inc(emitted_total,
                                             model=self._name)
            _obs.DECODE_ACTIVE_SLOTS.set(
                int(self._active.sum()),  # host numpy mirror  # mxtpu-lint: host-sync-ok
                model=self._name)

    def _clear_slot(self, s):
        self._slot_req[s] = None
        self._slot_tables[s] = None
        self._active[s] = False
        self._lens[s] = 0
        self._token[s] = 0
        self._remaining[s] = 0

    def _retire(self, req, table):
        self.cache.release(table)
        self._requests_ok += 1
        if _obs.ENABLED:
            _obs.record_serve_request(self._name, "ok")
            _obs.SERVE_LATENCY_SECONDS.observe(
                time.perf_counter() - req.t_submit, model=self._name)
        req.finish(result=_np.asarray(req.tokens, _np.int32),
                   version=self._version)

    def _abort_all(self, err):
        with self._lock:
            queued = list(self._queue)
            self._queue.clear()
        for req in queued:
            self._fail(req, err, "closed")
        for s in range(self._slots):
            req = self._slot_req[s]
            if req is not None:
                if self._slot_tables[s] is not None:
                    self.cache.release(self._slot_tables[s])
                self._clear_slot(s)
                self._fail(req, err, "closed")
        self._idle.set()

    # -- introspection -----------------------------------------------------
    @property
    def version(self):
        return self._version

    @property
    def buckets(self):
        """Prompt-length buckets, 1-tuples (InferenceEngine shape)."""
        return [(b,) for b in self._buckets]

    @property
    def sealed(self):
        return self._sealed

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def active_slots(self) -> int:
        return int(self._active.sum())

    def stats(self) -> dict:
        """Engine-local snapshot (plain floats; telemetry-independent).
        ``retraces_after_warmup`` is structurally 0: every executable is
        AOT-sealed and slot liveness is an operand, never a shape."""
        itl = _np.asarray(self._itl, _np.float64) if self._itl else None
        dispatches = self._chunks + self._prefills
        return {
            "model": self._name,
            "version": self._version,
            "engine": "generation",
            "buckets": list(self._buckets),
            "slots": self._slots,
            "chunk": self._chunk,
            "requests_ok": self._requests_ok,
            "refused": self._refused,
            "shed": self._shed,
            "timeouts": self._timeouts,
            "failed": self._failed,
            "tokens_generated": self._tokens,
            "prefills": self._prefills,
            "decode_chunks": self._chunks,
            "dispatches": dispatches,
            "tokens_per_dispatch": self._tokens / max(1, dispatches),
            "tokens_per_s": (self._tokens / self._decode_wall
                             if self._decode_wall else 0.0),
            "itl_p50_ms": (float(_np.percentile(itl, 50)) * 1e3
                           if itl is not None else None),
            "itl_p99_ms": (float(_np.percentile(itl, 99)) * 1e3
                           if itl is not None else None),
            "queue_depth": self.queue_depth(),
            "active_slots": self.active_slots(),
            "compiles": self._compiles,
            "retraces_after_warmup": 0 if self._sealed else None,
            "recompiles_after_warmup": 0 if self._sealed else None,
            "cache": self.cache.stats(),
        }

    def canary(self):
        """Deploy-time verification: a short greedy generation must
        return in-vocabulary token ids (the repository's staged-load
        veto for generation engines — finite-logits NaN screens ride
        the argmax: NaN logits produce out-of-range/degenerate ids)."""
        started = self._thread.is_alive()
        if not started:
            self._thread.start()
        toks = self.predict(_np.array([1, 2], _np.int32),
                            max_new_tokens=2, greedy=True, timeout=60.0)
        if len(toks) == 0 or _np.any(toks < 0) \
                or _np.any(toks >= self.vocab_size):
            raise ServingError(
                f"generation canary produced out-of-vocabulary ids "
                f"{toks!r} — refusing to serve this version")
        return toks

    # -- lifecycle ---------------------------------------------------------
    def pause(self):
        """Stop accepting work and drain: queued + in-flight
        generations complete, executables and pools stay resident
        (repository standby — resume() is a flag flip)."""
        with self._lock:
            if self._paused or self._closing:
                return
            self._paused = True
        self._work.set()
        self._idle.wait(timeout=120.0)

    def resume(self):
        with self._lock:
            if self._closing or self._killed:
                raise EngineClosed(
                    f"engine {self._name}:{self._version} was released; "
                    "reload instead of resume")
            self._paused = False

    def kill(self):
        """Abrupt host-death simulation: queued AND in-flight requests
        fail with typed :class:`ReplicaDead` (the fleet router fails
        them over); nothing drains. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._killed = True
            self._closing = True
        self._work.set()
        if self._thread.is_alive():
            self._thread.join(timeout=30.0)
        else:
            self._abort_all(ReplicaDead(
                f"generation engine {self._name}:{self._version} killed"))
        self._release()

    def close(self):
        """Drain queued + in-flight generations, then release
        executables, pools, and weight references. Idempotent."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
        self._work.set()
        if self._thread.is_alive():
            self._thread.join(timeout=120.0)
        self._abort_all(EngineClosed(
            f"generation engine {self._name}:{self._version} closed"))
        self._release()

    def _release(self):
        self._closed = True
        self._chunk_exe = None
        self._prefill_exes = {}
        self._params = None
        self.cache.k_pool = None
        self.cache.v_pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
