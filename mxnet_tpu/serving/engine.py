"""InferenceEngine: AOT shape-bucket executables + continuous batching.

Deploy path (all the compilation happens HERE, never per request):

1. ``net.aot_predict_fn()`` (HybridBlock, or a calibrated
   ``QuantizedNet`` for int8) gives the pure inference function;
2. ``jax.jit(fn).lower(params, batch).compile()`` builds ONE executable
   per declared shape bucket — ahead of time, warmed through the
   persistent compile cache (``MXTPU_COMPILE_CACHE``), each warmed with
   one throwaway batch so request 1 runs at steady state;
3. the engine SEALS: retrace budget is zero. A request whose signature
   matches no bucket is refused loudly with a typed
   :class:`RetraceForbidden` naming the cause
   (``gluon.block.signature_causes`` — the CachedGraph's retrace-cause
   machinery), never compiled for.

Weights stay device-resident and are passed to the executable per call
(NEVER donated — the same buffers serve every request, and a live
``ModelRepository`` swap just hands the next engine its own buffers).

Request path: ``submit()`` pads the request's rows onto its bucket
(``shape_guard.pad_to_shape`` / ``SequenceBucketer`` selection) and
queues it; the :class:`ContinuousBatcher` scheduler groups requests per
bucket and ``_execute`` stacks them, pads the partial batch to
``max_batch`` with ``shape_guard.pad_batch``, runs the ONE matching
executable, and unpads on the way out — only the validity prefix of the
batch axis is ever returned, pad rows never leak into results.
"""

from __future__ import annotations

import time

import numpy as _np

from .. import observability as _obs
from ..base import MXNetError, getenv
from ..observability import flight as _flight
from ..observability.metrics import Histogram as _Histogram
from .batcher import ContinuousBatcher, ServeFuture, _Request
from .errors import (
    EngineClosed,
    RequestTooLarge,
    RetraceForbidden,
    ServerOverloaded,
)

_MAX_BATCH_DEFAULT = 8
_MAX_WAIT_MS_DEFAULT = 5.0
_QUEUE_DEFAULT = 256


def serve_max_batch() -> int:
    """Batch capacity (rows) per dispatch, ``MXTPU_SERVE_MAX_BATCH``."""
    return max(1, int(getenv("MXTPU_SERVE_MAX_BATCH", _MAX_BATCH_DEFAULT,
                             dtype=int)))


def serve_max_wait_ms() -> float:
    """Longest a partial batch waits for fill before dispatching,
    ``MXTPU_SERVE_MAX_WAIT_MS`` (the latency/throughput knob)."""
    return float(getenv("MXTPU_SERVE_MAX_WAIT_MS", _MAX_WAIT_MS_DEFAULT,
                        dtype=float))


def serve_queue_cap() -> int:
    """Bounded submit-queue depth (requests) before load shedding,
    ``MXTPU_SERVE_QUEUE``."""
    return max(1, int(getenv("MXTPU_SERVE_QUEUE", _QUEUE_DEFAULT,
                             dtype=int)))


class InferenceEngine:
    """Serve one model version: sealed AOT executables behind a
    continuous batcher.

    ``shapes``: one per-ROW input shape (no batch dim) or a list of
    them — the shape buckets, e.g. ``[(8, 16), (16, 16), (32, 16)]``
    for ragged sequences. Shapes varying along exactly one axis get
    :class:`SequenceBucketer` smallest-fitting-bucket selection; any
    request row shape elementwise <= a bucket pads onto it.

    >>> eng = InferenceEngine(net, shapes=[(16,), (32,)], max_batch=8)
    >>> y = eng.predict(x)                  # sync, one row or a few
    >>> fut = eng.submit(x, deadline_ms=50) # async with a deadline
    >>> fut.result(), fut.version
    """

    def __init__(self, net, shapes, *, ctx=None, dtype="float32",
                 max_batch=None, max_wait_ms=None, queue_cap=None,
                 name="model", version="v1", autostart=True):
        from ..context import current_context

        self._name = str(name)
        self._version = str(version)
        self._ctx = ctx or current_context()
        self._dtype = _np.dtype(dtype)
        self._max_batch = int(max_batch) if max_batch is not None \
            else serve_max_batch()
        self._max_wait = (float(max_wait_ms) if max_wait_ms is not None
                          else serve_max_wait_ms()) / 1e3
        self._queue_cap = int(queue_cap) if queue_cap is not None \
            else serve_queue_cap()
        self._buckets = self._normalize_shapes(shapes)
        self._rank = len(self._buckets[0])
        self._bucketer = self._build_bucketer()
        self._compiled = {}
        self._single = True
        self._params = None
        self._fn = None
        self._sealed = False
        self._closed = False
        self._paused = False
        # engine-local SLO state: independent of the global telemetry
        # switch, so stats()/bench read real numbers with telemetry off
        self._latency = _Histogram("local_latency")
        self._fill_sum = 0.0
        self._batches = 0
        self._requests_ok = 0
        self._refused = 0
        self._shed = 0
        self._timeouts = 0
        self._compiles = 0
        self._deploy(net)
        self._batcher = ContinuousBatcher(
            self._execute, max_batch=self._max_batch,
            max_wait=self._max_wait, queue_cap=self._queue_cap,
            on_expire=self._on_expire, autostart=autostart,
            name=self._name)

    # -- bucket geometry ---------------------------------------------------
    @staticmethod
    def _normalize_shapes(shapes):
        if isinstance(shapes, tuple) or (
                isinstance(shapes, list) and shapes and
                not isinstance(shapes[0], (tuple, list))):
            shapes = [shapes]
        buckets = sorted({tuple(int(d) for d in s) for s in shapes},
                         key=lambda b: (int(_np.prod(b)), b))
        if not buckets or any(d <= 0 for b in buckets for d in b):
            raise MXNetError(f"invalid serving shape buckets {shapes!r}")
        if len({len(b) for b in buckets}) != 1:
            raise MXNetError(
                f"serving shape buckets must share one rank, got {buckets}")
        return buckets

    def _build_bucketer(self):
        """Shapes varying along exactly one axis -> SequenceBucketer
        selection on that axis (the ragged-sequence fast path)."""
        from ..gluon.data.shape_guard import SequenceBucketer

        if len(self._buckets) < 2:
            return None
        varying = [i for i in range(self._rank)
                   if len({b[i] for b in self._buckets}) > 1]
        if len(varying) != 1:
            return None
        return SequenceBucketer([b[varying[0]] for b in self._buckets],
                                axis=varying[0])

    def _bucket_for(self, row_shape):
        """Smallest bucket every dim of ``row_shape`` fits in; typed
        refusal (never a compile) when none does."""
        if self._bucketer is not None:
            ax = self._bucketer.axis
            try:
                target = self._bucketer.bucket_for(int(row_shape[ax]))
            except MXNetError:
                target = None
            if target is not None:
                cand = tuple(target if i == ax else d
                             for i, d in enumerate(row_shape))
                if cand in self._compiled:
                    return cand
        else:
            fits = [b for b in self._buckets
                    if all(d <= t for d, t in zip(row_shape, b))]
            if fits:
                return fits[0]  # buckets sorted smallest-first
        self._refuse(row_shape)

    def _refuse(self, row_shape, got_dtype=None):
        from ..gluon.block import signature_causes

        got_dtype = str(got_dtype or self._dtype)
        closest = min(self._buckets,
                      key=lambda b: sum(abs(d - t) for d, t in
                                        zip(row_shape, b))
                      if len(b) == len(row_shape) else float("inf"))
        causes = signature_causes(
            ((closest, str(self._dtype)),), ((tuple(row_shape), got_dtype),))
        self._refused += 1
        if _obs.ENABLED:
            _obs.record_serve_request(self._name, "error")
        raise RetraceForbidden(
            f"sealed serving engine {self._name}:{self._version} has no "
            f"executable for row signature {tuple(row_shape)}/{got_dtype} "
            f"(cause: {'+'.join(causes) or 'unknown'}; retrace budget is 0 "
            f"after warmup). Known buckets: {self._buckets} @ "
            f"{self._dtype.name}. Pad/bucket the client input, or add a "
            f"bucket and redeploy.")

    # -- deploy (AOT compile, seal) ----------------------------------------
    def _deploy(self, net):
        import jax
        import jax.numpy as jnp

        if not hasattr(net, "aot_predict_fn"):
            raise MXNetError(
                f"{type(net).__name__} has no aot_predict_fn — serve a "
                "HybridBlock (or contrib.quantization.QuantizedNet)")
        fn, param_raws = net.aot_predict_fn(
            ctx=self._ctx, dtype=self._dtype.name,
            sample_shape=(1,) + self._buckets[0])
        self._fn = fn
        self._params = param_raws  # device-resident; reused, never donated
        jfn = jax.jit(fn)
        for bucket in self._buckets:
            x = jnp.zeros((self._max_batch,) + bucket, self._dtype.name)
            t0 = time.perf_counter()
            compiled = jfn.lower(self._params, x).compile()
            self._compiled[bucket] = compiled
            self._compiles += 1
            if _obs.ENABLED:
                _obs.SERVE_COMPILE_TOTAL.inc(1, model=self._name)
                _obs.tracer().record(
                    "serving.compile", cat="serving",
                    ts=t0, dur=time.perf_counter() - t0,
                    args={"model": self._name, "version": self._version,
                          "bucket": str(bucket)})
            if _obs.introspect.ENABLED:
                site = f"serving[{self._name}:{'x'.join(map(str, bucket))}]"
                if not _obs.introspect.registered(site):
                    # nets may SANCTION graphcheck rules for their
                    # lowered form: QuantizedNet bakes its calibrated
                    # stage payloads as closure consts by design
                    sanction = getattr(net, "_GRAPHCHECK_CONST_OK", None)
                    meta = ({"disable": ("baked-constant",),
                             "reason": str(sanction)}
                            if sanction else None)
                    _obs.introspect.register_jit(site, jfn,
                                                 (self._params, x),
                                                 graph_meta=meta)
            # warm execution: request 1 must run at steady state
            out = compiled(self._params, x)
            self._single = not isinstance(out, (tuple, list))
            jax.block_until_ready(out)
        self._sealed = True

    # -- request path ------------------------------------------------------
    def submit(self, x, deadline_ms=None, cast=True) -> ServeFuture:
        """Queue one request (a single row, or a micro-batch with a
        leading rows axis, ``rows <= max_batch``). Raises typed errors:
        :class:`ServerOverloaded` (queue full), :class:`RequestTooLarge`,
        :class:`RetraceForbidden` (no bucket), :class:`EngineClosed`.
        ``deadline_ms``: drop (typed timeout) if not dispatched in time.
        ``cast=False`` refuses dtype mismatches instead of converting."""
        if self._closed or self._paused:
            if _obs.ENABLED:
                _obs.record_serve_request(self._name, "closed")
            raise EngineClosed(
                f"engine {self._name}:{self._version} is "
                f"{'closed' if self._closed else 'paused (standby)'}")
        arr = x.asnumpy() if hasattr(x, "asnumpy") else _np.asarray(x)
        if not cast and arr.dtype != self._dtype:
            self._refuse(arr.shape[1:] if arr.ndim == self._rank + 1
                         else arr.shape, got_dtype=arr.dtype)
        arr = _np.asarray(arr, self._dtype)
        if arr.ndim == self._rank:
            arr = arr[None]  # single row convenience
        if arr.ndim != self._rank + 1 or arr.shape[0] < 1:
            self._refuse(arr.shape)
        rows = int(arr.shape[0])
        if rows > self._max_batch:
            if _obs.ENABLED:
                _obs.record_serve_request(self._name, "too_large")
            raise RequestTooLarge(
                f"request carries {rows} rows > max_batch "
                f"{self._max_batch} (MXTPU_SERVE_MAX_BATCH) — it can "
                "never fit one dispatch; split it client-side")
        bucket = self._bucket_for(arr.shape[1:])
        if arr.shape[1:] != bucket:
            from ..gluon.data.shape_guard import pad_to_shape

            arr = pad_to_shape(arr, (rows,) + bucket)
        deadline = None if deadline_ms is None else \
            time.perf_counter() + float(deadline_ms) / 1e3
        req = _Request(arr, rows, bucket, deadline=deadline)
        req.version = self._version
        if _obs.ENABLED:
            _obs.record_serve_submit(self._name, req.req_id)
        try:
            self._batcher.submit(req)
        except ServerOverloaded:
            self._shed += 1
            if _obs.ENABLED:
                _obs.record_serve_request(self._name, "shed")
            raise
        except EngineClosed:
            if _obs.ENABLED:
                _obs.record_serve_request(self._name, "closed")
            raise
        return ServeFuture(req)

    def predict(self, x, timeout=None, deadline_ms=None):
        """Synchronous request: submit + wait. Returns the host result
        (numpy; tuple for multi-output nets), pad rows stripped."""
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    def _on_expire(self, req):
        self._timeouts += 1
        if _obs.ENABLED:
            _obs.record_serve_request(self._name, "timeout")

    def _execute(self, bucket, reqs):
        """Batcher dispatch hook (scheduler thread): stack the group,
        pad to capacity, run the ONE sealed executable, unpad."""
        from ..gluon.data.shape_guard import pad_batch

        compiled = self._compiled.get(bucket)
        if compiled is None:  # cannot happen post-seal; refuse, not trace
            raise RetraceForbidden(
                f"no executable for bucket {bucket} (engine sealed)")
        # phase boundary 1: queue-wait ends, batch assembly begins
        t_asm = time.perf_counter()
        for r in reqs:
            r.t_assembly = t_asm
        stacked = _np.concatenate([r.payload for r in reqs], axis=0) \
            if len(reqs) > 1 else reqs[0].payload
        n_valid = int(stacked.shape[0])
        padded = stacked
        if n_valid < self._max_batch:
            padded, _mask = pad_batch(stacked, self._max_batch)
            # the mask's valid prefix is exactly rows [:n_valid] — the
            # unpad below slices it; pad rows never reach a result
        t0 = time.perf_counter()
        if _flight.INSTALLED:
            with _flight.dispatch("serving"):
                out = compiled(self._params, padded)
        else:
            out = compiled(self._params, padded)
        if _obs.ENABLED:
            _obs.record_xla_dispatch("serving")
        outs = (out,) if self._single else tuple(out)
        # results leave the process as host payloads: ONE sync per batch
        host = [_np.asarray(o) for o in outs]  # mxtpu-lint: host-sync-ok
        dt = time.perf_counter() - t0
        now = time.perf_counter()
        off = 0
        for r in reqs:
            rows = [h[off:off + r.rows] for h in host]
            off += r.rows
            r.finish(result=rows[0] if self._single else tuple(rows))
            self._requests_ok += 1
            self._latency.observe(now - r.t_submit)
            if _obs.ENABLED:
                _obs.record_serve_request(self._name, "ok",
                                          latency=now - r.t_submit)
        self._batches += 1
        self._fill_sum += n_valid / self._max_batch
        if _obs.ENABLED:
            t_done = time.perf_counter()
            # one batch span id parents every request's phase span —
            # the correlated-trace join key (queue -> batch -> dispatch
            # -> slice, per request; p99 becomes decomposable)
            batch_span = _obs.tracer().new_span_id()
            for r in reqs:
                _obs.record_serve_phases(
                    self._name, r.req_id, r.t_submit,
                    {"queue": t_asm - r.t_submit,
                     "batch": t0 - t_asm,
                     "dispatch": dt,
                     "slice": t_done - now},
                    parent=batch_span)
            _obs.record_serve_batch(self._name, bucket, n_valid,
                                    self._max_batch, dt,
                                    self._batcher.qsize(),
                                    span_id=batch_span)

    # -- introspection -----------------------------------------------------
    @property
    def name(self):
        return self._name

    @property
    def version(self):
        return self._version

    @property
    def buckets(self):
        return list(self._buckets)

    @property
    def sealed(self):
        return self._sealed

    def queue_depth(self) -> int:
        """Requests waiting in the admission queue right now — the
        router's local least-queue-depth signal (one qsize read)."""
        return self._batcher.qsize() if self._batcher is not None else 0

    def stats(self) -> dict:
        """Engine-local SLO snapshot (plain floats, works with global
        telemetry off). ``compiles`` is flat after seal — the
        zero-recompiles-after-warmup contract the bench asserts."""
        p50 = self._latency.quantile(0.5)
        p99 = self._latency.quantile(0.99)
        return {
            "model": self._name, "version": self._version,
            "buckets": [list(b) for b in self._buckets],
            "max_batch": self._max_batch,
            "requests_ok": self._requests_ok,
            "batches": self._batches,
            "mean_batch_fill": (self._fill_sum / self._batches)
            if self._batches else None,
            "latency_p50_ms": None if p50 is None else p50 * 1e3,
            "latency_p99_ms": None if p99 is None else p99 * 1e3,
            "shed": self._shed, "timeouts": self._timeouts,
            "refused": self._refused,
            "compiles": self._compiles,
            "retraces_after_warmup": 0 if self._sealed else None,
            "queue_depth": self._batcher.qsize() if self._batcher else 0,
        }

    # -- lifecycle ---------------------------------------------------------
    def pause(self):
        """Stop accepting work and DRAIN in-flight requests, keeping the
        executables and weights resident (repository standby — rollback
        is ``resume()``, not a recompile)."""
        if self._paused or self._closed:
            return
        self._paused = True
        self._batcher.close()

    def resume(self):
        """Reactivate a paused standby engine (repository rollback)."""
        if self._closed:
            raise EngineClosed(f"engine {self._name}:{self._version} was "
                               "released; reload instead of resume")
        if not self._paused:
            return
        self._batcher = ContinuousBatcher(
            self._execute, max_batch=self._max_batch,
            max_wait=self._max_wait, queue_cap=self._queue_cap,
            on_expire=self._on_expire)
        self._paused = False

    def kill(self):
        """Abrupt host-death simulation (fleet chaos certification):
        queued requests FAIL with a typed :class:`ReplicaDead` instead
        of draining — their waiters unblock immediately, and the fleet
        router fails them over to a surviving replica. Idempotent;
        a no-op after ``close()``."""
        from .errors import ReplicaDead

        if self._closed:
            return
        self._closed = True
        name = f"{self._name}:{self._version}"
        if self._batcher is not None:
            self._batcher.abort(lambda: ReplicaDead(
                f"engine {name} killed (abrupt host death) with this "
                "request queued — retry on a surviving replica"))
        self._compiled = {}
        self._params = None
        self._fn = None

    def close(self):
        """Drain in-flight requests, then release: executables and
        weight references dropped. Idempotent; matches the
        DevicePrefetcher contract (errors propagate to waiters, safe
        from ``__del__``)."""
        if self._closed:
            return
        self._closed = True
        if self._batcher is not None:
            self._batcher.close()
        self._compiled = {}
        self._params = None
        self._fn = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
