"""Device contexts.

Reference: ``python/mxnet/context.py`` (symbol ``Context``). The TPU-native
design maps a Context onto a concrete ``jax.Device``:

- ``mx.cpu(i)``   -> i-th host CPU device
- ``mx.tpu(i)``   -> i-th accelerator device of the default JAX backend
- ``mx.gpu(i)``   -> alias for ``mx.tpu(i)`` so reference model scripts run
  with a one-line (or zero-line) change.

A thread-local default-context stack backs ``with mx.Context(...)`` exactly
like the reference. Unlike the reference there is no stream or dev_mask —
XLA owns scheduling; a Context is only a placement annotation consumed by
``jax.device_put`` / jit sharding.
"""

from __future__ import annotations

import threading

import jax

from .base import MXNetError

_DEVTYPE_TO_ID = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
_ID_TO_DEVTYPE = {v: k for k, v in _DEVTYPE_TO_ID.items()}


def _accelerator_platform() -> str:
    try:
        return jax.default_backend()
    except Exception:  # pragma: no cover
        return "cpu"


_CACHE_WIRED = False


def _wire_compile_cache():
    """One-shot env hookups deferred to the first Context so plain
    imports never touch jax config (and the flag keeps
    Context.__init__ to one boolean check afterwards):
    MXTPU_COMPILE_CACHE, the MXTPU_METRICS_PORT scrape endpoint, the
    MXTPU_FEDERATION publisher and the MXTPU_WATCHDOG loop."""
    global _CACHE_WIRED
    _CACHE_WIRED = True
    from . import runtime

    runtime.setup_compile_cache()
    from .observability import serve as _serve

    _serve.maybe_serve()
    from .observability import federation as _federation
    from .observability import watchdog as _watchdog

    _federation.maybe_start()
    _watchdog.maybe_start()


class Context:
    """A device context. ``Context('tpu', 0)`` or ``Context(other_ctx)``."""

    _default_stack = threading.local()
    devtype2str = _ID_TO_DEVTYPE
    devstr2type = _DEVTYPE_TO_ID

    def __init__(self, device_type, device_id: int = 0):
        if not _CACHE_WIRED:
            _wire_compile_cache()
        if isinstance(device_type, Context):
            self.device_type, self.device_id = (
                device_type.device_type,
                device_type.device_id,
            )
        elif isinstance(device_type, int):
            self.device_type = _ID_TO_DEVTYPE[device_type]
            self.device_id = device_id
        else:
            if device_type not in _DEVTYPE_TO_ID:
                raise MXNetError(f"unknown device type {device_type!r}")
            self.device_type = device_type
            self.device_id = device_id
        self._old_ctx = None

    # -- identity ---------------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return _DEVTYPE_TO_ID[self.device_type]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self._canonical() == other._canonical()
        )

    def _canonical(self):
        # gpu is an alias for tpu when the backend is a TPU; both resolve to
        # the same jax device, so they must compare equal.
        dt = self.device_type
        if dt in ("gpu", "tpu") and _accelerator_platform() != "cpu":
            dt = "accel"
        elif dt in ("cpu_pinned", "cpu_shared"):
            dt = "cpu"
        return (dt, self.device_id)

    def __hash__(self):
        return hash(self._canonical())

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return self.__repr__()

    # -- jax mapping ------------------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        plat = _accelerator_platform()
        # device ids index PROCESS-LOCAL devices: under multi-process SPMD
        # (jax.distributed), jax.devices() spans all hosts and remote
        # entries are non-addressable from this process.
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = (jax.local_devices(backend="cpu") if plat != "cpu"
                    else jax.local_devices())
        else:  # gpu / tpu -> default accelerator backend
            devs = jax.local_devices()
        if self.device_id >= len(devs):
            raise MXNetError(
                f"{self} out of range: backend '{plat}' has {len(devs)} device(s)"
            )
        return devs[self.device_id]

    # -- default-context stack -------------------------------------------
    @classmethod
    def _current(cls) -> "Context":
        stack = getattr(cls._default_stack, "stack", None)
        if stack:
            return stack[-1]
        return _DEFAULT

    def __enter__(self):
        stack = getattr(Context._default_stack, "stack", None)
        if stack is None:
            stack = Context._default_stack.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default_stack.stack.pop()
        return False

    # reference parity helpers
    def empty_cache(self):  # XLA owns the allocator; nothing to do
        return None


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias of :func:`tpu` on TPU backends (reference scripts use mx.gpu())."""
    return Context("gpu", device_id)


def num_gpus() -> int:
    """Number of accelerator devices (reference: ``context.py:num_gpus``)."""
    plat = _accelerator_platform()
    return 0 if plat == "cpu" else len(jax.local_devices())


def num_tpus() -> int:
    return num_gpus()


def current_context() -> Context:
    return Context._current()


def _default_ctx() -> Context:
    return Context("tpu", 0) if _accelerator_platform() != "cpu" else Context("cpu", 0)


class _LazyDefault(Context):
    """Default ctx resolved lazily so importing never initializes a backend."""

    def __init__(self):  # noqa: super-init-not-called - lazy by design
        self._resolved = None

    def _r(self) -> Context:
        if self._resolved is None:
            self._resolved = _default_ctx()
        return self._resolved

    @property
    def device_type(self):
        return self._r().device_type

    @property
    def device_id(self):
        return self._r().device_id


_DEFAULT = _LazyDefault()
