"""Generated ``mx.sym.*`` namespace over the shared op registry.

Reference: ``python/mxnet/symbol/register.py`` stub generation.
"""

from __future__ import annotations

import sys

from ..ops import registry as _registry
from .symbol import Symbol, var

_THIS = sys.modules[__name__]


def _num_outputs(opname, attrs):
    if opname in ("split", "SliceChannel"):
        return int(attrs.get("num_outputs", 1))
    if opname == "split_v2":
        if attrs.get("sections"):
            return int(attrs["sections"])
        return len(attrs.get("indices", ())) + 1
    if opname == "topk" and attrs.get("ret_typ") == "both":
        return 2
    if opname in ("_contrib_moe", "moe"):
        return 2  # (out, aux_loss)
    return 1


def _make_sym_op(opdef):
    def fn(*args, name=None, **kwargs):
        inputs = []
        attrs = {}
        for a in args:
            if isinstance(a, Symbol):
                inputs.append(a)
            elif a is None:
                continue
            else:
                attrs_positional_err = a
                raise TypeError(
                    f"positional non-Symbol argument {a!r} for sym.{opdef.name}"
                )
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                inputs.append(v)
            elif v is not None:
                attrs[k] = tuple(v) if isinstance(v, list) else v
        nout = _num_outputs(opdef.name, attrs)
        return Symbol(opdef.name, attrs, inputs, name=name, num_outputs=nout)

    fn.__name__ = opdef.name
    return fn


for _opname, _opdef in list(_registry.all_ops().items()):
    if not hasattr(_THIS, _opname):
        setattr(_THIS, _opname, _make_sym_op(_opdef))

Variable = var
