"""``mx.sym._internal`` (reference: ``python/mxnet/symbol/_internal.py``).

Underscore-prefixed symbolic op stubs — see ``ndarray/_internal.py``.
"""

from __future__ import annotations

import sys

from ..ops import registry as _registry
from . import op as _op

_THIS = sys.modules[__name__]

for _name in list(_registry.all_ops()):
    if _name.startswith("_") and hasattr(_op, _name) \
            and not hasattr(_THIS, _name):
        setattr(_THIS, _name, getattr(_op, _name))
