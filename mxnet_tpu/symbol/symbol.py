"""Symbol: lazy graph construction API.

Reference: ``python/mxnet/symbol/symbol.py`` + nnvm graph (``SaveJSON``).
TPU-native: a Symbol is a lightweight DAG of (op, attrs, inputs); shape
inference runs via ``jax.eval_shape`` over the same op implementations the
imperative path uses (single source of truth — no separate FInferShape
registry), and binding compiles the whole graph with ``jax.jit``.
"""

from __future__ import annotations

import json
import sys

import numpy as _np

from .. import name as _name_mod
from ..base import MXNetError
from ..ops import registry as _registry

# ops whose trailing inputs are auxiliary states (not gradient arguments)
_AUX_INPUTS = {"BatchNorm": ("moving_mean", "moving_var")}
_OP_INPUT_NAMES = {
    "BatchNorm": ("data", "gamma", "beta", "moving_mean", "moving_var"),
    "FullyConnected": ("data", "weight", "bias"),
    "Convolution": ("data", "weight", "bias"),
    "Deconvolution": ("data", "weight", "bias"),
    "Embedding": ("data", "weight"),
    "LayerNorm": ("data", "gamma", "beta"),
    "InstanceNorm": ("data", "gamma", "beta"),
    "GroupNorm": ("data", "gamma", "beta"),
    "RNN": ("data", "parameters", "state", "state_cell"),
    "SoftmaxOutput": ("data", "label"),
}


class Symbol:
    """A node in the symbolic graph (possibly selecting one output)."""

    __array_priority__ = 1000.0

    def __init__(self, op, attrs, inputs, name=None, index=0, num_outputs=1):
        self._op = op  # None for variables; "_group" for groups
        self._attrs = attrs or {}
        self._inputs = inputs or []
        self._index = index
        self._num_outputs = num_outputs
        if name is None and op is not None and op != "_group":
            name = _name_mod.next_name(op.lower())
        self._name = name

    # -- identity ---------------------------------------------------------
    @property
    def name(self):
        return self._name

    def attr(self, key):
        return self._attrs.get(key)

    def list_attr(self):
        return {k: str(v) for k, v in self._attrs.items()}

    def __repr__(self):
        return f"<Symbol {self._name}>"

    def __iter__(self):
        return (self[i] for i in range(len(self)))

    def __len__(self):
        if self._op == "_group":
            return len(self._inputs)
        return self._num_outputs

    def __getitem__(self, index):
        if isinstance(index, str):
            internals = self.get_internals()
            for s in internals._inputs:
                if s._name == index or f"{s._name}_output" == index:
                    return s
            raise MXNetError(f"no internal symbol named {index}")
        if self._op == "_group":
            return self._inputs[index]
        if index >= max(self._num_outputs, 1):
            raise IndexError(index)
        if self._num_outputs == 1:
            return self
        return Symbol(self._op, self._attrs, self._inputs, self._name,
                      index=index, num_outputs=self._num_outputs)

    # -- graph walks ------------------------------------------------------
    def _topo(self):
        order, seen = [], set()
        stack = [(self, False)]
        while stack:
            node, done = stack.pop()
            if done:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for i in node._inputs:
                stack.append((i, False))
        # dedupe multi-output views: keep first occurrence per base
        return order

    def list_arguments(self):
        args = []
        seen = set()
        for node in self._topo():
            if node._op is None and node._name not in seen \
                    and not node._attrs.get("__aux__"):
                seen.add(node._name)
                args.append(node._name)
        return args

    def list_auxiliary_states(self):
        auxs = []
        seen = set()
        for node in self._topo():
            if node._op is None and node._attrs.get("__aux__") \
                    and node._name not in seen:
                seen.add(node._name)
                auxs.append(node._name)
        return auxs

    def list_outputs(self):
        if self._op == "_group":
            out = []
            for s in self._inputs:
                out.extend(s.list_outputs())
            return out
        if self._num_outputs == 1:
            return [f"{self._name}_output"]
        return [f"{self._name}_output{self._index}"]

    def list_inputs(self):
        return self.list_arguments() + self.list_auxiliary_states()

    def get_internals(self):
        nodes = [n for n in self._topo()]
        return Symbol("_group", {}, nodes, name="internals")

    def get_children(self):
        if not self._inputs:
            return None
        return Symbol("_group", {}, list(self._inputs), name="children")

    # -- composition ------------------------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: rebind variable inputs (reference: ``Symbol.__call__``)."""
        s = self._deepcopy()
        s._compose(*args, **kwargs)
        return s

    def _deepcopy(self, memo=None):
        memo = memo if memo is not None else {}
        if id(self) in memo:
            return memo[id(self)]
        cp = Symbol(self._op, dict(self._attrs),
                    [i._deepcopy(memo) for i in self._inputs], self._name,
                    self._index, self._num_outputs)
        memo[id(self)] = cp
        return cp

    def _compose(self, *args, **kwargs):
        by_name = dict(kwargs)
        pos = list(args)
        for node in self._topo():
            for i, inp in enumerate(node._inputs):
                if inp._op is None:
                    if inp._name in by_name:
                        node._inputs[i] = by_name[inp._name]
                    elif pos:
                        node._inputs[i] = pos.pop(0)

    # -- shape/type inference --------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except Exception as e:
            raise MXNetError(f"infer_shape failed: {e}") from e

    def infer_shape_partial(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(True, *args, **kwargs)
        except Exception:
            return (None, None, None)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax

        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        shapes = {}
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    shapes[n] = s
        shapes.update({k: v for k, v in kwargs.items() if v is not None})

        known = dict(shapes)
        # iterative local propagation using eval_shape per node
        out_shapes, arg_out, aux_out = _infer_graph_shapes(self, known)
        args_res = [arg_out.get(n) for n in arg_names]
        auxs_res = [aux_out.get(n) for n in aux_names]
        return (args_res, out_shapes, auxs_res)

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        dtypes = {}
        if args:
            for n, t in zip(arg_names, args):
                dtypes[n] = t
        dtypes.update(kwargs)
        default = _np.float32
        args_res = [_np.dtype(dtypes.get(n, default)) for n in arg_names]
        outs = [
            _np.dtype(default) for _ in self.list_outputs()
        ]
        auxs = [_np.dtype(default) for _ in self.list_auxiliary_states()]
        return (args_res, outs, auxs)

    # -- evaluation -------------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        from .executor import eval_symbol

        return eval_symbol(self, kwargs)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    **kwargs):
        from .executor import Executor
        from ..ndarray.ndarray import zeros

        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        if arg_shapes is None or any(s is None for s in arg_shapes):
            raise MXNetError("simple_bind could not infer all argument shapes")
        args = {n: zeros(s, ctx=ctx) for n, s in zip(arg_names, arg_shapes)}
        args_grad = {
            n: zeros(s, ctx=ctx)
            for n, s in zip(arg_names, arg_shapes)
        } if grad_req != "null" else None
        auxs = {n: zeros(s, ctx=ctx) for n, s in zip(aux_names, aux_shapes or [])}
        return Executor(self, ctx, args, args_grad, grad_req, auxs)

    # -- save/load --------------------------------------------------------
    def tojson(self):
        """Serialize in the nnvm ``SaveJSON`` schema (reference:
        ``3rdparty/tvm/nnvm/src/core/graph.cc`` / ``MXSymbolSaveToJSON``):
        ``nodes`` (attrs stringified the MXNet way), ``arg_nodes`` (indices
        of variable nodes), ``node_row_ptr`` (cumulative output counts),
        ``heads``. Files interchange with reference ``sym.save`` /
        ``SymbolBlock.imports``."""
        nodes = []
        node_ids = {}
        for node in self._topo():
            if id(node) in node_ids:
                continue
            node_ids[id(node)] = len(nodes)
            nodes.append(node)
        json_nodes = []
        arg_nodes = []
        node_row_ptr = [0]
        for n in nodes:
            entry = {
                "op": n._op or "null",
                "name": n._name,
                "inputs": [[node_ids[id(i)], i._index, 0] for i in n._inputs],
            }
            if n._attrs:
                entry["attrs"] = {k: _attr_str(k, v)
                                  for k, v in n._attrs.items()}
            if n._op is None:
                arg_nodes.append(node_ids[id(n)])
            json_nodes.append(entry)
            node_row_ptr.append(node_row_ptr[-1] + n._num_outputs)
        blob = {
            "nodes": json_nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": node_row_ptr,
            "heads": [[node_ids[id(self)], self._index, 0]]
            if self._op != "_group"
            else [[node_ids[id(s)], s._index, 0] for s in self._inputs],
            "attrs": {"mxnet_version": ["int", 10700]},
        }
        return json.dumps(blob, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- operators --------------------------------------------------------
    def _binop(self, opname, other, reverse=False):
        from . import op as _sym_op

        fn = getattr(_sym_op, opname)
        if not isinstance(other, Symbol):
            other = _scalar_sym(other)
        a, b = (other, self) if reverse else (self, other)
        return fn(a, b)

    def __add__(self, o):
        return self._binop("broadcast_add", o)

    def __radd__(self, o):
        return self._binop("broadcast_add", o, True)

    def __sub__(self, o):
        return self._binop("broadcast_sub", o)

    def __rsub__(self, o):
        return self._binop("broadcast_sub", o, True)

    def __mul__(self, o):
        return self._binop("broadcast_mul", o)

    def __rmul__(self, o):
        return self._binop("broadcast_mul", o, True)

    def __truediv__(self, o):
        return self._binop("broadcast_div", o)

    def __rtruediv__(self, o):
        return self._binop("broadcast_div", o, True)

    def __pow__(self, o):
        return self._binop("broadcast_power", o)

    def __neg__(self):
        return self._binop("broadcast_mul", -1.0)

    def reshape(self, *shape, **kwargs):
        from . import op as _sym_op

        if "shape" in kwargs:
            shape = kwargs["shape"]
        elif len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _sym_op.reshape(self, shape=tuple(shape))


def _attr_str(key, v):
    """Stringify an attr the MXNet JSON way: every value is a string —
    tuples print as ``(3, 3)``, bools as ``True``, numbers via str().
    ``__dtype__`` is the one key with special encoding: the reference
    writes the mshadow integer type flag ('0' for float32), not the
    numpy name, and its loaders int()-parse it."""
    if key == "__dtype__" and isinstance(v, str):
        flags = {n: f for f, n in _DTYPE_FLAG_NAMES.items()}
        if v in flags:
            return str(flags[v])
    return str(v)


def _attr_parse(v):
    """Parse a JSON attr back to a typed value: nnvm-schema files carry
    strings ('(3, 3)', '64', 'True', 'relu'); legacy mxtpu files carry
    typed JSON (lists for tuples)."""
    if isinstance(v, list):
        return tuple(v)
    if not isinstance(v, str):
        return v
    try:
        import ast

        parsed = ast.literal_eval(v)
        if isinstance(parsed, list):
            return tuple(parsed)
        return parsed
    except (ValueError, SyntaxError):
        return v  # plain string attr ('relu', 'valid', ...)


def _scalar_sym(value):
    return Symbol("_full_scalar", {"value": float(value)}, [], name=None)


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """Create a variable symbol (reference: ``sym.var``/``sym.Variable``)."""
    attrs = dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(_np.dtype(dtype))
    attrs.update(kwargs)
    return Symbol(None, attrs, [], name=name)


Variable = var


def Group(symbols):
    return Symbol("_group", {}, list(symbols), name="group")


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    """Load either schema: nnvm ``SaveJSON`` (reference ``sym.load`` files;
    stringified attrs, ``arg_nodes``/``node_row_ptr`` ignored on load the
    way nnvm's own loader does) or the legacy mxtpu_version=1 typed form."""
    blob = json.loads(json_str)
    nodes = []
    for n in blob["nodes"]:
        # pre-1.6 reference files use "attr"/"param" instead of "attrs"
        raw_attrs = n.get("attrs") or n.get("attr") or n.get("param") or {}
        attrs = {k: _attr_parse(v) for k, v in raw_attrs.items()}
        # reference variable nodes carry __dtype__ as a mshadow type flag
        if isinstance(attrs.get("__dtype__"), int):
            attrs["__dtype__"] = _DTYPE_FLAG_NAMES.get(
                attrs["__dtype__"], "float32")
        if n["op"] == "null":
            sym = Symbol(None, attrs, [], name=n["name"])
        else:
            inputs = [nodes[i][idx] if nodes[i]._num_outputs > 1 else nodes[i]
                      for i, idx, _ in n["inputs"]]
            nout = _num_outputs_of(n["op"], attrs)
            sym = Symbol(n["op"], attrs, inputs, name=n["name"],
                         num_outputs=nout)
        nodes.append(sym)
    heads = [nodes[i][idx] if nodes[i]._num_outputs > 1 else nodes[i]
             for i, idx, _ in blob["heads"]]
    return heads[0] if len(heads) == 1 else Group(heads)


def _dtype_flag_names():
    """mshadow type-flag -> numpy name, derived from the single source of
    truth in ndarray.serialization (the .params serializer's table)."""
    from ..ndarray import serialization as _ser

    names = {f: _np.dtype(t).name for f, t in _ser._TYPE_FLAG_TO_NP.items()}
    names[_ser._BF16_FLAG] = "bfloat16"
    return names


_DTYPE_FLAG_NAMES = _dtype_flag_names()


def _num_outputs_of(op, attrs):
    if op in ("split", "SliceChannel"):
        return int(attrs.get("num_outputs", 1))
    return 1


def _prod(xs):
    r = 1
    for x in xs:
        r *= x
    return r


def _infer_params_for_node(node, in_shapes):
    """Deduce unknown VARIABLE input shapes from known data shapes —
    the nnvm FInferShape role for layer ops (reference:
    ``infer_graph_attr_pass.cc``). Returns {input_pos: shape}."""
    op = node._op
    a = node._attrs
    out = {}
    if op == "FullyConnected":
        data = in_shapes[0]
        if data is None:
            return out
        nh = int(a.get("num_hidden"))
        flatten = a.get("flatten", True)
        in_units = _prod(data[1:]) if flatten else data[-1]
        out[1] = (nh, in_units)
        if len(node._inputs) > 2 and not a.get("no_bias", False):
            out[2] = (nh,)
    elif op in ("Convolution", "Deconvolution"):
        data = in_shapes[0]
        if data is None:
            return out
        kernel = tuple(a.get("kernel", ()))
        nf = int(a.get("num_filter"))
        ng = int(a.get("num_group", 1))
        cin = data[1]
        if op == "Convolution":
            out[1] = (nf, cin // ng) + kernel
        else:
            out[1] = (cin, nf // ng) + kernel
        if len(node._inputs) > 2 and not a.get("no_bias", False):
            out[2] = (nf,)
    elif op in ("BatchNorm", "InstanceNorm", "GroupNorm"):
        data = in_shapes[0]
        if data is None:
            return out
        c = data[int(a.get("axis", 1))] if op == "BatchNorm" else data[1]
        for i in range(1, len(node._inputs)):
            out[i] = (c,)
    elif op == "LayerNorm":
        data = in_shapes[0]
        if data is None:
            return out
        c = data[int(a.get("axis", -1))]
        for i in range(1, len(node._inputs)):
            out[i] = (c,)
    elif op == "Embedding":
        out[1] = (int(a.get("input_dim")), int(a.get("output_dim")))
    elif op == "SoftmaxOutput":
        data = in_shapes[0]
        if data is None:
            return out
        out[1] = tuple(data[:-1])  # label
    return out


def _infer_graph_shapes(root, known_shapes, return_node_map=False):
    """Fixed-point shape inference: forward abstract eval where inputs are
    known; layer-specific parameter deduction where they aren't.

    With ``return_node_map`` also returns the per-node output-shape map
    (id(node) -> [shape, ...]) — used by ``visualization.print_summary``."""
    import jax
    import jax.numpy as jnp

    from ..ops import registry as reg

    for node in root._topo():  # shapes recorded on var attrs
        if node._op is None and node._name not in known_shapes:
            s = node._attrs.get("__shape__")
            if s and all(d > 0 for d in s):
                known_shapes[node._name] = tuple(s)

    nodes = [n for n in root._topo()]
    node_out = {}  # id(node) -> tuple of output shapes

    def in_shape(node, i):
        inp = node._inputs[i]
        if inp._op is None:
            return known_shapes.get(inp._name)
        shapes = node_out.get(id(inp))
        if shapes is None:
            return None
        return shapes[inp._index] if inp._num_outputs > 1 else shapes[0]

    for _ in range(len(nodes) + 2):  # fixed point
        progress = False
        for node in nodes:
            if node._op in (None, "_group"):
                continue
            ins = [in_shape(node, i) for i in range(len(node._inputs))]
            # 1) deduce unknown variable inputs
            for pos, shp in _infer_params_for_node(node, ins).items():
                inp = node._inputs[pos]
                if inp._op is None and known_shapes.get(inp._name) is None:
                    known_shapes[inp._name] = tuple(shp)
                    progress = True
            ins = [in_shape(node, i) for i in range(len(node._inputs))]
            # 2) forward abstract eval when all inputs known
            if id(node) not in node_out and all(s is not None for s in ins):
                if node._op == "_full_scalar":
                    node_out[id(node)] = [()]
                    progress = True
                    continue
                if node._op == "_zeros_const":
                    node_out[id(node)] = [tuple(node._attrs["shape"])]
                    progress = True
                    continue
                try:
                    opdef = reg.get(node._op)
                except KeyError:
                    continue
                attrs = {k: v for k, v in node._attrs.items()
                         if not k.startswith("__")}
                structs = [jax.ShapeDtypeStruct(tuple(s), jnp.float32)
                           for s in ins]
                try:
                    if node._op == "BatchNorm":
                        attrs = dict(attrs)
                        attrs["training"] = False
                    res = jax.eval_shape(
                        lambda *xs, _f=opdef.fn, _a=attrs: _f(*xs, **_a),
                        *structs)
                except Exception:
                    continue
                if isinstance(res, (tuple, list)):
                    node_out[id(node)] = [tuple(r.shape) for r in res]
                else:
                    node_out[id(node)] = [tuple(res.shape)]
                progress = True
        if not progress:
            break

    heads = root._inputs if root._op == "_group" else [root]
    out_shapes = []
    for h in heads:
        if h._op is None:
            out_shapes.append(known_shapes.get(h._name))
        else:
            shapes = node_out.get(id(h))
            if shapes is None:
                out_shapes.append(None)
            else:
                out_shapes.append(shapes[h._index]
                                  if h._num_outputs > 1 else shapes[0])
    arg_out = {n: known_shapes.get(n) for n in root.list_arguments()}
    aux_out = {n: known_shapes.get(n) for n in root.list_auxiliary_states()}
    if return_node_map:
        return out_shapes, arg_out, aux_out, node_out
    return out_shapes, arg_out, aux_out
