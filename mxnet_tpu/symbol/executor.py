"""Symbol graph evaluation + Executor.

Reference: ``src/executor/graph_executor.cc`` (``GraphExecutor``,
``SimpleBind``). TPU-native: the "memory planning / op attachment" passes
are XLA's job — binding a graph means jitting one function that evaluates
the node DAG; backward is ``jax.vjp`` over it (SURVEY.md §3.4 collapses to
two compiled executables).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import autograd
from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray
from ..ops import registry as _registry


def _evaluate_graph(root, arg_dict, training=False, key=None):
    """Evaluate the DAG with raw arrays for variables. Returns raw outputs."""
    from .symbol import Symbol

    heads = root._inputs if root._op == "_group" else [root]
    cache = {}

    def eval_node(node):
        nid = id(node)
        if nid in cache:
            return cache[nid]
        if node._op is None:
            if node._name not in arg_dict:
                raise MXNetError(f"missing argument {node._name}")
            res = arg_dict[node._name]
        elif node._op == "_full_scalar":
            res = node._attrs["value"]
        elif node._op == "_zeros_const":
            res = jnp.zeros(node._attrs["shape"],
                            node._attrs.get("dtype", "float32"))
        elif node._op == "_group":
            res = [eval_node(i) for i in node._inputs]
        else:
            raws = []
            for i in node._inputs:
                r = eval_node(i)
                if isinstance(r, tuple) and i._num_outputs > 1:
                    r = r[i._index]
                raws.append(r)
            opdef = _registry.get(node._op)
            attrs = {k: v for k, v in node._attrs.items()
                     if not k.startswith("__")}
            if node._op == "Dropout":
                if training and key is not None and attrs.get("p", 0.5) > 0:
                    raws = [raws[0], jax.random.fold_in(key, nid % (2 ** 31))]
                    attrs = {k: v for k, v in attrs.items() if k != "mode"}
                    res = opdef.fn(*raws, **attrs)
                else:
                    res = raws[0]
            elif node._op == "BatchNorm":
                res = opdef.fn(*raws, training=False, **attrs)
            else:
                res = opdef.fn(*raws, **attrs)
        cache[nid] = res
        return res

    outs = []
    for h in heads:
        r = eval_node(h)
        if isinstance(r, tuple) and h._num_outputs > 1:
            r = r[h._index]
        outs.append(r)
    return outs


def eval_symbol(sym, arg_dict, training=False):
    """Eager evaluation helper (used by SymbolBlock / Symbol.eval)."""
    raw_args = {
        k: (v.data if isinstance(v, NDArray) else jnp.asarray(v))
        for k, v in arg_dict.items()
    }
    from .. import random as _random

    key = _random._next_key() if training else None
    outs = _evaluate_graph(sym, raw_args, training=training, key=key)
    return [NDArray(o) for o in outs]


class Executor:
    """Bound computation graph (reference: ``Executor`` /
    ``MXExecutorForward``)."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        arg_names = symbol.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        self.arg_dict = dict(args or {})
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        self.grad_dict = dict(args_grad or {})
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(symbol.list_auxiliary_states(), aux_states))
        self.aux_dict = dict(aux_states or {})
        self.grad_req = grad_req
        self.outputs = []
        self._fwd_jit = {}
        self._vjp_fn = None
        self.arg_arrays = [self.arg_dict[n] for n in arg_names
                           if n in self.arg_dict]
        self.grad_arrays = [self.grad_dict.get(n) for n in arg_names]
        self.aux_arrays = [self.aux_dict[n]
                           for n in symbol.list_auxiliary_states()
                           if n in self.aux_dict]

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(
                    v.data if isinstance(v, NDArray) else jnp.asarray(v))
            else:
                self.arg_dict[k] = v if isinstance(v, NDArray) else NDArray(jnp.asarray(v))
        raw_args = {k: v.data for k, v in self.arg_dict.items()}
        raw_args.update({k: v.data for k, v in self.aux_dict.items()})
        from .. import random as _random

        key = _random._next_key()

        sig = (tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in raw_args.items())), bool(is_train))
        jitted = self._fwd_jit.get(sig)
        if jitted is None:
            symbol = self._symbol

            def f(args_raw, k):
                return _evaluate_graph(symbol, args_raw,
                                       training=bool(is_train), key=k)

            jitted = jax.jit(f)
            self._fwd_jit[sig] = jitted

        if is_train and self.grad_req != "null":
            grad_names = [n for n in self._symbol.list_arguments()
                          if self.grad_dict.get(n) is not None]

            def f_diff(diff_raws):
                merged = dict(raw_args)
                merged.update(dict(zip(grad_names, diff_raws)))
                return _evaluate_graph(self._symbol, merged, training=True,
                                       key=key)

            outs, vjp_fn = jax.vjp(f_diff, [raw_args[n] for n in grad_names])
            self._vjp_fn = (vjp_fn, grad_names, [o for o in outs])
        else:
            outs = jitted(raw_args, key)
            self._vjp_fn = None
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        if self._vjp_fn is None:
            raise MXNetError("call forward(is_train=True) before backward")
        vjp_fn, grad_names, outs = self._vjp_fn
        if out_grads is None:
            cts = [jnp.ones_like(o) for o in outs]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = [g.data for g in out_grads]
        (grads,) = vjp_fn(cts)
        for n, g in zip(grad_names, grads):
            buf = self.grad_dict[n]
            if self.grad_req == "add":
                buf._set_data(buf.data + g)
            else:
                buf._set_data(g)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(v.data)
            elif not allow_extra_params:
                raise MXNetError(f"extra param {k}")
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    self.aux_dict[k]._set_data(v.data)
                elif not allow_extra_params:
                    raise MXNetError(f"extra aux {k}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        from ..ndarray.ndarray import zeros

        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        arg_names = self._symbol.list_arguments()
        new_args = {}
        for n, s in zip(arg_names, arg_shapes):
            old = self.arg_dict.get(n)
            if old is not None and tuple(old.shape) == tuple(s):
                new_args[n] = old
            else:
                new_args[n] = zeros(s, ctx=self._ctx)
        return Executor(self._symbol, self._ctx, new_args,
                        {n: zeros(s, ctx=self._ctx)
                         for n, s in zip(arg_names, arg_shapes)}
                        if self.grad_req != "null" else None,
                        self.grad_req, self.aux_dict)
