"""``mx.sym`` (reference: ``python/mxnet/symbol/``)."""

from .symbol import (  # noqa: F401
    Symbol,
    var,
    Variable,
    Group,
    load,
    load_json,
)
from . import op  # noqa: F401
from . import _internal  # noqa: F401
from .op import *  # noqa: F401,F403
from .executor import Executor, eval_symbol  # noqa: F401
from . import op as _op_mod

# make `mx.sym.FullyConnected(...)` etc. available at package level
import sys as _sys

_pkg = _sys.modules[__name__]
for _n in dir(_op_mod):
    if not _n.startswith("_") and not hasattr(_pkg, _n):
        setattr(_pkg, _n, getattr(_op_mod, _n))

zeros = None  # set below to avoid clobbering op namespace accidentally
from ..ndarray.ndarray import zeros as _nd_zeros  # noqa: E402


def zeros(shape, dtype="float32", **kw):  # symbolic zeros becomes a constant var
    from .symbol import Symbol

    return Symbol("_zeros_const", {"shape": tuple(shape), "dtype": dtype}, [])

# hybrid_forward's SYMBOLIC F namespace (export/SymbolBlock path) mirrors
# the nd one: F.contrib.* and F.image.* resolve to the sym op namespace
# (flat names like F.contrib.cond fall back to the registered sym ops)
from . import op as _op_ns  # noqa: E402


class _SymSubNamespace:
    """Attribute proxy: F.contrib.X / F.image.X -> the sym op for X
    (contrib control-flow gets the real symbolic implementations when
    they exist as registered ops; everything else resolves by name)."""

    def __init__(self, prefixes):
        self._prefixes = prefixes

    def __getattr__(self, name):
        for pre in self._prefixes:
            if hasattr(_op_ns, pre + name):
                return getattr(_op_ns, pre + name)
        if hasattr(_op_ns, name):
            return getattr(_op_ns, name)
        raise AttributeError(name)


_op_ns.contrib = _SymSubNamespace(("_contrib_",))
_op_ns.image = _SymSubNamespace(("_image_", "image_"))
