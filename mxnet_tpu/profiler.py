"""``mx.profiler`` — wraps ``jax.profiler``.

Reference: ``python/mxnet/profiler.py`` + ``src/profiler/`` (SURVEY.md §5.1).
The engine-integrated chrome://tracing dump maps to JAX's TensorBoard/
perfetto trace; custom scopes map to ``jax.profiler.TraceAnnotation``.
"""

from __future__ import annotations

import os
import time

import jax

_config = {"profile_all": False, "filename": "profile.json", "aggregate_stats": False}
_state = {"running": False, "dir": None}
_records = []
_AGGREGATE = {}  # op name -> [count, total_s, min_s, max_s]


def aggregate_enabled() -> bool:
    """True when per-op aggregate timing is on (set_config(aggregate_stats=
    True)). Op dispatch then blocks per call to attribute device time
    (reference: ``AggregateStats``, engine-integrated)."""
    return bool(_config.get("aggregate_stats"))


def record_op(name: str, dt: float) -> None:
    rec = _AGGREGATE.get(name)
    if rec is None:
        _AGGREGATE[name] = [1, dt, dt, dt]
    else:
        rec[0] += 1
        rec[1] += dt
        rec[2] = min(rec[2], dt)
        rec[3] = max(rec[3], dt)


def set_config(**kwargs):
    _config.update(kwargs)


def profiler_set_config(mode="symbolic", filename="profile.json"):
    _config["filename"] = filename


def set_state(state="stop", profile_process="worker"):
    if state == "run":
        start()
    else:
        stop()


def start(profile_process="worker"):
    out = _config.get("filename", "profile.json")
    trace_dir = os.path.splitext(out)[0] + "_trace"
    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)
    _state["running"] = True
    _state["dir"] = trace_dir


def stop(profile_process="worker"):
    if _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False


def pause(profile_process="worker"):
    stop()


def resume(profile_process="worker"):
    start()


def dump(finished=True, profile_process="worker"):
    stop()
    return _state["dir"]


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Aggregate statistics as a printable table (reference:
    ``mx.profiler.dumps(aggregate_stats=True)`` -> ``AggregateStats``
    Name / Total Count / Time (ms) / Min / Max / Avg columns)."""
    lines = []
    if _AGGREGATE:
        lines.append("Profile Statistics:")
        lines.append(f"{'Name':<40}{'Total Count':>12}{'Time (ms)':>14}"
                     f"{'Min (ms)':>12}{'Max (ms)':>12}{'Avg (ms)':>12}")
        key = {"total": lambda kv: kv[1][1], "count": lambda kv: kv[1][0],
               "avg": lambda kv: kv[1][1] / kv[1][0]}.get(
                   sort_by, lambda kv: kv[1][1])
        for name, (cnt, tot, mn, mx) in sorted(
                _AGGREGATE.items(), key=key, reverse=not ascending):
            lines.append(f"{name:<40}{cnt:>12}{tot * 1e3:>14.4f}"
                         f"{mn * 1e3:>12.4f}{mx * 1e3:>12.4f}"
                         f"{tot / cnt * 1e3:>12.4f}")
    lines.extend(f"{n}: {d * 1e3:.3f} ms" for n, d in _records)
    if reset:
        _AGGREGATE.clear()
        _records.clear()
    return "\n".join(lines)


class ProfileTask:
    """Named task scope (reference: ``profiler::ProfileTask``)."""

    def __init__(self, name, domain=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def start(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()

    def stop(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            _records.append((self.name, time.perf_counter() - self._t0))
            self._ann = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


ProfileEvent = ProfileTask
Task = ProfileTask
Event = ProfileTask


class ProfileCounter:
    """Named user counter (reference: ``profiler::ProfileCounter``).

    Backed by the observability metrics registry (gauge
    ``mxtpu_profile_counter{name=...}``), so values show up in
    ``observability.dump_prometheus()`` alongside the runtime metrics.
    User-driven, so it records regardless of the MXTPU_TELEMETRY switch.
    """

    def __init__(self, name, domain=None):
        self.name = name
        if domain is not None:
            self.name = f"{getattr(domain, 'name', domain)}:{name}"

    @property
    def _gauge(self):
        from . import observability

        return observability.PROFILE_COUNTER

    @property
    def value(self):
        return self._gauge.value(name=self.name)

    @value.setter
    def value(self, v):
        self.set_value(v)

    def set_value(self, value):
        self._gauge.set(value, name=self.name)

    def increment(self, delta=1):
        self._gauge.inc(delta, name=self.name)

    def decrement(self, delta=1):
        self._gauge.inc(-delta, name=self.name)


Counter = ProfileCounter


class Domain:
    def __init__(self, name):
        self.name = name


class ProfileMarker:
    def __init__(self, name, domain=None):
        self.name = name

    def mark(self, scope="process"):
        pass


def device_memory_profile(path=None):
    """Device memory snapshot (reference analog: MXNET_MEMORY_PROFILE)."""
    path = path or "memory.prof"
    jax.profiler.save_device_memory_profile(path)
    return path
