"""Llama-3 family (BASELINE config #5 — the modern-LLM stretch goal).

No reference counterpart (MXNet predates Llama); built TPU-first:
RMSNorm + RoPE + SwiGLU + grouped-query attention over the Pallas flash
kernel, causal by construction. ``tp_sharding_map`` returns the
PartitionSpecs that shard this model tensor-parallel over a mesh ``tp``
axis for ``parallel.SPMDTrainStep`` (Megatron-style: attention heads and
FFN intermediate split column-wise, output projections row-wise); long
sequences shard over ``sp`` with ``parallel.ring_attention``.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from ..gluon.block import HybridBlock
from ..gluon import nn
from ..ndarray.ndarray import NDArray


class RMSNorm(HybridBlock):
    def __init__(self, units, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self._eps = eps
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(units,),
                                          init="ones")

    def hybrid_forward(self, F, x, weight):
        xf = x.data.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        normed = xf * (1.0 / jnp.sqrt(var + self._eps))
        return NDArray((normed * weight.data.astype(jnp.float32))
                       .astype(x.data.dtype), ctx=x.ctx)


def _rope(x, base=500000.0):
    """Rotary position embeddings on (B, H, T, D)."""
    B, H, T, D = x.shape
    half = D // 2
    freqs = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    t = jnp.arange(T, dtype=jnp.float32)
    ang = jnp.einsum("t,f->tf", t, freqs)  # (T, half)
    cos = jnp.cos(ang)[None, None, :, :]
    sin = jnp.sin(ang)[None, None, :, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1 = x1.astype(jnp.float32)
    xf2 = x2.astype(jnp.float32)
    out = jnp.concatenate([xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


class LlamaAttention(HybridBlock):
    def __init__(self, units, num_heads, num_kv_heads, rope_base=500000.0,
                 sliding_window=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._h = num_heads
        self._kvh = num_kv_heads
        self._d = units // num_heads
        self._rope_base = rope_base
        self._window = sliding_window
        with self.name_scope():
            self.q_proj = nn.Dense(units, flatten=False, use_bias=False,
                                   prefix="q_")
            self.k_proj = nn.Dense(self._kvh * self._d, flatten=False,
                                   use_bias=False, prefix="k_")
            self.v_proj = nn.Dense(self._kvh * self._d, flatten=False,
                                   use_bias=False, prefix="v_")
            self.o_proj = nn.Dense(units, flatten=False, use_bias=False,
                                   prefix="o_")

    def hybrid_forward(self, F, x):
        B, T, C = x.shape
        H, KVH, D = self._h, self._kvh, self._d
        q = F.transpose(F.reshape(self.q_proj(x), shape=(B, T, H, D)),
                        axes=(0, 2, 1, 3))
        k = F.transpose(F.reshape(self.k_proj(x), shape=(B, T, KVH, D)),
                        axes=(0, 2, 1, 3))
        v = F.transpose(F.reshape(self.v_proj(x), shape=(B, T, KVH, D)),
                        axes=(0, 2, 1, 3))
        q = NDArray(_rope(q.data, self._rope_base), ctx=x.ctx)
        k = NDArray(_rope(k.data, self._rope_base), ctx=x.ctx)
        # grouped-query kv heads (KVH < H) go to the op unrepeated; the
        # op's default path repeats kv internally (fastest measured), and
        # flash_attention(native_gqa=True) exists for long-context runs
        # where the O(H) kv repeat in HBM is the binding constraint
        # sliding_window > 0 selects the banded Pallas kernels
        # (Mistral-style local attention, O(T*W) instead of O(T^2))
        out = F.flash_attention(q, k, v, causal=True, window=self._window)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)), shape=(B, T, C))
        return self.o_proj(out)


class LlamaMLP(HybridBlock):
    def __init__(self, units, intermediate, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.gate_proj = nn.Dense(intermediate, flatten=False,
                                      use_bias=False, prefix="gate_")
            self.up_proj = nn.Dense(intermediate, flatten=False,
                                    use_bias=False, prefix="up_")
            self.down_proj = nn.Dense(units, flatten=False, use_bias=False,
                                      prefix="down_")

    def hybrid_forward(self, F, x):
        return self.down_proj(_silu(F, self.gate_proj(x)) * self.up_proj(x))


def _silu(F, x):
    return x * F.sigmoid(x)


class LlamaDecoderLayer(HybridBlock):
    def __init__(self, units, intermediate, num_heads, num_kv_heads,
                 rope_base, sliding_window=0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.input_layernorm = RMSNorm(units, prefix="in_ln_")
            self.self_attn = LlamaAttention(units, num_heads, num_kv_heads,
                                            rope_base,
                                            sliding_window=sliding_window,
                                            prefix="attn_")
            self.post_attention_layernorm = RMSNorm(units, prefix="post_ln_")
            self.mlp = LlamaMLP(units, intermediate, prefix="mlp_")

    def hybrid_forward(self, F, x):
        x = x + self.self_attn(self.input_layernorm(x))
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(HybridBlock):
    def __init__(self, vocab_size=128256, num_layers=32, units=4096,
                 intermediate=14336, num_heads=32, num_kv_heads=8,
                 rope_base=500000.0, sliding_window=0, **kwargs):
        super().__init__(**kwargs)
        self._cfg = dict(vocab_size=vocab_size, num_layers=num_layers,
                         units=units, intermediate=intermediate,
                         num_heads=num_heads, num_kv_heads=num_kv_heads,
                         sliding_window=sliding_window)
        with self.name_scope():
            self.embed_tokens = nn.Embedding(vocab_size, units,
                                             prefix="embed_")
            self.layers = nn.HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for i in range(num_layers):
                    self.layers.add(LlamaDecoderLayer(
                        units, intermediate, num_heads, num_kv_heads,
                        rope_base, sliding_window=sliding_window,
                        prefix=f"l{i}_"))
            self.norm = RMSNorm(units, prefix="norm_")
            self.lm_head = nn.Dense(vocab_size, flatten=False, use_bias=False,
                                    prefix="lm_head_")

    def hybrid_forward(self, F, input_ids):
        x = self.embed_tokens(input_ids)
        for layer in self.layers._children.values():
            x = layer(x)
        x = self.norm(x)
        return self.lm_head(x)

    def tp_sharding_map(self, tp_axis="tp"):
        """PartitionSpecs for Megatron-style TP over ``tp_axis``.

        Dense weights are (out, in): column-parallel layers shard dim 0
        (q/k/v/gate/up and the LM head), row-parallel shard dim 1 (o/down).
        Embeddings shard the hidden dim.
        """
        from jax.sharding import PartitionSpec as P

        mapping = {}
        for name, p in self.collect_params().items():
            if p.shape is None:
                continue
            if any(t in name for t in ("q_weight", "k_weight", "v_weight",
                                       "gate_weight", "up_weight",
                                       "lm_head_weight")):
                mapping[name] = P(tp_axis, None)
            elif any(t in name for t in ("o_weight", "down_weight")):
                mapping[name] = P(None, tp_axis)
            elif "embed_weight" in name:
                mapping[name] = P(None, tp_axis)
            else:  # norms replicated
                mapping[name] = P()
        return mapping


_LLAMA_CONFIGS = {
    "llama3_8b": dict(vocab_size=128256, num_layers=32, units=4096,
                      intermediate=14336, num_heads=32, num_kv_heads=8),
    "llama3_70b": dict(vocab_size=128256, num_layers=80, units=8192,
                       intermediate=28672, num_heads=64, num_kv_heads=8),
    "llama_tiny": dict(vocab_size=256, num_layers=2, units=64,
                       intermediate=128, num_heads=4, num_kv_heads=2),
}


def get_llama(name, **kwargs):
    cfg = dict(_LLAMA_CONFIGS[name])
    cfg.update(kwargs)
    return LlamaModel(**cfg)


def llama3_8b(**kwargs):
    return get_llama("llama3_8b", **kwargs)


def llama_tiny(**kwargs):
    return get_llama("llama_tiny", **kwargs)
