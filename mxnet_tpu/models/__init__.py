"""``mxnet_tpu.models`` — NLP/LLM model families.

The reference's NLP zoo lived in GluonNLP (external repo; SURVEY.md §6
"BERT-base ... lives in GluonNLP repo scripts, not core"); this package
provides the equivalent in-tree: transformer building blocks, BERT
(config #3 of BASELINE.json), a seq2seq Transformer, and the Llama-3
stretch family (config #5) with tensor/sequence-parallel sharding maps.
"""

from .bert import (  # noqa: F401
    BERTModel,
    BERTEncoder,
    MultiHeadAttention,
    PositionwiseFFN,
    TransformerEncoderCell,
    get_bert_model,
    bert_base,
    bert_large,
)
from .transformer import Transformer, TransformerDecoderCell  # noqa: F401
from .llama import LlamaModel, get_llama, llama3_8b, llama_tiny  # noqa: F401
