"""Seq2seq Transformer for MT (GluonNLP ``model/transformer.py`` parity;
BASELINE config #3 'Transformer-base MT')."""

from __future__ import annotations

from ..gluon.block import HybridBlock
from ..gluon import nn
from .bert import MultiHeadAttention, PositionwiseFFN, TransformerEncoderCell


class TransformerDecoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attention = MultiHeadAttention(
                units, num_heads, dropout, causal=True, prefix="self_attn_")
            self.ln1 = nn.LayerNorm(in_channels=units, prefix="ln1_")
            self.cross_attention = MultiHeadAttention(
                units, num_heads, dropout, prefix="cross_attn_")
            self.ln2 = nn.LayerNorm(in_channels=units, prefix="ln2_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation="relu", prefix="ffn_")
            self.ln3 = nn.LayerNorm(in_channels=units, prefix="ln3_")

    def hybrid_forward(self, F, x, mem):
        x = self.ln1(x + self.self_attention(x))
        x = self.ln2(x + self.cross_attention(x, mem, mem))
        x = self.ln3(x + self.ffn(x))
        return x


class Transformer(HybridBlock):
    """Encoder-decoder transformer; base config = the reference MT model."""

    def __init__(self, src_vocab, tgt_vocab, num_layers=6, units=512,
                 hidden_size=2048, num_heads=8, dropout=0.1, max_length=512,
                 tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab, units, prefix="src_embed_")
            self.tgt_embed = nn.Embedding(tgt_vocab, units, prefix="tgt_embed_")
            self.pos_weight = self.params.get(
                "pos_weight", shape=(max_length, units), init="normal")
            self.encoder = nn.HybridSequential(prefix="enc_")
            with self.encoder.name_scope():
                for i in range(num_layers):
                    self.encoder.add(TransformerEncoderCell(
                        units, hidden_size, num_heads, dropout,
                        prefix=f"layer{i}_"))
            self.dec_cells = nn.HybridSequential(prefix="dec_")
            with self.dec_cells.name_scope():
                for i in range(num_layers):
                    self.dec_cells.add(TransformerDecoderCell(
                        units, hidden_size, num_heads, dropout,
                        prefix=f"layer{i}_"))
            self.proj = nn.Dense(tgt_vocab, flatten=False, prefix="proj_")

    def _pos(self, F, x):
        T = x.shape[1]
        pos = F.slice_axis(self.pos_weight.data(x.ctx), axis=0, begin=0, end=T)
        return x + F.expand_dims(pos, axis=0)

    def encode(self, src):
        from ..ndarray import op as F

        x = self._pos(F, self.src_embed(src) * (self._units ** 0.5))
        for cell in self.encoder._children.values():
            x = cell(x)
        return x

    def decode(self, tgt, mem):
        from ..ndarray import op as F

        x = self._pos(F, self.tgt_embed(tgt) * (self._units ** 0.5))
        for cell in self.dec_cells._children.values():
            x = cell(x, mem)
        return self.proj(x)

    def hybrid_forward(self, F, src, tgt, pos_weight=None):
        mem = self.encode(src)
        return self.decode(tgt, mem)
