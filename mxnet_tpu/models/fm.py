"""Factorization Machine on sparse CTR features (BASELINE config #4).

Reference anchor: the sparse end-to-end path named in SURVEY.md §7.S7 —
``example/sparse/factorization_machine/`` driving ``dot(csr, dense)``
(``src/operator/tensor/dot``), sparse embedding gradients, and
``row_sparse_pull`` through the dist kvstore.

Model (Rendle 2010, degree-2):
    y(x) = w0 + <x, w> + 1/2 * sum_f [ (x V)_f^2 - (x^2) (V^2)_f ]

Inputs arrive as CSR batches; the V/w gradients touch only the feature
rows present in the batch, so after ``backward()`` they cast to
``row_sparse`` for the kvstore push (the reference's sparse-grad path).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import autograd
from ..ndarray import op as ndop
from ..ndarray.ndarray import NDArray, array as nd_array, zeros as nd_zeros
from ..ndarray.sparse import CSRNDArray, RowSparseNDArray, dot as sp_dot


class FactorizationMachine:
    """Eager FM with explicit sparse-aware parameters.

    Not a HybridBlock: CSR minibatches and row_sparse gradient flow are
    inherently eager (the reference trains FM through Module + sparse
    kvstore, not Gluon hybridize)."""

    def __init__(self, num_features, num_factors=8, seed=0):
        rng = np.random.RandomState(seed)
        self.w0 = nd_array(np.zeros((1,), np.float32))
        self.w = nd_array(np.zeros((num_features, 1), np.float32))
        self.v = nd_array(
            (rng.randn(num_features, num_factors) * 0.05).astype(np.float32))
        for p in (self.w0, self.w, self.v):
            p.attach_grad()
        self.num_features = num_features
        self.num_factors = num_factors

    def params(self):
        return {"fm_w0": self.w0, "fm_w": self.w, "fm_v": self.v}

    def forward(self, x_csr):
        """x_csr: CSRNDArray (B, F) -> logits (B,)."""
        linear = sp_dot(x_csr, self.w)                       # (B, 1)
        xv = sp_dot(x_csr, self.v)                           # (B, K)
        x2 = CSRNDArray(x_csr.values * x_csr.values, x_csr.indptr,
                        x_csr.indices, x_csr.shape) \
            if hasattr(x_csr, "indptr") else x_csr * x_csr
        v2 = self.v * self.v
        x2v2 = sp_dot(x2, v2)                                # (B, K)
        inter = 0.5 * (xv * xv - x2v2).sum(axis=1)           # (B,)
        return linear.reshape((-1,)) + inter + self.w0

    def loss(self, x_csr, y):
        """Logistic loss on +-1 labels (CTR convention)."""
        logits = self.forward(x_csr)
        return ndop.log(1.0 + ndop.exp(-y * logits)).mean()

    def grad_rsp(self, param):
        """Cast a dense param gradient to row_sparse (rows touched by the
        batch) for the kvstore push — the sparse-grad wire format."""
        raw = param.grad.data
        nz = jnp.any(raw != 0, axis=tuple(range(1, raw.ndim)))
        nz_host = np.nonzero(np.asarray(nz))[0].astype(np.int32)
        vals = np.asarray(raw)[nz_host]
        return RowSparseNDArray(vals, nz_host, raw.shape)


def synthetic_ctr(num_samples, num_features, nnz_per_row=8, seed=0):
    """Synthetic CTR data: sparse one-hot-ish rows, labels from a planted
    low-rank interaction model (so FM can actually fit it)."""
    rng = np.random.RandomState(seed)
    indptr = [0]
    indices = []
    values = []
    planted_v = rng.randn(num_features, 4) * 0.5
    planted_w = rng.randn(num_features) * 0.3
    labels = []
    for _ in range(num_samples):
        cols = rng.choice(num_features, size=nnz_per_row, replace=False)
        vals = np.ones(nnz_per_row, np.float32)
        indices.extend(cols.tolist())
        values.extend(vals.tolist())
        indptr.append(len(indices))
        xv = planted_v[cols].sum(0)
        score = planted_w[cols].sum() + 0.5 * (
            (xv ** 2).sum() - (planted_v[cols] ** 2).sum())
        labels.append(1.0 if score > 0 else -1.0)
    return (np.array(values, np.float32), np.array(indptr, np.int32),
            np.array(indices, np.int32), np.array(labels, np.float32))


def train_step(fm, x_csr, y, kv=None, lr=0.05):
    """One FM step: record -> backward -> (optionally) push row_sparse
    grads through the kvstore -> SGD update. Returns the loss value."""
    with autograd.record():
        l = fm.loss(x_csr, y)
    l.backward()
    updates = [("fm_w", fm.w), ("fm_v", fm.v)]
    if kv is not None:
        for name, p in updates:
            kv.push(name, fm.grad_rsp(p))
        # pull only the rows this worker's batch touched (plus row 0):
        # the reference row_sparse_pull contract
        for name, p in updates:
            rows = nd_array(np.arange(p.shape[0]).astype(np.int32))
            kv.row_sparse_pull(name, out=p, row_ids=rows)
        kv.push("fm_w0", fm.w0.grad)
        kv.pull("fm_w0", out=fm.w0)
    else:
        for _, p in updates:
            p._set_data((p - lr * p.grad).data)
        fm.w0._set_data((fm.w0 - lr * fm.w0.grad).data)
    return float(l.asnumpy())
