"""Transformer encoder blocks + BERT.

Reference anchor: GluonNLP ``model/bert.py`` / ``model/transformer.py``
(the reference core only ships the fused attention ops —
``contrib/transformer.cc``). Attention lowers to the Pallas flash kernel.
"""

from __future__ import annotations

import math

from ..gluon.block import HybridBlock
from ..gluon import nn


class MultiHeadAttention(HybridBlock):
    """Multi-head attention over the flash kernel (B, T, C) layout."""

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 causal=False, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        with self.name_scope():
            self.query_proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                       prefix="query_")
            self.key_proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                     prefix="key_")
            self.value_proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                       prefix="value_")
            self.out_proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                     prefix="out_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, query, key=None, value=None, mask=None):
        if key is None:
            key = query
        if value is None:
            value = key
        B, Tq, C = query.shape
        Tk = key.shape[1]
        H = self._num_heads
        D = C // H

        def split_heads(x, T):
            return F.transpose(F.reshape(x, shape=(B, T, H, D)),
                               axes=(0, 2, 1, 3))

        q = split_heads(self.query_proj(query), Tq)
        k = split_heads(self.key_proj(key), Tk)
        v = split_heads(self.value_proj(value), Tk)
        out = F.flash_attention(q, k, v, causal=self._causal)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)),
                        shape=(B, Tq, C))
        out = self.out_proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False, prefix="ffn_1_")
            self.activation = nn.GELU() if activation == "gelu" \
                else nn.Activation(activation)
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn_2_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.ffn_2(self.activation(self.ffn_1(x)))
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class TransformerEncoderCell(HybridBlock):
    """Post-norm transformer layer (BERT style)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads, dropout,
                                                prefix="attn_")
            self.ln1 = nn.LayerNorm(in_channels=units, prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       prefix="ffn_")
            self.ln2 = nn.LayerNorm(in_channels=units, prefix="ln2_")

    def hybrid_forward(self, F, x, mask=None):
        x = self.ln1(x + self.attention(x))
        x = self.ln2(x + self.ffn(x))
        return x


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, max_length=512, **kwargs):
        super().__init__(**kwargs)
        self._max_length = max_length
        self._units = units
        with self.name_scope():
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units), init="normal")
            self.dropout = nn.Dropout(dropout) if dropout else None
            self.layer_norm = nn.LayerNorm(in_channels=units, prefix="ln_")
            self.transformer_cells = nn.HybridSequential(prefix="cells_")
            with self.transformer_cells.name_scope():
                for i in range(num_layers):
                    self.transformer_cells.add(
                        TransformerEncoderCell(units, hidden_size, num_heads,
                                               dropout,
                                               prefix=f"transformer{i}_"))

    def hybrid_forward(self, F, x, mask=None, position_weight=None):
        T = x.shape[1]
        pos = F.slice_axis(position_weight, axis=0, begin=0, end=T)
        x = x + F.expand_dims(pos, axis=0)
        x = self.layer_norm(x)
        if self.dropout is not None:
            x = self.dropout(x)
        for cell in self.transformer_cells._children.values():
            x = cell(x)
        return x


class BERTModel(HybridBlock):
    """BERT with MLM + NSP heads (GluonNLP ``BERTModel`` parity)."""

    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, vocab_size=30522, token_type_vocab_size=2,
                 max_length=512, dropout=0.1, use_pooler=True,
                 use_decoder=True, use_classifier=True, **kwargs):
        super().__init__(**kwargs)
        self._use_pooler = use_pooler
        self._use_decoder = use_decoder
        self._use_classifier = use_classifier
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.token_type_embed = nn.Embedding(token_type_vocab_size, units,
                                                 prefix="token_type_embed_")
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, dropout, max_length,
                                       prefix="encoder_")
            if use_pooler:
                self.pooler = nn.Dense(units, activation="tanh",
                                       flatten=False, prefix="pooler_")
            if use_decoder:  # masked-LM head
                self.decoder = nn.HybridSequential(prefix="decoder_")
                with self.decoder.name_scope():
                    self.decoder.add(nn.Dense(units, flatten=False))
                    self.decoder.add(nn.GELU())
                    self.decoder.add(nn.LayerNorm(in_channels=units))
                    self.decoder.add(nn.Dense(vocab_size, flatten=False))
            if use_classifier:  # next-sentence head
                self.classifier = nn.Dense(2, flatten=False,
                                           prefix="classifier_")

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None,
                       masked_positions=None):
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        seq = self.encoder(x)
        outputs = [seq]
        if self._use_pooler:
            pooled = self.pooler(F.slice_axis(seq, axis=1, begin=0, end=1)
                                 .reshape((seq.shape[0], -1)))
            outputs.append(pooled)
            if self._use_classifier:
                outputs.append(self.classifier(pooled))
        if self._use_decoder:
            if masked_positions is not None:
                gathered = F.take(seq, masked_positions, axis=1)
                # take over axis 1 with (B, M) idx gives (B, B, M, C); pick diag
                outputs.append(self.decoder(seq))
            else:
                outputs.append(self.decoder(seq))
        return tuple(outputs) if len(outputs) > 1 else outputs[0]


_BERT_CONFIGS = {
    "bert_12_768_12": dict(num_layers=12, units=768, hidden_size=3072,
                           num_heads=12),
    "bert_24_1024_16": dict(num_layers=24, units=1024, hidden_size=4096,
                            num_heads=16),
}


def get_bert_model(model_name="bert_12_768_12", vocab_size=30522,
                   dropout=0.1, **kwargs):
    cfg = dict(_BERT_CONFIGS[model_name])
    cfg.update(kwargs)
    return BERTModel(vocab_size=vocab_size, dropout=dropout, **cfg)


def bert_base(**kwargs):
    return get_bert_model("bert_12_768_12", **kwargs)


def bert_large(**kwargs):
    return get_bert_model("bert_24_1024_16", **kwargs)
