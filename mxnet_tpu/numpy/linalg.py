"""``mx.np.linalg`` over ``jax.numpy.linalg``."""

from __future__ import annotations

import sys

import jax.numpy.linalg as jla

from . import _make

_THIS = sys.modules[__name__]

for _n in ("norm", "svd", "cholesky", "qr", "inv", "pinv", "det", "slogdet",
           "eig", "eigh", "eigvals", "eigvalsh", "solve", "lstsq",
           "matrix_rank", "matrix_power", "tensorinv", "tensorsolve",
           "multi_dot"):
    if hasattr(jla, _n):
        setattr(_THIS, _n, _make(getattr(jla, _n), _n))
