"""``mx.np`` — NumPy-compatible array API (reference: ``python/mxnet/numpy/``,
1.6+ ``mx.np`` namespace, SURVEY.md §2.4).

TPU-native: thin wrappers over ``jax.numpy`` returning framework NDArrays,
so ``mx.np`` arrays interoperate with Gluon/autograd exactly like ``mx.nd``
arrays (they are the same handle type)."""

from __future__ import annotations

import sys

import numpy as _onp

import jax.numpy as jnp

from ..context import current_context
from ..ndarray.ndarray import NDArray

ndarray = NDArray
_THIS = sys.modules[__name__]


def _unwrap(x):
    if isinstance(x, NDArray):
        return x.data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(i) for i in x)
    return x


def _wrap(r):
    import jax

    if isinstance(r, jax.Array):
        return NDArray(r, ctx=current_context())
    if isinstance(r, tuple) and hasattr(r, "_fields"):  # namedtuple results
        return type(r)(*(_wrap(i) for i in r))
    if isinstance(r, (list, tuple)):
        return type(r)(_wrap(i) for i in r)
    return r


def _call_recorded(jfn, name, args, kwargs):
    """Execute with tape recording so ``mx.np`` composes with autograd
    exactly like op dispatch (reference: every mx.np op registers a
    gradient; the shared machinery lives in autograd.record_functional)."""
    from .. import autograd

    return autograd.record_functional(jfn, args, kwargs, f"np.{name}",
                                      wrap=_wrap)


def _make(jfn, name):
    def f(*args, **kwargs):
        return _call_recorded(jfn, name, args, kwargs)

    f.__name__ = name
    f.__doc__ = getattr(jfn, "__doc__", None)
    return f


_FUNCS = [
    # creation
    "array", "zeros", "ones", "full", "empty", "arange", "linspace",
    "logspace", "eye", "identity", "zeros_like", "ones_like", "full_like",
    "meshgrid", "tri", "tril", "triu", "diag", "diagonal", "indices",
    # manipulation
    "reshape", "ravel", "transpose", "moveaxis", "swapaxes", "expand_dims",
    "squeeze", "concatenate", "stack", "vstack", "hstack", "dstack",
    "column_stack", "split", "array_split", "hsplit", "vsplit", "dsplit",
    "tile", "repeat", "flip", "fliplr", "flipud", "roll", "rot90", "pad",
    "broadcast_to", "broadcast_arrays", "atleast_1d", "atleast_2d",
    "atleast_3d", "append", "delete", "insert", "resize", "unique", "where",
    "extract", "searchsorted", "sort", "argsort", "partition", "argpartition",
    # math
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "power", "mod", "remainder", "fmod", "negative", "positive", "absolute",
    "abs", "fabs", "sign", "rint", "floor", "ceil", "trunc", "around",
    "round", "exp", "expm1", "exp2", "log", "log2", "log10", "log1p", "sqrt",
    "cbrt", "square", "reciprocal", "sin", "cos", "tan", "arcsin", "arccos",
    "arctan", "arctan2", "sinh", "cosh", "tanh", "arcsinh", "arccosh",
    "arctanh", "degrees", "radians", "deg2rad", "rad2deg", "hypot", "maximum",
    "minimum", "fmax", "fmin", "clip", "nan_to_num", "interp", "heaviside",
    "gcd", "lcm", "ldexp", "signbit", "copysign", "nextafter",
    # reductions
    "sum", "prod", "cumsum", "cumprod", "nansum", "nanprod", "nancumsum",
    "mean", "std", "var", "median", "average", "min", "max", "amin", "amax",
    "nanmin", "nanmax", "nanmean", "nanstd", "nanvar", "ptp", "percentile",
    "quantile", "argmin", "argmax", "nanargmin", "nanargmax", "count_nonzero",
    "any", "all",
    # linalg-ish
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum", "kron",
    "trace", "cross",
    # logic / comparison
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "isnan",
    "isinf", "isfinite", "isposinf", "isneginf", "isclose", "allclose",
    "array_equal", "array_equiv",
    # indexing
    "take", "take_along_axis", "choose", "compress", "diag_indices",
    "tril_indices", "triu_indices", "nonzero", "flatnonzero", "argwhere",
    "unravel_index", "ravel_multi_index",
    # misc
    "bincount", "histogram", "digitize", "corrcoef", "cov", "convolve",
    "correlate", "gradient", "diff", "ediff1d", "trapezoid", "vander",
    "polyval", "real", "imag", "conj", "conjugate", "angle",
    # round-3 breadth (auto-skipped when absent from jnp)
    "divmod", "float_power", "frexp", "modf", "logaddexp", "logaddexp2",
    "i0", "sinc", "isin", "intersect1d", "union1d", "setdiff1d",
    "ix_", "mask_indices",
    "histogram2d", "histogramdd", "bartlett", "blackman", "hamming",
    "hanning", "kaiser", "nanmedian", "nanpercentile", "nanquantile",
    "nancumprod", "select", "piecewise", "rollaxis",
    "trim_zeros", "unwrap", "roots", "polyadd", "polyder", "polyfit",
    "polyint", "polymul", "polysub", "diag_indices_from", "packbits",
    "unpackbits",
    "geomspace", "block", "apply_along_axis", "fromfunction", "setxor1d",
]

for _n in _FUNCS:
    if hasattr(jnp, _n) and not hasattr(_THIS, _n):
        setattr(_THIS, _n, _make(getattr(jnp, _n), _n))

# dtypes / constants re-exported
pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_
dtype = _onp.dtype

# aliases / shims jnp spells differently
if not hasattr(_THIS, "trapz") and hasattr(_THIS, "trapezoid"):
    trapz = trapezoid  # noqa: F821 - numpy<2 name

row_stack = vstack  # noqa: F821 - numpy legacy name


def einsum_path(*operands, **kwargs):
    """Contraction-order planner (metadata only — MUST bypass the
    autograd-recording wrapper: its output is a (list, str) pair, not an
    array, and jax.vjp rejects it)."""
    return jnp.einsum_path(*(_unwrap(o) for o in operands), **kwargs)


def in1d(ar1, ar2, assume_unique=False, invert=False):
    """numpy-1.x spelling of ``isin`` on the flattened first array."""
    res = isin(ar1, ar2, invert=invert)  # noqa: F821
    return res.reshape((-1,))


def fromiter(iterable, dtype, count=-1):
    """Host constructor (reference mx.np mirrors numpy's)."""
    host = _onp.fromiter(iterable, dtype=dtype, count=count)
    return array(host)  # noqa: F821


def frombuffer(buffer, dtype=float, count=-1, offset=0):
    host = _onp.frombuffer(buffer, dtype=dtype, count=count, offset=offset)
    return array(host)  # noqa: F821


def real_if_close(a, tol=100):
    data = a.data if isinstance(a, NDArray) else jnp.asarray(a)
    if not jnp.iscomplexobj(data):
        # numpy returns the input unchanged — preserves tape lineage
        return a if isinstance(a, NDArray) else _wrap(data)
    # numpy semantics: tol > 1 scales machine eps; tol <= 1 is absolute
    if tol > 1:
        tol = float(jnp.finfo(data.dtype).eps) * tol
    # jnp.all is True on empty arrays, matching numpy's behavior
    if bool(jnp.all(jnp.abs(data.imag) < tol)):
        return _call_recorded(jnp.real, "real_if_close", (a,), {})
    return a if isinstance(a, NDArray) else _wrap(data)


def _view_span(x):
    """(root, index-or-None) for overlap checks."""
    idx = None
    while isinstance(x, NDArray) and x._base is not None:
        idx = x._index if idx is None else idx  # outermost view's index
        x = x._base
    return x, idx


def shares_memory(a, b, max_work=None):
    """True when the two handles alias the same storage. Same root
    (write-through views) counts as sharing unless both are sibling
    slice views with PROVABLY disjoint leading-axis spans — numpy's
    exact variant returns False for non-overlapping siblings."""
    ra, ia = _view_span(a)
    rb, ib = _view_span(b)
    same_root = (ra is rb) if isinstance(ra, NDArray) else False
    if not same_root:
        da = ra.data if isinstance(ra, NDArray) else ra
        db = rb.data if isinstance(rb, NDArray) else rb
        return da is db
    if ia is None or ib is None:
        return True  # one side IS the base
    sa = ia[0] if isinstance(ia, tuple) else ia
    sb = ib[0] if isinstance(ib, tuple) else ib
    if isinstance(sa, slice) and isinstance(sb, slice)             and (sa.step in (None, 1)) and (sb.step in (None, 1)):
        dim = ra.shape[0]
        a0, a1 = sa.indices(dim)[:2]
        b0, b1 = sb.indices(dim)[:2]
        return not (a1 <= b0 or b1 <= a0)
    return True  # can't prove disjoint -> conservative


def may_share_memory(a, b, max_work=None):
    """Conservative variant: any same-root pair may share."""
    ra, _ = _view_span(a)
    rb, _ = _view_span(b)
    if isinstance(ra, NDArray) and ra is rb:
        return True
    da = ra.data if isinstance(ra, NDArray) else ra
    db = rb.data if isinstance(rb, NDArray) else rb
    return da is db


def msort(a):
    """Sort along the first axis (legacy numpy msort)."""
    return sort(a, axis=0)  # noqa: F821


def fill_diagonal(a, val, wrap=False):
    """numpy contract: fills ``a``'s diagonal IN PLACE (rebinding the
    NDArray handle; jax buffers are immutable underneath) and returns
    None, exactly like numpy — ported `fill_diagonal(w, 0); use(w)`
    code keeps working."""
    from .. import autograd as _ag

    fn = lambda x, v: jnp.fill_diagonal(x, v, wrap=wrap,  # noqa: E731
                                        inplace=False)
    if not hasattr(a, "_set_data"):
        return _call_recorded(fn, "fill_diagonal", (a, val), {})
    tracked = (val,) if hasattr(val, "_set_data") else ()
    _ag.record_inplace(a, fn, (val,), "np.fill_diagonal",
                       tracked_extra=tracked)
    return None


def place(arr, mask, vals):
    """numpy-signature place (jnp defaults to inplace=True which always
    raises on immutable jax arrays); mutates NDArray inputs like numpy."""
    from .. import autograd as _ag

    fn = lambda a, m, v: jnp.place(a, m, v, inplace=False)  # noqa: E731
    # plain numpy inputs carry a .data memoryview that record_inplace's
    # unwrapping would trip over — normalize to jax arrays up front
    tracked = (vals,) if hasattr(vals, "_set_data") else ()
    if not hasattr(mask, "_set_data"):
        mask = jnp.asarray(mask)
    if not hasattr(vals, "_set_data"):
        vals = jnp.asarray(vals)
    if not hasattr(arr, "_set_data"):
        return _call_recorded(fn, "place", (arr, mask, vals), {})
    _ag.record_inplace(arr, fn, (mask, vals), "np.place",
                       tracked_extra=tracked)
    return None


def put_along_axis(arr, indices, values, axis):
    """numpy-signature put_along_axis (jnp defaults to inplace=True which
    always raises); mutates NDArray inputs in place like numpy."""
    from .. import autograd as _ag

    fn = lambda a, i, v: jnp.put_along_axis(a, i, v, axis,  # noqa: E731
                                            inplace=False)
    if not hasattr(arr, "_set_data"):
        return _call_recorded(fn, "put_along_axis",
                              (arr, indices, values), {})
    tracked = (values,) if hasattr(values, "_set_data") else ()
    _ag.record_inplace(arr, fn, (indices, values), "np.put_along_axis",
                       tracked_extra=tracked)
    return None


from . import linalg  # noqa: E402,F401
from . import random  # noqa: E402,F401


def asnumpy(a):
    return a.asnumpy() if isinstance(a, NDArray) else _onp.asarray(a)


def shape(a):
    return tuple(a.shape)


def ndim(a):
    return len(a.shape)


def size(a):
    return a.size if isinstance(a, NDArray) else _onp.size(a)
