"""``mx.np.random`` — numpy-style names over the stateful key stream."""

from __future__ import annotations

from ..random import (  # noqa: F401
    uniform,
    normal,
    randint,
    gamma,
    exponential,
    multinomial,
    shuffle,
    seed,
)


def rand(*shape):
    return uniform(0.0, 1.0, shape=shape or None)


def randn(*shape):
    return normal(0.0, 1.0, shape=shape or None)


def choice(a, size=None, replace=True, p=None):
    import jax

    from .. import random as _r
    from ..ndarray.ndarray import NDArray
    import jax.numpy as jnp

    if isinstance(a, NDArray):
        arr = a.data
    elif isinstance(a, int):
        arr = jnp.arange(a)
    else:
        arr = jnp.asarray(a)
    shape = (size,) if isinstance(size, int) else tuple(size or ())
    idx = jax.random.choice(_r._next_key(), arr.shape[0], shape or (),
                            replace=replace,
                            p=None if p is None else jnp.asarray(p))
    return NDArray(arr[idx])
