"""``mx.model`` legacy namespace (reference: ``python/mxnet/model.py``).

The reference's ``FeedForward`` class was already deprecated in 1.x in
favor of ``mx.mod.Module``; what survives in real code is the checkpoint
helpers, re-exported here with reference signatures. ``FeedForward``
raises with a pointer to Module (same guidance the reference docs give).
"""

from __future__ import annotations

from .base import MXNetError
from .callback import BatchEndParam  # noqa: F401 (reference re-export)
from .module.module import load_checkpoint, save_checkpoint  # noqa: F401


class FeedForward:
    """Removed legacy API (reference deprecated it in favor of Module)."""

    def __init__(self, *a, **k):
        raise MXNetError(
            "FeedForward was deprecated by the reference in favor of "
            "mx.mod.Module (and gluon); use those APIs")

    @staticmethod
    def load(prefix, epoch, **kwargs):
        raise MXNetError("use mx.mod.Module.load(prefix, epoch)")
