"""Runtime feature detection (reference: ``python/mxnet/runtime.py`` +
``src/libinfo.cc``)."""

from __future__ import annotations

import jax


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


class Features(dict):
    """Queryable feature set (reference: ``mx.runtime.Features``)."""

    def __init__(self):
        backend = "cpu"
        try:
            backend = jax.default_backend()
        except Exception:
            pass
        feats = {
            "TPU": backend not in ("cpu", "gpu"),
            "CUDA": False,
            "CUDNN": False,
            "XLA": True,
            "PJIT": True,
            "PALLAS": True,
            "MKLDNN": False,
            "OPENCV": _has_pillow(),
            "DIST_KVSTORE": True,
            "INT64_TENSOR_SIZE": True,
            "SIGNAL_HANDLER": True,
            "F16C": True,
            "BF16": True,
        }
        super().__init__({k: Feature(k, v) for k, v in feats.items()})

    def is_enabled(self, name):
        return self[name.upper()].enabled


def _has_pillow():
    try:
        import PIL  # noqa: F401

        return True
    except ImportError:
        return False


def feature_list():
    return list(Features().values())
