"""Runtime feature detection (reference: ``python/mxnet/runtime.py`` +
``src/libinfo.cc``)."""

from __future__ import annotations

import jax


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


class Features(dict):
    """Queryable feature set (reference: ``mx.runtime.Features``)."""

    def __init__(self):
        backend = "cpu"
        try:
            backend = jax.default_backend()
        except Exception:
            pass
        feats = {
            "TPU": backend not in ("cpu", "gpu"),
            "CUDA": False,
            "CUDNN": False,
            "XLA": True,
            "PJIT": True,
            "PALLAS": True,
            "MKLDNN": False,
            "OPENCV": _has_pillow(),
            "DIST_KVSTORE": True,
            # >2^31-element arrays: value ops (create/elementwise/
            # reduce/matmul rows) work on host at any size, but
            # INDEX-producing ops (argmax/argsort/take, big slice
            # offsets) need int64 index types, which JAX only enables
            # globally via jax_enable_x64 — report accordingly
            # (reference: MXNET_INT64_TENSOR_SIZE build flag;
            # tests/test_large_tensor.py; docs/design_decisions.md)
            "INT64_TENSOR_SIZE": bool(jax.config.jax_enable_x64),
            "SIGNAL_HANDLER": True,
            "F16C": True,
            "BF16": True,
        }
        super().__init__({k: Feature(k, v) for k, v in feats.items()})

    def is_enabled(self, name):
        return self[name.upper()].enabled


def _has_pillow():
    try:
        import PIL  # noqa: F401

        return True
    except ImportError:
        return False


def feature_list():
    return list(Features().values())
