"""Runtime feature detection (reference: ``python/mxnet/runtime.py`` +
``src/libinfo.cc``) and persistent-compilation-cache wiring."""

from __future__ import annotations

import logging
import os
import random
import time

import jax

_logger = logging.getLogger("mxnet_tpu.runtime")

#: process-local RNG for retry jitter, seeded from OS entropy — every
#: process in a fleet draws a DIFFERENT backoff sequence, which is the
#: whole point (never seed this from a shared config value)
_RETRY_RNG = random.Random()


# ---------------------------------------------------------------------------
# transient-failure retry (the PR-5 bench.py backend-init pattern, now a
# shared primitive: backend bring-up, collective setup and the kvstore
# barrier all retry through here instead of each growing its own loop)
# ---------------------------------------------------------------------------

def backoff_delays(attempts, base_delay, max_delay=30.0, jitter=True,
                   rng=None):
    """The sleep schedule ``retry_with_backoff`` walks, as a list of
    ``attempts - 1`` floats. With ``jitter`` (the default) it is
    DEcorrelated jitter (AWS-style): ``d_i = min(max_delay,
    uniform(base_delay, 3 * d_{i-1}))``, seeded per process — a fleet
    of replicas reconnecting after a coordinator blip spreads out
    instead of thundering-herding it in lockstep. ``jitter=False``
    keeps the old deterministic linear ramp (``base_delay * i``) for
    callers that need reproducible timing."""
    attempts = max(1, int(attempts))
    base_delay = float(base_delay)
    if not jitter:
        return [base_delay * i for i in range(1, attempts)]
    r = rng if rng is not None else _RETRY_RNG
    delays, prev = [], base_delay
    for _ in range(attempts - 1):
        prev = min(float(max_delay), r.uniform(base_delay, max(base_delay,
                                                               prev * 3.0)))
        delays.append(prev)
    return delays


def retry_with_backoff(fn, attempts=3, base_delay=2.0, desc="operation",
                       retry_on=(Exception,), no_retry=(), logger=None,
                       jitter=True, max_delay=30.0, rng=None,
                       sleep=time.sleep):
    """Call ``fn()`` up to ``attempts`` times with backoff between
    tries (decorrelated jitter by default — see :func:`backoff_delays`;
    ``jitter=False`` restores the deterministic linear ramp), logging
    each failure LOUDLY. Re-raises the last exception when every
    attempt fails — a transient infra hiccup retries, a real failure
    still surfaces (never silently swallowed). Exception types in
    ``no_retry`` surface IMMEDIATELY (e.g. a barrier watchdog timeout:
    the peers are gone, and re-entering the same barrier tag after
    abandoning a still-blocked watchdog thread could double-join)."""
    log = logger or _logger
    attempts = max(1, int(attempts))
    delays = backoff_delays(attempts, base_delay, max_delay=max_delay,
                            jitter=jitter, rng=rng)
    last = None
    for i in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 - retry loop by design
            if no_retry and isinstance(e, no_retry):
                raise
            last = e
            log.warning("%s attempt %d/%d failed: %s: %s", desc, i,
                        attempts, type(e).__name__, str(e)[:300])
            if i < attempts:
                sleep(delays[i - 1])
    raise last


def init_backend(attempts=3):
    """Resolve the JAX backend with retry + backoff. Returns
    ``(backend_name, None)`` or ``(None, error_string)`` — one
    transient 'Unable to initialize backend' at startup must not erase
    a run (VERDICT r5; formerly private to bench.py)."""
    try:
        return retry_with_backoff(jax.default_backend, attempts=attempts,
                                  desc="backend init"), None
    except Exception as e:
        return None, f"{type(e).__name__}: {e}"[:300]

# ---------------------------------------------------------------------------
# persistent compilation cache (MXTPU_COMPILE_CACHE)
# ---------------------------------------------------------------------------
# The reference never recompiled across restarts (kernels were AOT .so
# code); XLA recompiles every executable per process, which on a pod is
# minutes of startup per restart. JAX's persistent cache keys compiled
# executables by (HLO, compile options, backend version) in a shared
# directory; wiring it behind one env var makes restart N cost tracing
# only. Hit/miss counts land in the telemetry registry
# (mxtpu_compile_cache_{hit,miss}_total) via jax.monitoring.

_CACHE_STATE = {"dir": None, "listener": False}


def setup_compile_cache(path=None):
    """Enable JAX's persistent compilation cache at ``path`` (or
    ``$MXTPU_COMPILE_CACHE``). Idempotent; called automatically the
    first time a ``Context`` is created. Returns the active cache dir,
    or None when unconfigured."""
    from .base import getenv

    path = path or getenv("MXTPU_COMPILE_CACHE")
    if not path:
        return _CACHE_STATE["dir"]
    path = os.path.abspath(os.path.expanduser(str(path)))
    if _CACHE_STATE["dir"] == path:
        return path
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache EVERY executable: the defaults skip sub-second compiles,
    # which is exactly the many-small-executables regime the fused step
    # produces (and the whole of the CPU test/bench tier)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _CACHE_STATE["dir"] = path
    if not _CACHE_STATE["listener"]:
        _CACHE_STATE["listener"] = True
        import jax.monitoring as _mon

        from . import observability as _obs

        def _on_event(name, **kwargs):
            if name == "/jax/compilation_cache/cache_hits":
                _obs.COMPILE_CACHE_HITS.inc()
            elif name == "/jax/compilation_cache/cache_misses":
                _obs.COMPILE_CACHE_MISSES.inc()

        _mon.register_event_listener(_on_event)
    return path


def compile_cache_dir():
    """The active persistent-compile-cache directory (or None)."""
    return _CACHE_STATE["dir"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


class Features(dict):
    """Queryable feature set (reference: ``mx.runtime.Features``)."""

    def __init__(self):
        backend = "cpu"
        try:
            backend = jax.default_backend()
        except Exception:
            pass
        feats = {
            "TPU": backend not in ("cpu", "gpu"),
            "CUDA": False,
            "CUDNN": False,
            "XLA": True,
            "PJIT": True,
            "PALLAS": True,
            "MKLDNN": False,
            "OPENCV": _has_pillow(),
            "DIST_KVSTORE": True,
            # >2^31-element arrays: value ops (create/elementwise/
            # reduce/matmul rows) work on host at any size, but
            # INDEX-producing ops (argmax/argsort/take, big slice
            # offsets) need int64 index types, which JAX only enables
            # globally via jax_enable_x64 — report accordingly
            # (reference: MXNET_INT64_TENSOR_SIZE build flag;
            # tests/test_large_tensor.py; docs/design_decisions.md)
            "INT64_TENSOR_SIZE": bool(jax.config.jax_enable_x64),
            "COMPILE_CACHE": _CACHE_STATE["dir"] is not None,
            # XLA cost/memory analysis + MFU/roofline estimation
            # (observability.introspect); the estimator checks this
            # feature and degrades to null-with-reason when disabled
            "INTROSPECTION": True,
            "SIGNAL_HANDLER": True,
            "F16C": True,
            "BF16": True,
        }
        super().__init__({k: Feature(k, v) for k, v in feats.items()})

    def is_enabled(self, name):
        return self[name.upper()].enabled


def _has_pillow():
    try:
        import PIL  # noqa: F401

        return True
    except ImportError:
        return False


def feature_list():
    return list(Features().values())
