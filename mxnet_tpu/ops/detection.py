"""Detection operators: SSD multibox pipeline + Faster-RCNN proposals.

Reference surface: ``src/operator/contrib/multibox_target.cc``,
``multibox_detection.cc``, ``proposal.cc`` (+ ``multibox_prior.cc``, which
lives in ``ops/contrib.py``).

TPU-first notes: everything is static-shape. Matching is a dense IoU
matrix + argmax (the reference ran a greedy CPU bipartite loop); NMS is a
fixed-trip-count ``fori_loop`` over score-sorted boxes producing a padded
(-1 filled) result, so the whole detection head stays inside one XLA
program — no host sync, no dynamic shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _rank_from_order(order):
    """Invert a sort permutation: rank[i] = position of element i in order."""
    n = order.shape[0]
    return jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))


def _corner_to_center(boxes):
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    cx = boxes[..., 0] + w / 2
    cy = boxes[..., 1] + h / 2
    return cx, cy, w, h


def _iou_matrix(a, b):
    """a (N,4), b (M,4) corners -> (N,M) IoU."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0.0) * jnp.maximum(a[:, 3] - a[:, 1], 0.0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0.0) * jnp.maximum(b[:, 3] - b[:, 1], 0.0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("MultiBoxTarget", aliases=("_contrib_MultiBoxTarget", "multibox_target"))
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets (reference: multibox_target.cc).

    anchor (1, N, 4) corners; label (B, M, 5) rows [cls, x1, y1, x2, y2]
    (-1 padded); cls_pred (B, C+1, N) for negative mining.
    Returns (box_target (B, N*4), box_mask (B, N*4), cls_target (B, N)).
    """
    anchors = anchor.reshape(-1, 4)
    n = anchors.shape[0]
    v = jnp.asarray(variances, anchors.dtype)

    def one_batch(lab, cpred):
        valid = lab[:, 0] >= 0
        gt = lab[:, 1:5]
        iou = _iou_matrix(anchors, gt)  # (N, M)
        iou = jnp.where(valid[None, :], iou, -1.0)

        best_gt = jnp.argmax(iou, axis=1)  # per anchor
        best_gt_iou = jnp.max(iou, axis=1)
        matched = best_gt_iou > overlap_threshold

        # force-match the best anchor of each valid gt; invalid (padded)
        # gts are routed to out-of-range index n so mode="drop" discards
        # them instead of clobbering a real gt's slot at anchor 0
        best_anchor = jnp.argmax(iou, axis=0)  # (M,)
        best_anchor = jnp.where(valid, best_anchor, n)
        forced = jnp.zeros((n,), bool)
        forced = forced.at[best_anchor].set(True, mode="drop")
        forced_gt = jnp.zeros((n,), jnp.int32)
        forced_gt = forced_gt.at[best_anchor].set(
            jnp.arange(gt.shape[0], dtype=jnp.int32), mode="drop")
        match_gt = jnp.where(forced, forced_gt, best_gt.astype(jnp.int32))
        is_pos = matched | forced

        cls = lab[match_gt, 0] + 1.0
        cls_target = jnp.where(is_pos, cls, 0.0)

        if negative_mining_ratio > 0:
            # eligibility follows the reference (multibox_target.cc): an
            # unmatched anchor is a candidate negative when its best gt IoU
            # is BELOW negative_mining_thresh; ranking within the budget is
            # by max non-background confidence (hardest negatives first)
            probs = jax.nn.softmax(cpred, axis=0)
            max_fg = jnp.max(probs[1:], axis=0)  # (N,)
            neg = (~is_pos) & (best_gt_iou < negative_mining_thresh)
            num_pos = jnp.sum(is_pos)
            budget = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                minimum_negative_samples)
            rank = _rank_from_order(jnp.argsort(jnp.where(neg, -max_fg,
                                                          jnp.inf)))
            keep_neg = neg & (rank < budget)
            cls_target = jnp.where(is_pos, cls_target,
                                   jnp.where(keep_neg, 0.0, ignore_label))

        # encode matched boxes (center form, variance-scaled)
        acx, acy, aw, ah = _corner_to_center(anchors)
        g = gt[match_gt]
        gcx, gcy, gw, gh = _corner_to_center(g)
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / v[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / v[1]
        tw = jnp.log(jnp.maximum(gw, 1e-8) / jnp.maximum(aw, 1e-8)) / v[2]
        th = jnp.log(jnp.maximum(gh, 1e-8) / jnp.maximum(ah, 1e-8)) / v[3]
        target = jnp.stack([tx, ty, tw, th], axis=-1)
        mask = is_pos.astype(anchors.dtype)[:, None]
        return (target * mask).reshape(-1), jnp.broadcast_to(
            mask, (n, 4)).reshape(-1), cls_target

    bt, bm, ct = jax.vmap(one_batch)(label, cls_pred)
    return bt, bm, ct


def _decode_boxes(anchors, loc, variances, clip):
    acx, acy, aw, ah = _corner_to_center(anchors)
    v = variances
    cx = loc[..., 0] * v[0] * aw + acx
    cy = loc[..., 1] * v[1] * ah + acy
    w = jnp.exp(jnp.clip(loc[..., 2] * v[2], -10, 10)) * aw
    h = jnp.exp(jnp.clip(loc[..., 3] * v[3], -10, 10)) * ah
    boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _nms_loop(boxes, scores, classes, iou_threshold, force_suppress,
              order=None):
    """Greedy NMS on score-sorted boxes; returns keep mask (same order).
    ``order`` may pass a precomputed descending sort of ``scores``."""
    n = boxes.shape[0]
    if order is None:
        order = jnp.argsort(-scores)
    b = boxes[order]
    c = classes[order]
    s = scores[order]
    iou = _iou_matrix(b, b)
    same_cls = (c[:, None] == c[None, :]) | force_suppress
    suppress = (iou > iou_threshold) & same_cls

    def body(i, keep):
        # i suppresses later boxes only if i itself is kept and valid
        row = suppress[i] & (jnp.arange(n) > i) & keep[i] & (s[i] > -jnp.inf)
        return keep & ~row

    keep_sorted = lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    return keep_sorted[_rank_from_order(order)], order


@register("MultiBoxDetection",
          aliases=("_contrib_MultiBoxDetection", "multibox_detection"))
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD inference head (reference: multibox_detection.cc).

    cls_prob (B, C, N), loc_pred (B, N*4), anchor (1, N, 4).
    Returns (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], -1 padded.
    """
    anchors = anchor.reshape(-1, 4)
    n = anchors.shape[0]
    v = jnp.asarray(variances, anchors.dtype)

    def one_batch(cp, lp):
        loc = lp.reshape(n, 4)
        boxes = _decode_boxes(anchors, loc, v, clip)
        fg = jnp.concatenate([cp[:background_id], cp[background_id + 1:]],
                             axis=0) if cp.shape[0] > 1 else cp
        # fg row index IS the output class id (reference convention:
        # detection ids are 0-based with background removed)
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        valid = score > threshold
        score_v = jnp.where(valid, score, -jnp.inf)
        order0 = jnp.argsort(-score_v)
        if nms_topk > 0:
            # reference truncates to the top nms_topk score-sorted
            # candidates BEFORE NMS (multibox_detection.cc), so boxes past
            # that rank never participate in suppression. Masking to -inf
            # keeps order0 a valid descending sort, so the sort is not
            # recomputed inside _nms_loop.
            rank = _rank_from_order(order0)
            score_v = jnp.where(rank < nms_topk, score_v, -jnp.inf)
            valid = valid & (rank < nms_topk)
        keep, order = _nms_loop(boxes, score_v, cls_id, nms_threshold,
                                force_suppress, order=order0)
        ok = valid & keep
        rows = jnp.concatenate([
            jnp.where(ok, cls_id, -1.0)[:, None],
            jnp.where(ok, score, -1.0)[:, None],
            jnp.where(ok[:, None], boxes, -1.0),
        ], axis=1)
        # reference returns rows sorted by score; we sort for stability
        return rows[order]

    return jax.vmap(one_batch)(cls_prob, loc_pred)


def _make_grid_anchors(h, w, stride, scales, ratios, dtype):
    # scales/ratios are static attrs (python tuples), not traced values
    base = stride
    ws = []
    for r in ratios:
        for s in scales:
            size = base * float(s)
            ws.append((size * (1.0 / float(r)) ** 0.5,
                       size * float(r) ** 0.5))
    wh = jnp.asarray(ws, dtype)  # (A, 2)
    cx = (jnp.arange(w, dtype=dtype) + 0.5) * stride
    cy = (jnp.arange(h, dtype=dtype) + 0.5) * stride
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([gx, gy], axis=-1).reshape(-1, 1, 2)  # (HW, 1, 2)
    half = wh[None] / 2.0  # (1, A, 2)
    boxes = jnp.concatenate([centers - half, centers + half], axis=-1)
    return boxes.reshape(-1, 4)  # (HW*A, 4)


@register("Proposal", aliases=("_contrib_Proposal", "proposal"))
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):
    """Faster-RCNN proposal layer (reference: contrib/proposal.cc).

    cls_prob (B, 2A, H, W), bbox_pred (B, 4A, H, W), im_info (B, 3)
    [height, width, scale]. Returns (B*post_nms, 5) rows
    [batch_idx, x1, y1, x2, y2] (and scores if output_score).
    """
    b, c2a, h, w = cls_prob.shape
    a = c2a // 2
    dtype = cls_prob.dtype
    anchors = _make_grid_anchors(h, w, feature_stride, scales, ratios, dtype)
    n = anchors.shape[0]
    pre = min(rpn_pre_nms_top_n, n)

    def one_batch(cp, bp, info):
        scores = cp[a:].transpose(1, 2, 0).reshape(-1)  # fg scores (HW*A,)
        deltas = bp.reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        acx, acy, aw, ah = _corner_to_center(anchors)
        cx = deltas[:, 0] * aw + acx
        cy = deltas[:, 1] * ah + acy
        pw = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * aw
        ph = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - pw / 2, cy - ph / 2,
                           cx + pw / 2, cy + ph / 2], axis=-1)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=-1)
        min_size = rpn_min_size * info[2]
        keep_size = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_size) & \
            ((boxes[:, 3] - boxes[:, 1] + 1) >= min_size)
        scores = jnp.where(keep_size, scores, -jnp.inf)
        top_scores, top_idx = lax.top_k(scores, pre)
        top_boxes = boxes[top_idx]
        keep, order = _nms_loop(top_boxes, top_scores,
                                jnp.zeros((pre,), dtype), threshold, True)
        kept_scores = jnp.where(keep, top_scores, -jnp.inf)
        # when the anchor grid is smaller than post_nms_top_n, top_k over
        # the available `pre` and pad back up to the static output size
        post = min(rpn_post_nms_top_n, pre)
        sel_scores, sel = lax.top_k(kept_scores, post)
        out_boxes = top_boxes[sel]
        # pad slots with no surviving proposal by repeating the best box
        # (reference pads with index-0 samples), keeping shapes static
        ok = sel_scores > -jnp.inf
        out_boxes = jnp.where(ok[:, None], out_boxes, out_boxes[0])
        out_scores = jnp.where(ok, sel_scores, 0.0)
        if post < rpn_post_nms_top_n:
            extra = rpn_post_nms_top_n - post
            out_boxes = jnp.concatenate(
                [out_boxes, jnp.broadcast_to(out_boxes[0], (extra, 4))], axis=0)
            out_scores = jnp.concatenate(
                [out_scores, jnp.zeros((extra,), out_scores.dtype)], axis=0)
        return out_boxes, out_scores

    boxes, scores = jax.vmap(one_batch)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(b, dtype=dtype), rpn_post_nms_top_n)
    rois = jnp.concatenate([batch_idx[:, None],
                            boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


@register("box_encode", aliases=("_contrib_box_encode",))
def box_encode(samples, matches, anchors, refs,
               means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2)):
    """Encode matched ground-truth boxes into normalized regression targets
    (reference: ``src/operator/contrib/bounding_box.cc`` ``_contrib_box_encode``).

    samples (B,N) +1 matched / otherwise, matches (B,N) gt index,
    anchors (B,N,4) corners, refs (B,M,4) corners -> (targets (B,N,4),
    masks (B,N,4)). Targets are center-form deltas, (delta - mean)/std.
    """
    means = jnp.asarray(means, anchors.dtype)
    stds = jnp.asarray(stds, anchors.dtype)

    def one(sample, match, anc, ref):
        g = ref[jnp.clip(match.astype(jnp.int32), 0, ref.shape[0] - 1)]
        acx, acy, aw, ah = _corner_to_center(anc)
        gcx, gcy, gw, gh = _corner_to_center(g)
        aw = jnp.maximum(aw, 1e-12)
        ah = jnp.maximum(ah, 1e-12)
        t0 = ((gcx - acx) / aw - means[0]) / stds[0]
        t1 = ((gcy - acy) / ah - means[1]) / stds[1]
        t2 = (jnp.log(jnp.maximum(gw, 1e-12) / aw) - means[2]) / stds[2]
        t3 = (jnp.log(jnp.maximum(gh, 1e-12) / ah) - means[3]) / stds[3]
        t = jnp.stack([t0, t1, t2, t3], axis=-1)
        m = (sample > 0.5).astype(anc.dtype)[:, None]
        return t * m, jnp.broadcast_to(m, t.shape)

    return jax.vmap(one)(samples, matches, anchors, refs)


@register("box_decode", aliases=("_contrib_box_decode",))
def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):
    """Decode regression deltas back to corner boxes (reference:
    ``bounding_box.cc`` ``_contrib_box_decode``). data (B,N,4) deltas,
    anchors (1,N,4) or (B,N,4) in ``format`` ('corner'|'center')."""
    a = jnp.asarray(anchors, data.dtype)
    if format == "corner":
        acx, acy, aw, ah = _corner_to_center(a)
    else:
        acx, acy, aw, ah = (a[..., 0], a[..., 1], a[..., 2], a[..., 3])
    dx = data[..., 0] * std0
    dy = data[..., 1] * std1
    dw = data[..., 2] * std2
    dh = data[..., 3] * std3
    if clip is not None and clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    cx = dx * aw + acx
    cy = dy * ah + acy
    w = jnp.exp(dw) * aw
    h = jnp.exp(dh) * ah
    out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                    axis=-1)
    return jnp.broadcast_to(out, data.shape[:-1] + (4,))


@register("bipartite_matching", aliases=("_contrib_bipartite_matching",))
def bipartite_matching(data, is_ascend=False, threshold=0.5, topk=-1):
    """Greedy bipartite matching over a (B,N,M) score matrix (reference:
    ``bounding_box.cc`` ``_contrib_bipartite_matching``): repeatedly take
    the globally best unmatched (row, col) pair until scores cross
    ``threshold`` (or ``topk`` pairs matched). Returns (row_match (B,N)
    col index or -1, col_match (B,M) row index or -1).

    TPU-first: a fixed min(N,M)-trip ``fori_loop`` over an argmax of the
    masked matrix — no host loop, static shapes throughout.
    """
    b, n, m = data.shape
    trips = min(n, m) if topk is None or topk <= 0 else min(topk, n, m)
    sign = -1.0 if is_ascend else 1.0
    neg = -jnp.inf

    def one(mat):
        score = sign * mat.astype(jnp.float32)
        thr = sign * jnp.float32(threshold)

        def body(_, carry):
            s, rowm, colm = carry
            flat = jnp.argmax(s)
            i, j = flat // m, flat % m
            best = s[i, j]
            ok = best >= thr
            rowm = jnp.where(ok, rowm.at[i].set(j.astype(jnp.float32)), rowm)
            colm = jnp.where(ok, colm.at[j].set(i.astype(jnp.float32)), colm)
            s = jnp.where(ok, s.at[i, :].set(neg).at[:, j].set(neg), s)
            return s, rowm, colm

        rowm = jnp.full((n,), -1.0, jnp.float32)
        colm = jnp.full((m,), -1.0, jnp.float32)
        _, rowm, colm = lax.fori_loop(0, trips, body, (score, rowm, colm))
        return rowm, colm

    rows, cols = jax.vmap(one)(data)
    return rows.astype(data.dtype), cols.astype(data.dtype)
