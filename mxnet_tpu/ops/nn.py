"""Neural-network ops: the MXU-facing surface.

Reference surface: ``src/operator/nn/`` (symbols ``Convolution``,
``FullyConnected``, ``BatchNorm``, ``Pooling``, ``Activation``,
``Dropout``, ``LayerNorm`` ...). TPU-native notes:

- Conv/FC lower to ``lax.conv_general_dilated`` / ``lax.dot_general`` —
  XLA tiles these onto the MXU; there is no cuDNN algo selection to port.
- BatchNorm is pure: training mode returns (out, new_moving_mean,
  new_moving_var); the Gluon layer writes the stats back into its aux
  parameters (works eagerly and under CachedOp functionalized tracing).
- Dropout draws from :mod:`mxnet_tpu.ndarray.random`'s key stream so it is
  reproducible under ``mx.random.seed`` and traceable under hybridize.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


@register("FullyConnected", aliases=("fully_connected",))
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    x = data.reshape((data.shape[0], -1)) if flatten else data
    y = jnp.matmul(x, weight.T) if x.ndim == 2 else jnp.einsum("...i,oi->...o", x, weight)
    if bias is not None and not no_bias:
        y = y + bias
    return y


_CONV_DN = {
    1: ("NCW", "OIW", "NCW"),
    2: ("NCHW", "OIHW", "NCHW"),
    3: ("NCDHW", "OIDHW", "NCDHW"),
}


@register("Convolution", aliases=("convolution",))
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                num_filter=0, num_group=1, no_bias=False, layout=None,
                workspace=0, cudnn_tune=None, cudnn_off=False):
    nd = len(kernel)
    stride = stride or (1,) * nd
    dilate = dilate or (1,) * nd
    pad = pad or (0,) * nd
    channels_last = bool(layout) and layout.endswith("C")
    if channels_last:
        # weights stay OIHW (the param layout never changes — only the
        # activation layout; used by the TPU fused-conv-BN pipeline)
        spec = (layout, "OI" + layout[1:-1], layout)
        dn = lax.conv_dimension_numbers(data.shape, weight.shape, spec)
    else:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_DN[nd])
    y = lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if bias is not None and not no_bias:
        shape = (1,) + (1,) * nd + (-1,) if channels_last \
            else (1, -1) + (1,) * nd
        y = y + bias.reshape(shape)
    return y


@register("Deconvolution", aliases=("deconvolution",))
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), target_shape=(), num_filter=0, num_group=1,
                  no_bias=True, layout=None, workspace=0, cudnn_tune=None,
                  cudnn_off=False):
    nd = len(kernel)
    stride = stride or (1,) * nd
    dilate = dilate or (1,) * nd
    pad = pad or (0,) * nd
    adj = adj or (0,) * nd
    # transposed conv == gradient of conv wrt input: use conv_general_dilated
    # with lhs_dilation=stride and flipped spatial padding.
    pads = []
    for i in range(nd):
        k = (kernel[i] - 1) * dilate[i]
        pads.append((k - pad[i], k - pad[i] + adj[i]))
    if num_group > 1:
        # weight layout (Cin, Cout/g, *k): split into groups
        xs = jnp.split(data, num_group, axis=1)
        ws = jnp.split(weight, num_group, axis=0)
        outs = [_deconv_one(x, w, stride, dilate, pads, nd) for x, w in zip(xs, ws)]
        y = jnp.concatenate(outs, axis=1)
    else:
        y = _deconv_one(data, weight, stride, dilate, pads, nd)
    if bias is not None and not no_bias:
        y = y + bias.reshape((1, -1) + (1,) * nd)
    return y


def _deconv_one(data, weight, stride, dilate, pads, nd):
    # weight (Cin, Cout, *k) -> conv kernel (Cout, Cin, *k) flipped
    w = jnp.swapaxes(weight, 0, 1)
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    dn = lax.conv_dimension_numbers(data.shape, w.shape, _CONV_DN[nd])
    return lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
    )


@register("Pooling", aliases=("pooling",))
def pooling(data, kernel=(), pool_type="max", global_pool=False, stride=(),
            pad=(), pooling_convention="valid", count_include_pad=True,
            cudnn_off=False, p_value=2, layout=None):
    nd = data.ndim - 2
    channels_last = bool(layout) and layout.endswith("C")
    sp0 = 1 if channels_last else 2  # first spatial axis
    if global_pool:
        kernel = data.shape[sp0:sp0 + nd]
        stride = (1,) * nd
        pad = (0,) * nd
    stride = stride or (1,) * nd
    pad = pad or (0,) * nd

    def _expand(sp, fill):
        sp = tuple(sp)
        return (fill,) + sp + (fill,) if channels_last else (fill, fill) + sp

    window = _expand(kernel, 1)
    strides = _expand(stride, 1)
    pads = _expand([(p, p) for p in pad], (0, 0))
    if pooling_convention == "full":
        # ceil-mode: add extra right-padding so the last window fits
        extra = []
        for i in range(nd):
            size = data.shape[sp0 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            extra.append((stride[i] - rem) % stride[i] if size > kernel[i] else 0)
        pads = _expand([(pad[i], pad[i] + extra[i]) for i in range(nd)],
                       (0, 0))
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return s / denom
        ones = jnp.ones_like(data)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / cnt
    if pool_type == "lp":
        s = lax.reduce_window(jnp.abs(data) ** p_value, 0.0, lax.add, window, strides, pads)
        return s ** (1.0 / p_value)
    raise ValueError(f"unknown pool_type {pool_type}")


@register("Activation", aliases=("activation",))
def activation(data, act_type="relu"):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise ValueError(f"unknown act_type {act_type}")


@register("LeakyReLU")
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 and data.ndim > 2 else gamma
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":  # eval mode: mean slope
        return jnp.where(data >= 0, data, (lower_bound + upper_bound) / 2 * data)
    raise ValueError(f"unknown act_type {act_type}")


@register("softmax", aliases=("Softmax", "SoftmaxActivation"))
def softmax(data, axis=-1, temperature=None, length=None, use_length=False,
            dtype=None):
    x = data / temperature if temperature not in (None, 1.0) else data
    if use_length and length is not None:
        steps = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        mask = steps.reshape(shape) < jnp.expand_dims(length, axis)
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None, dtype=None):
    x = data / temperature if temperature not in (None, 1.0) else data
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def softmin(data, axis=-1, temperature=None, dtype=None):
    return softmax(-data, axis=axis, temperature=temperature)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    lsm = jax.nn.log_softmax(data, axis=-1)
    oh = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1], dtype=data.dtype)
    return -jnp.sum(lsm * oh)


@register("SoftmaxOutput", aliases=("softmax_output",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    """Legacy Module-era loss layer: forward = softmax; the CE gradient is
    injected via custom VJP (reference: ``softmax_output-inl.h``)."""
    return _softmax_output_vjp(data, label, grad_scale, ignore_label, use_ignore,
                               normalization)


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _softmax_output_vjp(data, label, grad_scale, ignore_label, use_ignore, norm):
    return jax.nn.softmax(data, axis=-1)


def _so_fwd(data, label, grad_scale, ignore_label, use_ignore, norm):
    p = jax.nn.softmax(data, axis=-1)
    return p, (p, label)


def _so_bwd(grad_scale, ignore_label, use_ignore, norm, res, g):
    p, label = res
    oh = jax.nn.one_hot(label.astype(jnp.int32), p.shape[-1], dtype=p.dtype)
    grad = p - oh
    if use_ignore:
        keep = (label != ignore_label).astype(p.dtype)
        grad = grad * keep[..., None]
    if norm == "batch":
        grad = grad / p.shape[0]
    elif norm == "valid" and use_ignore:
        keep = (label != ignore_label).astype(p.dtype)
        grad = grad / jnp.maximum(jnp.sum(keep), 1.0)
    return (grad * grad_scale, jnp.zeros_like(label))


_softmax_output_vjp.defvjp(_so_fwd, _so_bwd)


def _f32_moments(data, axes, keepdims=False):
    """One-pass mean/variance with f32 (or wider) accumulation: E[x] and
    E[x^2] fuse into a SINGLE read of the input where jnp.var's two-pass
    form re-reads it (measured on v5e: -5ms/step on ResNet-50 bs128,
    +2% BERT step). Trade-off: E[x^2]-E[x]^2 can cancel when
    |mean| >> std; the clamp floors it at 0 (same form and rationale as
    flax's norm layers). Stats stay in the accumulation dtype — cast at
    the use site."""
    acc = jnp.promote_types(data.dtype, jnp.float32)
    xf = data.astype(acc)
    mean = jnp.mean(xf, axis=axes, keepdims=keepdims)
    var = jnp.maximum(jnp.mean(xf * xf, axis=axes, keepdims=keepdims)
                      - mean * mean, 0.0)
    return mean, var


@register("BatchNorm", aliases=("batch_norm",))
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False, training=False):
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    if training and not use_global_stats:
        red = tuple(i for i in range(data.ndim) if i != axis)
        mean, var = _f32_moments(data, red)  # one read of the conv output
        # running stats keep their storage dtype (f32 moments must not
        # silently promote e.g. float16 aux arrays across a step)
        new_mean = (momentum * moving_mean + (1 - momentum) * mean) \
            .astype(moving_mean.dtype)
        new_var = (momentum * moving_var + (1 - momentum) * var) \
            .astype(moving_var.dtype)
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    inv = lax.rsqrt(var + eps).astype(data.dtype)
    out = (data - mean.reshape(shape).astype(data.dtype)) * inv.reshape(shape) \
        * g.reshape(shape).astype(data.dtype) + beta.reshape(shape).astype(data.dtype)
    if training and not use_global_stats:
        return out, new_mean, new_var
    if output_mean_var:
        return out, mean, var
    return out


@register("LayerNorm", aliases=("layer_norm",))
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    mean, var = _f32_moments(data, axis, keepdims=True)
    inv = lax.rsqrt(var + eps).astype(data.dtype)  # rsqrt in f32
    out = (data - mean.astype(data.dtype)) * inv
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = out * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(mean.astype(data.dtype), axis), \
            jnp.squeeze(var.astype(data.dtype), axis)
    return out


@register("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean, var = _f32_moments(data, red, keepdims=True)
    out = (data - mean.astype(data.dtype)) \
        * lax.rsqrt(var + eps).astype(data.dtype)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(shape) + beta.reshape(shape)


@register("GroupNorm")
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    n, c = data.shape[0], data.shape[1]
    rest = data.shape[2:]
    x = data.reshape((n, num_groups, c // num_groups) + rest)
    red = tuple(range(2, x.ndim))
    mean, var = _f32_moments(x, red, keepdims=True)
    x = (x - mean.astype(x.dtype)) * lax.rsqrt(var + eps).astype(x.dtype)
    x = x.reshape(data.shape)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(shape) + beta.reshape(shape)


@register("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    elif mode == "channel":
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=1, keepdims=True) + eps)
    else:  # spatial
        red = tuple(range(2, data.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    return data / n


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(padded[:, i:i + data.shape[1]] for i in range(nsize))
    return data / jnp.power(knorm + alpha / nsize * acc, beta)


@register("Dropout", aliases=("dropout",))
def dropout_op(data, key, p=0.5, mode="training", axes=(), cudnn_off=False):
    if p <= 0.0:
        return data
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


@register("identity_with_attr_like_rhs")
def identity_with_attr_like_rhs(lhs, rhs):
    return lhs


@register("RNN")
def rnn_fused(data, params, state, state_cell=None, key=None, state_size=0,
              num_layers=1, mode="lstm", bidirectional=False, p=0.0,
              state_outputs=True, projection_size=None,
              lstm_state_clip_min=None, lstm_state_clip_max=None,
              lstm_state_clip_nan=False, use_sequence_length=False):
    """Fused multi-layer RNN (reference: ``src/operator/rnn.cc``).

    TPU-native: each layer is a ``lax.scan`` over time; weights are sliced
    out of the flat ``params`` vector using cuDNN's canonical packing order
    (the order the reference uses, so zoo checkpoints load unchanged).
    Layout: seq-major ``data (T, N, C)``, ``state (L*D, N, H)``.
    """
    T, N, C = data.shape
    H = state_size
    D = 2 if bidirectional else 1
    ngates = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}[mode]

    offset = 0

    def take_mat(rows, cols):
        nonlocal offset
        w = lax.dynamic_slice(params, (offset,), (rows * cols,)).reshape(rows, cols)
        offset += rows * cols
        return w

    # collect per-layer weights (cuDNN order: all Wx, Wh per layer/direction
    # first, then all biases)
    layer_w = []
    for layer in range(num_layers):
        for d in range(D):
            in_c = C if layer == 0 else H * D
            wx = take_mat(ngates * H, in_c)
            wh = take_mat(ngates * H, H)
            layer_w.append((wx, wh))
    layer_b = []
    for layer in range(num_layers):
        for d in range(D):
            bx = lax.dynamic_slice(params, (offset,), (ngates * H,))
            offset += ngates * H
            bh = lax.dynamic_slice(params, (offset,), (ngates * H,))
            offset += ngates * H
            layer_b.append((bx, bh))

    def cell_step(mode):
        def lstm(carry, xw, wh, bh):
            h, c = carry
            gates = xw + jnp.matmul(h, wh.T) + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c2 = f * c + i * g
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2

        def gru(carry, xw, wh, bh):
            (h,) = carry
            xr, xz, xn = jnp.split(xw, 3, axis=-1)
            hr, hz, hn = jnp.split(jnp.matmul(h, wh.T) + bh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h2 = (1 - z) * n + z * h
            return (h2,), h2

        def vanilla(carry, xw, wh, bh, act):
            (h,) = carry
            h2 = act(xw + jnp.matmul(h, wh.T) + bh)
            return (h2,), h2

        if mode == "lstm":
            return lstm
        if mode == "gru":
            return gru
        if mode == "rnn_tanh":
            return lambda c, xw, wh, bh: vanilla(c, xw, wh, bh, jnp.tanh)
        return lambda c, xw, wh, bh: vanilla(c, xw, wh, bh, lambda v: jnp.maximum(v, 0))

    step = cell_step(mode)
    x = data
    h_states, c_states = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(D):
            li = layer * D + d
            wx, wh = layer_w[li]
            bx, bh = layer_b[li]
            h0 = state[li]
            carry = (h0, state_cell[li]) if mode == "lstm" else (h0,)
            seq = x if d == 0 else jnp.flip(x, axis=0)
            xw = jnp.einsum("tnc,gc->tng", seq, wx) + bx

            def scan_fn(carry, xw_t, wh=wh, bh=bh):
                return step(carry, xw_t, wh, bh)

            carry, ys = lax.scan(scan_fn, carry, xw)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            outs.append(ys)
            h_states.append(carry[0])
            if mode == "lstm":
                c_states.append(carry[1])
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and layer < num_layers - 1 and key is not None:
            sub = jax.random.fold_in(key, layer)
            mask = jax.random.bernoulli(sub, 1 - p, x.shape).astype(x.dtype)
            x = x * mask / (1 - p)
    hN = jnp.stack(h_states, axis=0)
    if mode == "lstm":
        return x, hN, jnp.stack(c_states, axis=0)
    return x, hN
