"""Shape-manipulation and indexing ops.

Reference surface: ``src/operator/tensor/matrix_op*`` (reshape/transpose/
slice/concat/...), ``indexing_op*`` (take/one_hot/gather_nd/Embedding).
MXNet reshape magic codes (0, -1, -2, -3, -4) are implemented in full.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _infer_reshape(src_shape, target):
    """MXNet reshape special values (reference: matrix_op ``ReshapeParam``):
    0 copy input dim; -1 infer; -2 copy all remaining; -3 merge next two
    input dims; -4 split an input dim by the following two target values."""
    out = []
    src = list(src_shape)
    i = 0  # index into src
    t = 0
    target = list(target)
    while t < len(target):
        d = target[t]
        if d == 0:
            out.append(src[i])
            i += 1
        elif d == -1:
            out.append(-1)
            i += 1
        elif d == -2:
            out.extend(src[i:])
            i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1])
            i += 2
        elif d == -4:
            d1, d2 = target[t + 1], target[t + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2])
            t += 2
            i += 1
        else:
            out.append(d)
            i += 1
        t += 1
    # resolve a single -1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in src_shape:
            total *= d
        out[out.index(-1)] = total // known if known else 0
    return tuple(out)


@register("reshape", aliases=("Reshape",))
def reshape(data, shape=None, reverse=False):
    shape = tuple(shape)
    if reverse:
        rs = _infer_reshape(data.shape[::-1], tuple(reversed(shape)))
        return jnp.reshape(data, rs[::-1])
    return jnp.reshape(data, _infer_reshape(data.shape, shape))


@register("reshape_like")
def reshape_like(lhs, rhs):
    return jnp.reshape(lhs, rhs.shape)


@register("flatten", aliases=("Flatten",))
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose")
def transpose(data, axes=None):
    return jnp.transpose(data, axes if axes else None)


@register("swapaxes", aliases=("SwapAxis",))
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register("expand_dims")
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis=axis)


@register("concat", aliases=("Concat",))
def concat(*args, dim=1):
    return jnp.concatenate(args, axis=dim)


@register("stack")
def stack(*args, axis=0):
    return jnp.stack(args, axis=axis)


@register("split", aliases=("SliceChannel",))
def split(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("split_v2")
def split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0):
    if sections:
        parts = jnp.split(data, sections, axis=axis)
    else:
        parts = jnp.split(data, list(indices), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register("slice", aliases=("crop",))
def slice_op(data, begin=(), end=(), step=()):
    idx = []
    for i in range(len(begin)):
        st = step[i] if step and i < len(step) and step[i] is not None else 1
        idx.append(slice(begin[i], end[i], st))
    return data[tuple(idx)]


@register("_slice_basic")
def _slice_basic(data, index=None):
    from ..ndarray.ndarray import _thaw_index

    return data[_thaw_index(index)]


@register("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like")
def slice_like(data, shape_like, axes=()):
    axes = axes or range(data.ndim)
    idx = [slice(None)] * data.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("take")
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    n = a.shape[axis]
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    else:
        idx = jnp.clip(idx, 0, n - 1)
    return jnp.take(a, idx, axis=axis)


@register("pick")
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    r = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    return r if keepdims else jnp.squeeze(r, axis=axis)


@register("Embedding", aliases=("embedding",))
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    idx = jnp.clip(data.astype(jnp.int32), 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


@register("one_hot")
def one_hot(indices, depth=None, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * (on_value - off_value) + off_value


@register("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd")
def scatter_nd(data, indices, shape=None):
    idx = tuple(indices.astype(jnp.int32))
    out = jnp.zeros(shape, data.dtype)
    return out.at[idx].set(data)


@register("where")
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("tile")
def tile(data, reps=()):
    return jnp.tile(data, reps)


@register("repeat")
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("pad", aliases=("Pad",))
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(data, pw, constant_values=constant_value)
    return jnp.pad(data, pw, mode="edge" if mode == "edge" else "reflect")


@register("flip", aliases=("reverse",))
def flip(data, axis=()):
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.flip(data, axis=axis)


@register("broadcast_to")
def broadcast_to(data, shape=()):
    tgt = tuple(s if s != 0 else d for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like")
def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    tgt = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        tgt[la] = rhs.shape[ra]
    return jnp.broadcast_to(lhs, tuple(tgt))


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=(), size=()):
    if isinstance(axis, int):
        axis, size = (axis,), (size,)
    tgt = list(data.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register("full_like")
def full_like(data, fill_value=0.0):
    return jnp.full_like(data, fill_value)


@register("shape_array")
def shape_array(data):
    return jnp.asarray(data.shape, dtype=jnp.int64 if False else jnp.int32)


@register("size_array")
def size_array(data):
    s = 1
    for d in data.shape:
        s *= d
    return jnp.asarray([s], dtype=jnp.int32)


@register("diag")
def diag(data, k=0):
    if data.ndim == 1:
        return jnp.diag(data, k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@register("identity", aliases=("_copy", "copy"))
def identity(data):
    return data + 0  # new buffer, same values


@register("stop_gradient", aliases=("BlockGrad", "make_loss", "MakeLoss"))
def stop_gradient(data):
    return jax.lax.stop_gradient(data)


@register("boolean_mask", aliases=("_contrib_boolean_mask",))
def boolean_mask(data, index, axis=0):
    # dynamic-shape op: TPU-native contract returns padded data + valid count
    # is handled at contrib level; eager path materializes on host semantics
    mask = index.astype(bool)
    return jnp.compress(mask, data, axis=axis)


@register("sequence_mask", aliases=("SequenceMask",))
def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0,
                  axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    mask = steps[:, None] < sequence_length[None, :]  # (T, B)
    if axis == 1:
        mask = mask.T
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    batch_axis = 1 - axis
    shape[batch_axis] = data.shape[batch_axis]
    mask = mask.reshape(shape)
    return jnp.where(mask, data, value)


@register("SequenceLast")
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    moved = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    return jnp.take_along_axis(
        moved, last.reshape((1, -1) + (1,) * (moved.ndim - 2)), axis=0
    )[0]


@register("SequenceReverse")
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    moved = jnp.moveaxis(data, axis, 0)
    T = moved.shape[0]
    steps = jnp.arange(T)[:, None]
    L = sequence_length.astype(jnp.int32)[None, :]
    src = jnp.where(steps < L, L - 1 - steps, steps)
    rev = jnp.take_along_axis(moved, src.reshape(src.shape + (1,) * (moved.ndim - 2)), axis=0)
    return jnp.moveaxis(rev, 0, axis)
