"""CTC loss (reference: ``src/operator/contrib/ctc_loss-inl.h``).

TPU-native: log-space forward (alpha) recursion as a ``lax.scan`` over time;
gradients come from JAX autodiff of the scan instead of the reference's
hand-written beta recursion. Blank label = 0 (the reference default).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_NEG_INF = -1e30


def _interleave_blanks(labels):
    """(N, L) -> (N, 2L+1) label sequence with blanks (0) interleaved."""
    n, L = labels.shape
    ext = jnp.full((n, 2 * L + 1), 0, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    return ext


@register("_ctc_loss", aliases=("ctc_loss", "CTCLoss"))
def ctc_loss(pred, label, pred_lengths=None, label_lengths=None):
    """pred: (T, N, C) raw activations; label: (N, L) int32, 0 = blank padding.

    Returns per-example negative log likelihood, shape (N,).
    """
    T, N, C = pred.shape
    logp = jax.nn.log_softmax(pred, axis=-1)
    if label_lengths is None:
        # labels padded with 0 (blank): length = count of non-zero entries
        label_len = jnp.sum((label != 0).astype(jnp.int32), axis=1)
    else:
        label_len = label_lengths.astype(jnp.int32)
    if pred_lengths is None:
        pred_len = jnp.full((N,), T, dtype=jnp.int32)
    else:
        pred_len = pred_lengths.astype(jnp.int32)

    ext = _interleave_blanks(label.astype(jnp.int32))  # (N, S) S = 2L+1
    S = ext.shape[1]
    ext_len = 2 * label_len + 1

    # allow-transition mask: alpha[s] can come from s, s-1, and s-2 when
    # ext[s] != blank and ext[s] != ext[s-2]
    same_as_two_back = jnp.concatenate(
        [jnp.zeros((N, 2), dtype=bool), ext[:, 2:] == ext[:, :-2]], axis=1
    )
    can_skip = (ext != 0) & (~same_as_two_back)

    # initial alpha: positions 0 (blank) and 1 (first label)
    init = jnp.full((N, S), _NEG_INF)
    init = init.at[:, 0].set(logp[0, jnp.arange(N), ext[:, 0]])
    init = init.at[:, 1].set(
        jnp.where(S > 1, logp[0, jnp.arange(N), ext[:, 1]], _NEG_INF)
    )

    def step(alpha, t):
        prev1 = jnp.concatenate([jnp.full((N, 1), _NEG_INF), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate([jnp.full((N, 2), _NEG_INF), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(can_skip, prev2, _NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
        emit = jnp.take_along_axis(logp[t], ext, axis=1)
        new_alpha = merged + emit
        # freeze once past this example's input length
        new_alpha = jnp.where((t < pred_len)[:, None], new_alpha, alpha)
        return new_alpha, None

    alpha, _ = lax.scan(step, init, jnp.arange(1, T))

    idx = jnp.arange(N)
    last = jnp.take_along_axis(alpha, (ext_len - 1)[:, None], axis=1)[:, 0]
    second_last = jnp.take_along_axis(
        alpha, jnp.maximum(ext_len - 2, 0)[:, None], axis=1
    )[:, 0]
    ll = jnp.logaddexp(last, second_last)
    return -ll
