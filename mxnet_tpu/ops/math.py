"""Elementwise, broadcast, comparison and reduction ops.

Reference surface: ``src/operator/tensor/elemwise_*`` ,
``broadcast_reduce_op_*`` (symbols ``broadcast_add``, ``sum``, ``norm`` ...).
All are thin MXNet-semantics shims over jnp/lax; XLA fuses chains of these
into single kernels (the reference needed an RTC pointwise-fusion pass for
that — SURVEY.md §2.1 'Pointwise fusion' — here it is free).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register

# --------------------------------------------------------------------------
# binary broadcast (MXNet: broadcast_* family; dispatch also routes
# elemwise_add/_plus_scalar etc. here — jnp broadcasting is a superset)
# --------------------------------------------------------------------------

_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "broadcast_logical_and": lambda a, b: (jnp.logical_and(a, b)).astype(jnp.result_type(a, b)),
    "broadcast_logical_or": lambda a, b: (jnp.logical_or(a, b)).astype(jnp.result_type(a, b)),
    "broadcast_logical_xor": lambda a, b: (jnp.logical_xor(a, b)).astype(jnp.result_type(a, b)),
    "arctan2": jnp.arctan2,
}

_BINARY_ALIASES = {
    "broadcast_add": ("elemwise_add", "add", "_plus", "_add"),
    "broadcast_sub": ("elemwise_sub", "subtract", "_minus", "_sub"),
    "broadcast_mul": ("elemwise_mul", "multiply", "_mul"),
    "broadcast_div": ("elemwise_div", "divide", "_div"),
    "broadcast_mod": ("_mod",),
    "broadcast_power": ("_power", "pow"),
    "broadcast_maximum": ("maximum", "_maximum", "broadcast_max"),
    "broadcast_minimum": ("minimum", "_minimum", "broadcast_min"),
}

for _name, _fn in _BINARY.items():

    def _mk(fn):
        def op(lhs, rhs):
            return fn(lhs, rhs)

        return op

    register(_name, aliases=_BINARY_ALIASES.get(_name, ()))(_mk(_fn))

_COMPARE = {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
}

for _name, _fn in _COMPARE.items():

    def _mkc(fn):
        def op(lhs, rhs):
            # MXNet comparisons return the input float dtype (1.0/0.0)
            return fn(lhs, rhs).astype(
                jnp.result_type(lhs, rhs)
                if jnp.issubdtype(jnp.result_type(lhs, rhs), jnp.floating)
                else jnp.float32
            )

        return op

    register(_name, aliases=(_name.replace("broadcast_", ""),))(_mkc(_fn))


# --------------------------------------------------------------------------
# unary
# --------------------------------------------------------------------------

import jax.scipy.special as jsp

_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "round": jnp.round,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "sigmoid": lambda x: jax_sigmoid(x),
    "softsign": lambda x: x / (1.0 + jnp.abs(x)),
    "relu": lambda x: jnp.maximum(x, 0),
    "gamma": lambda x: jnp.exp(jsp.gammaln(x)),
    "gammaln": jsp.gammaln,
    "erf": jsp.erf,
    "erfinv": jsp.erfinv,
    "reciprocal": jnp.reciprocal,
    "negative": jnp.negative,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32),
    "isnan": lambda x: jnp.isnan(x).astype(jnp.float32),
    "isinf": lambda x: jnp.isinf(x).astype(jnp.float32),
    "isfinite": lambda x: jnp.isfinite(x).astype(jnp.float32),
}


def jax_sigmoid(x):
    return jax.nn.sigmoid(x)


import jax

for _name, _fn in _UNARY.items():

    def _mku(fn):
        def op(data):
            return fn(data)

        return op

    register(_name)(_mku(_fn))


@register("clip")
def clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


@register("cast", aliases=("Cast", "astype"))
def cast(data, dtype="float32"):
    return data.astype(jnp.dtype(dtype))


@register("smooth_l1")
def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(
        jnp.abs(data) < 1.0 / s2, 0.5 * s2 * data * data, jnp.abs(data) - 0.5 / s2
    )


# --------------------------------------------------------------------------
# reductions (MXNet axis semantics: axis=None → all, `exclude` inverts)
# --------------------------------------------------------------------------


def _axes(axis, exclude, ndim):
    if axis is None or axis == ():
        ax = tuple(range(ndim))
        return tuple(set(range(ndim)) - set(ax)) if exclude else ax
    if isinstance(axis, int):
        axis = (axis,)
    ax = tuple(a % ndim for a in axis)
    if exclude:
        ax = tuple(i for i in range(ndim) if i not in ax)
    return ax


def _mkreduce(jfn):
    def op(data, axis=None, keepdims=False, exclude=False):
        return jfn(data, axis=_axes(axis, exclude, data.ndim), keepdims=keepdims)

    return op


for _name, _jfn, _aliases in (
    ("sum", jnp.sum, ("sum_axis",)),
    ("nansum", jnp.nansum, ()),
    ("mean", jnp.mean, ()),
    ("prod", jnp.prod, ()),
    ("nanprod", jnp.nanprod, ()),
    ("max", jnp.max, ("max_axis",)),
    ("min", jnp.min, ("min_axis",)),
):
    register(_name, aliases=_aliases)(_mkreduce(_jfn))


@register("norm")
def norm(data, ord=2, axis=None, keepdims=False):
    ax = axis if axis is not None else tuple(range(data.ndim))
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims))


@register("argmax")
def argmax(data, axis=None, keepdims=False):
    r = jnp.argmax(data, axis=axis, keepdims=keepdims).astype(jnp.float32)
    return r


@register("argmin")
def argmin(data, axis=None, keepdims=False):
    return jnp.argmin(data, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register("argmax_channel")
def argmax_channel(data):
    return jnp.argmax(data, axis=1).astype(jnp.float32)


@register("topk")
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    x = data if not is_ascend else -data
    x = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(x, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return (vals, idx.astype(jnp.dtype(dtype)))
    if ret_typ == "mask":
        m = jnp.zeros_like(jnp.moveaxis(data, axis, -1))
        m = m.at[..., :].set(0)
        oh = jnp.sum(jax.nn.one_hot(jnp.moveaxis(idx, axis, -1), data.shape[axis]), axis=-2)
        return jnp.moveaxis(oh, -1, axis).astype(data.dtype)
    return idx.astype(jnp.dtype(dtype))


@register("sort")
def sort(data, axis=-1, is_ascend=True):
    r = jnp.sort(data, axis=axis)
    return r if is_ascend else jnp.flip(r, axis=axis)


@register("argsort")
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    r = jnp.argsort(data, axis=axis, stable=True)
    if not is_ascend:
        r = jnp.flip(r, axis=axis)
    return r.astype(jnp.dtype(dtype))


@register("cumsum")
def cumsum(a, axis=None, dtype=None):
    return jnp.cumsum(a, axis=axis, dtype=dtype)


# --------------------------------------------------------------------------
# linalg-ish (reference: src/operator/tensor/dot*, la_op)
# --------------------------------------------------------------------------


@register("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    # MXNet dot: contract last axis of a with first axis of b;
    # transpose flags reverse ALL axes of the operand (reference doc).
    a = jnp.transpose(lhs) if transpose_a else lhs
    b = jnp.transpose(rhs) if transpose_b else rhs
    return jnp.tensordot(a, b, axes=1)


@register("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("matmul")
def matmul(a, b):
    return jnp.matmul(a, b)


@register("khatri_rao")
def khatri_rao(*args):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(-1, out.shape[-1])
    return out


@register("linalg_gemm2")
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_gemm")
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("linalg_potrf")
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register("linalg_syrk")
def linalg_syrk(A, transpose=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))
